"""FleetRouter: health/load-aware front door with prefix affinity.

The cluster-tier dispatch over N replica handles, composing three
placement and two failure rules:

- **least-loaded among ready** — replicas are scraped (``/readyz`` +
  the merged ``load`` sub-dict) at most every ``poll_interval_s``;
  a scrape older than ``stale_after_s`` disqualifies its replica (a
  silent process is indistinguishable from a dead one). Among ready
  replicas the lowest ``(queue_depth, occupancy)`` wins.
- **prefix affinity (rendezvous)** — the prompt's full-block prefix is
  chain-hashed with the SAME ``prefix_block_hashes`` the paged server's
  prefix cache keys on, so "routes to the same replica" and "hits that
  replica's prefix cache" are literally the same address space. The
  hash picks its home replica by rendezvous (highest-random-weight)
  hashing over the CURRENT ready set: replicas joining/leaving remap
  only their own share of keys, no ring state to persist.
- **load-aware spill** — an affinity home past ``spill_queue_depth`` or
  ``spill_occupancy`` forfeits the request to the least-loaded replica:
  a hot prefix cache is worth one queue slot of patience, not a
  convoy.
- **retry on shed/death** — a typed
  :class:`~deeplearning4j_tpu.serving.resilience.RetryableServingError`
  is retried up to ``retry_budget`` times, sleeping the error's own
  ``retry_after_s`` hint (bounded by ``max_backoff_s``); a replica
  whose submit/result raises ``ServerClosedError`` (or whose worker
  crashed it into a ``ServingError``) is marked dead and the request
  moves on immediately. Budget exhausted → the last typed shed
  re-raises as-is (the caller inherits the backoff hint).
- **never retried** — permanent ``ValueError`` (bad request),
  ``PoisonedRequestError`` (the request IS the fault — it would poison
  the next replica too), and deadline misses (``RequestTimeoutError``:
  the SLO is already blown; retrying manufactures load, not answers).

See docs/serving.md ("Fleet") for the full semantics table.
"""
from __future__ import annotations

import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.serving.fleet.metrics import FleetMetrics
from deeplearning4j_tpu.serving.fleet.replica import FleetReplica, ReplicaLoad
from deeplearning4j_tpu.serving.paged.pool import prefix_block_hashes
from deeplearning4j_tpu.serving.queue import (RequestTimeoutError,
                                              ServerClosedError,
                                              ServingError)
from deeplearning4j_tpu.serving.resilience import (PoisonedRequestError,
                                                   RetryableServingError)


class FleetUnavailableError(RetryableServingError):
    """No ready replica can take the request right now (all draining,
    dead, stale, or shedding). Typed retryable — carries the router's
    suggested re-poll interval as ``retry_after_s``."""


@dataclass
class FleetResult:
    """One completed front-door generation, tagged with where and how
    hard it was to place (what the fleet load generator logs per row)."""

    tokens: List[int]
    replica: str
    retries: int = 0
    routed: str = "least_loaded"        # affinity | spill | least_loaded
    ttft_ms: Optional[float] = None
    intertoken_ms: List[float] = field(default_factory=list)


class FleetRouter:
    """Front door over :class:`FleetReplica` handles.

    ``affinity_blocks`` bounds how much of the prompt feeds the
    affinity key (default 1: the first full block — shared system
    prompts land together while long distinct tails still spread).
    ``sleep``/``clock`` are injectable for deterministic tests.
    """

    def __init__(self, replicas=(), *, block_size: Optional[int] = None,
                 affinity: bool = True, affinity_blocks: int = 1,
                 retry_budget: int = 3, max_backoff_s: float = 1.0,
                 stale_after_s: float = 5.0, poll_interval_s: float = 0.25,
                 spill_queue_depth: int = 4, spill_occupancy: float = 0.9,
                 metrics: Optional[FleetMetrics] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.RLock()
        self.replicas: Dict[str, FleetReplica] = {}
        self.affinity = bool(affinity)
        self.affinity_blocks = int(affinity_blocks)
        self.retry_budget = int(retry_budget)
        self.max_backoff_s = float(max_backoff_s)
        self.stale_after_s = float(stale_after_s)
        self.poll_interval_s = float(poll_interval_s)
        self.spill_queue_depth = int(spill_queue_depth)
        self.spill_occupancy = float(spill_occupancy)
        self.metrics = metrics if metrics is not None else FleetMetrics()
        self._sleep = sleep
        self._clock = clock
        self._block_size = block_size
        self._last_poll = float("-inf")
        self._loads: Dict[str, ReplicaLoad] = {}
        for r in replicas:
            self.add_replica(r)

    # -- membership -----------------------------------------------------
    def add_replica(self, replica: FleetReplica) -> None:
        with self._lock:
            self.replicas[replica.name] = replica
            self._last_poll = float("-inf")     # force a re-scrape

    def remove_replica(self, name: str) -> Optional[FleetReplica]:
        with self._lock:
            rep = self.replicas.pop(name, None)
            self._loads.pop(name, None)
        self.metrics.forget_replica(name)
        return rep

    @property
    def block_size(self) -> int:
        if self._block_size is not None:
            return int(self._block_size)
        with self._lock:
            for r in self.replicas.values():
                bs = getattr(r.server, "block_size", None)
                if bs:
                    return int(bs)
        return 16

    # -- load polling ---------------------------------------------------
    def poll(self, force: bool = False) -> Dict[str, ReplicaLoad]:
        """Refresh every replica's load if the cached scrape is older
        than ``poll_interval_s`` (or ``force``). Dispatch reads the
        cache — scraping is amortized over requests, not per-request."""
        with self._lock:
            now = self._clock()
            if not force and (now - self._last_poll) < self.poll_interval_s:
                return dict(self._loads)
            self._last_poll = now
            replicas = list(self.replicas.values())
        for r in replicas:
            load = r.scrape()
            with self._lock:
                self._loads[r.name] = load
            self.metrics.observe_replica(r.name, load)
        with self._lock:
            return dict(self._loads)

    def snapshot_loads(self) -> Dict[str, ReplicaLoad]:
        """Fresh loads for every replica (forced poll) — what the
        autoscaler evaluates."""
        return self.poll(force=True)

    def _ready(self) -> List[Tuple[FleetReplica, ReplicaLoad]]:
        now = self._clock()
        out = []
        with self._lock:
            for name, rep in self.replicas.items():
                load = self._loads.get(name)
                if (rep.routable and load is not None and load.ready
                        and not load.stale(now, self.stale_after_s)):
                    out.append((rep, load))
        return out

    # -- placement ------------------------------------------------------
    def _affinity_key(self, prompt) -> Optional[bytes]:
        if not self.affinity:
            return None
        hashes = prefix_block_hashes(prompt, self.block_size,
                                     n_blocks=self.affinity_blocks)
        return hashes[-1] if hashes else None

    @staticmethod
    def _rendezvous(key: bytes, candidates) -> FleetReplica:
        """Highest-random-weight choice: each (key, replica) pair gets
        a deterministic pseudo-random weight; the max wins. Stable per
        key while membership holds; a leaving replica re-homes only its
        own keys."""
        def weight(rep):
            h = hashlib.blake2b(key + rep.name.encode("utf-8"),
                                digest_size=8).digest()
            return int.from_bytes(h, "big")
        return max(candidates, key=weight)

    def route(self, prompt) -> Tuple[FleetReplica, str]:
        """Pick (replica, kind) for ``prompt`` from the current load
        cache; kind ∈ {affinity, spill, least_loaded}. Raises
        :class:`FleetUnavailableError` when the ready set is empty."""
        self.poll()
        ready = self._ready()
        if not ready:
            raise FleetUnavailableError(
                "no ready replicas in the fleet",
                retry_after_s=self.poll_interval_s)
        by_name = {rep.name: (rep, load) for rep, load in ready}
        key = self._affinity_key(prompt)
        if key is not None:
            home = self._rendezvous(key, [rep for rep, _ in ready])
            load = by_name[home.name][1]
            if (load.queue_depth < self.spill_queue_depth
                    and load.occupancy < self.spill_occupancy):
                return home, "affinity"
            least = min(ready, key=lambda rl: rl[1].score())[0]
            return least, "spill"
        least = min(ready, key=lambda rl: rl[1].score())[0]
        return least, "least_loaded"

    # -- dispatch -------------------------------------------------------
    def _backoff(self, err: RetryableServingError) -> float:
        hint = getattr(err, "retry_after_s", None)
        if hint is None:
            hint = self.poll_interval_s
        return min(max(0.0, float(hint)), self.max_backoff_s)

    def _mark_dead(self, replica: FleetReplica) -> None:
        replica.mark_dead()
        with self._lock:
            self._loads.pop(replica.name, None)
            self._last_poll = float("-inf")
        self.metrics.inc("replica_deaths_seen")

    def submit(self, prompt, max_new_tokens: int = 16,
               timeout_ms: Optional[float] = None, **kw):
        """Place one generation and return ``(handle, replica_name,
        retries)`` — the streaming entry point. Retries SUBMIT-time
        sheds/deaths within the budget; once a handle exists, failures
        surface through it (use :meth:`generate` for end-to-end
        retry)."""
        attempts = 0
        while True:
            replica, kind = None, "least_loaded"
            try:
                replica, kind = self.route(prompt)
                handle = replica.submit(prompt,
                                        max_new_tokens=max_new_tokens,
                                        timeout_ms=timeout_ms, **kw)
                self.metrics.on_routed(kind, replica.name)
                return handle, replica.name, attempts
            except (ValueError, PoisonedRequestError, RequestTimeoutError):
                self.metrics.inc("requests_failed")
                raise
            except RetryableServingError as e:
                self.metrics.inc("sheds_seen")
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    raise
                self.metrics.inc("retries")
                self._sleep(self._backoff(e))
            except ServingError:
                # ServerClosedError / crash-typed failure: the replica
                # is gone — no sleep, next candidate immediately
                if replica is not None:
                    self._mark_dead(replica)
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    raise FleetUnavailableError(
                        f"request failed on {attempts} replicas",
                        retry_after_s=self.poll_interval_s)
                self.metrics.inc("retries")

    def generate(self, prompt, max_new_tokens: int = 16,
                 timeout_ms: Optional[float] = None, **kw) -> FleetResult:
        """The blocking front door: place, stream, and return the full
        generation — retrying sheds AND mid-generation replica deaths
        within one shared budget. This is the callable the fleet load
        generator drives."""
        t0 = self._clock()
        attempts = 0
        while True:
            replica, kind = None, "least_loaded"
            marks: List[float] = []
            try:
                replica, kind = self.route(prompt)
                handle = replica.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    timeout_ms=timeout_ms,
                    on_token=lambda tok: marks.append(self._clock()),
                    **kw)
                tokens = handle.result()
                self.metrics.on_routed(kind, replica.name)
                self.metrics.inc("requests_ok")
                ttft = (marks[0] - t0) * 1000.0 if marks else None
                inter = [(b - a) * 1000.0
                         for a, b in zip(marks, marks[1:])]
                return FleetResult(tokens=list(tokens),
                                   replica=replica.name,
                                   retries=attempts, routed=kind,
                                   ttft_ms=ttft, intertoken_ms=inter)
            except (ValueError, PoisonedRequestError):
                self.metrics.inc("requests_failed")
                raise
            except RequestTimeoutError:
                self.metrics.inc("requests_timed_out")
                raise
            except RetryableServingError as e:
                self.metrics.inc("sheds_seen")
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    self.metrics.inc("requests_failed")
                    raise
                self.metrics.inc("retries")
                self._sleep(self._backoff(e))
            except ServingError:
                if replica is not None:
                    self._mark_dead(replica)
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    self.metrics.inc("requests_failed")
                    raise FleetUnavailableError(
                        f"request failed on {attempts} replicas",
                        retry_after_s=self.poll_interval_s)
                self.metrics.inc("retries")

    # -- observability --------------------------------------------------
    def publish(self, storage) -> None:
        """Append the current ``{"type": "fleet"}`` record to a
        ``StatsStorage`` (the report/registry feed)."""
        storage.put(self.metrics.to_record())


__all__ = ["FleetResult", "FleetRouter", "FleetUnavailableError"]
