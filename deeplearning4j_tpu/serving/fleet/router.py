"""FleetRouter: health/load-aware front door with prefix affinity.

The cluster-tier dispatch over N replica handles, composing three
placement and two failure rules:

- **least-loaded among ready** — replicas are scraped (``/readyz`` +
  the merged ``load`` sub-dict) at most every ``poll_interval_s``;
  a scrape older than ``stale_after_s`` disqualifies its replica (a
  silent process is indistinguishable from a dead one). Among ready
  replicas the lowest ``(queue_depth, occupancy)`` wins.
- **prefix affinity (rendezvous)** — the prompt's full-block prefix is
  chain-hashed with the SAME ``prefix_block_hashes`` the paged server's
  prefix cache keys on, so "routes to the same replica" and "hits that
  replica's prefix cache" are literally the same address space. The
  hash picks its home replica by rendezvous (highest-random-weight)
  hashing over the CURRENT ready set: replicas joining/leaving remap
  only their own share of keys, no ring state to persist.
- **load-aware spill** — an affinity home past ``spill_queue_depth`` or
  ``spill_occupancy`` forfeits the request to the least-loaded replica:
  a hot prefix cache is worth one queue slot of patience, not a
  convoy.
- **retry on shed/death** — a typed
  :class:`~deeplearning4j_tpu.serving.resilience.RetryableServingError`
  is retried up to ``retry_budget`` times, sleeping the error's own
  ``retry_after_s`` hint (bounded by ``max_backoff_s``); a replica
  whose submit/result raises ``ServerClosedError`` (or whose worker
  crashed it into a ``ServingError``) is marked dead and the request
  moves on immediately. Budget exhausted → the last typed shed
  re-raises as-is (the caller inherits the backoff hint).
- **never retried** — permanent ``ValueError`` (bad request),
  ``PoisonedRequestError`` (the request IS the fault — it would poison
  the next replica too), and deadline misses (``RequestTimeoutError``:
  the SLO is already blown; retrying manufactures load, not answers).
  The deadline is a TOTAL wall-time budget: every retry attempt sees
  only what is left of it.
- **resume, don't restart** — a mid-stream death resumes from the
  already-emitted prefix (``submit_continuation``: prompt + emitted as
  the prefill, budget decremented, seed pinned so sampled draws land on
  identical absolute indices — bit-identical to the uninterrupted run)
  with caller streaming deduplicated exactly-once through a
  :class:`~deeplearning4j_tpu.serving.fleet.durable.StreamCursor`.
  With a :class:`~deeplearning4j_tpu.serving.fleet.durable.
  RequestJournal` attached, requests are write-ahead logged and a
  restarted router replays the incomplete ones via :meth:`recover`.

See docs/serving.md ("Fleet", "Durability") for the full semantics
table and the journal/recovery contract.
"""
from __future__ import annotations

import hashlib
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.monitor.reqtrace import (RequestTracer, SLOTracker,
                                                 TraceContext, ttft_breakdown)
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.serving.fleet.durable import (DurabilityMetrics,
                                                      RequestJournal,
                                                      StreamCursor)
from deeplearning4j_tpu.serving.fleet.metrics import FleetMetrics
from deeplearning4j_tpu.serving.fleet.replica import FleetReplica, ReplicaLoad
from deeplearning4j_tpu.serving.paged.pool import prefix_block_hashes
from deeplearning4j_tpu.serving.queue import (RequestTimeoutError,
                                              ServerClosedError,
                                              ServingError)
from deeplearning4j_tpu.serving.resilience import (PoisonedRequestError,
                                                   RetryableServingError)


class FleetUnavailableError(RetryableServingError):
    """No ready replica can take the request right now (all draining,
    dead, stale, or shedding). Typed retryable — carries the router's
    suggested re-poll interval as ``retry_after_s``."""


@dataclass
class FleetResult:
    """One completed front-door generation, tagged with where and how
    hard it was to place (what the fleet load generator logs per row)."""

    tokens: List[int]
    replica: str
    retries: int = 0
    routed: str = "least_loaded"        # affinity | spill | least_loaded
    ttft_ms: Optional[float] = None
    intertoken_ms: List[float] = field(default_factory=list)
    # durability rail: how many mid-stream failovers resumed from the
    # emitted prefix, and how many already-decoded tokens they carried
    # instead of regenerating (0/0 on an uninterrupted request)
    resumes: int = 0
    tokens_salvaged: int = 0
    # request-tracing rail: the fleet-wide trace id every segment of
    # this request carried, and (when the trace was sampled) the
    # assembled waterfall's TTFT decomposition — both None when the
    # router runs with tracing off (observational only, never math)
    trace_id: Optional[int] = None
    ttft_breakdown: Optional[dict] = None


class FleetRouter:
    """Front door over :class:`FleetReplica` handles.

    ``affinity_blocks`` bounds how much of the prompt feeds the
    affinity key (default 1: the first full block — shared system
    prompts land together while long distinct tails still spread).
    ``sleep``/``clock`` are injectable for deterministic tests.
    """

    def __init__(self, replicas=(), *, block_size: Optional[int] = None,
                 affinity: bool = True, affinity_blocks: int = 1,
                 retry_budget: int = 3, max_backoff_s: float = 1.0,
                 stale_after_s: float = 5.0, poll_interval_s: float = 0.25,
                 spill_queue_depth: int = 4, spill_occupancy: float = 0.9,
                 metrics: Optional[FleetMetrics] = None,
                 journal: Optional[RequestJournal] = None,
                 slo=None, trace_sample: float = 1.0, reqtrace=None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self._lock = threading.RLock()
        self.replicas: Dict[str, FleetReplica] = {}
        self.affinity = bool(affinity)
        self.affinity_blocks = int(affinity_blocks)
        self.retry_budget = int(retry_budget)
        self.max_backoff_s = float(max_backoff_s)
        self.stale_after_s = float(stale_after_s)
        self.poll_interval_s = float(poll_interval_s)
        self.spill_queue_depth = int(spill_queue_depth)
        self.spill_occupancy = float(spill_occupancy)
        self.metrics = metrics if metrics is not None else FleetMetrics()
        # the durability rail: resumes/salvage/dedup counters ride the
        # fleet record as its "durability" sub-dict, and the journal
        # (when given) times its fsyncs into the same instance
        self.durability = DurabilityMetrics()
        self.metrics.durability = self.durability
        # the request-tracing/SLO rail: ``slo`` is a SLOTracker (None →
        # default objectives, False → disabled), ``reqtrace`` a
        # RequestTracer (None → head-sample ``trace_sample`` of
        # requests, False → disabled). Attainment/burn ride the fleet
        # record as its "slo" sub-dict; waterfalls are host-side only.
        if slo is False:
            self.slo: Optional[SLOTracker] = None
        else:
            self.slo = slo if slo is not None else SLOTracker()
        self.metrics.slo = self.slo
        if reqtrace is False:
            self.reqtrace: Optional[RequestTracer] = None
        else:
            self.reqtrace = (reqtrace if reqtrace is not None
                             else RequestTracer(sample=float(trace_sample),
                                                slo=self.slo))
        self._journal = journal
        if journal is not None and journal.metrics is None:
            journal.metrics = self.durability
        self._rid = itertools.count(1)      # journal-less fallback ids
        self._sleep = sleep
        self._clock = clock
        self._block_size = block_size
        self._last_poll = float("-inf")
        self._loads: Dict[str, ReplicaLoad] = {}
        for r in replicas:
            self.add_replica(r)

    # -- membership -----------------------------------------------------
    def add_replica(self, replica: FleetReplica) -> None:
        with self._lock:
            self.replicas[replica.name] = replica
            self._last_poll = float("-inf")     # force a re-scrape

    def remove_replica(self, name: str) -> Optional[FleetReplica]:
        with self._lock:
            rep = self.replicas.pop(name, None)
            self._loads.pop(name, None)
        self.metrics.forget_replica(name)
        return rep

    @property
    def block_size(self) -> int:
        if self._block_size is not None:
            return int(self._block_size)
        with self._lock:
            for r in self.replicas.values():
                bs = getattr(r.server, "block_size", None)
                if bs:
                    return int(bs)
        return 16

    # -- load polling ---------------------------------------------------
    def poll(self, force: bool = False) -> Dict[str, ReplicaLoad]:
        """Refresh every replica's load if the cached scrape is older
        than ``poll_interval_s`` (or ``force``). Dispatch reads the
        cache — scraping is amortized over requests, not per-request."""
        with self._lock:
            now = self._clock()
            if not force and (now - self._last_poll) < self.poll_interval_s:
                return dict(self._loads)
            self._last_poll = now
            replicas = list(self.replicas.values())
        for r in replicas:
            load = r.scrape()
            with self._lock:
                self._loads[r.name] = load
            self.metrics.observe_replica(r.name, load)
        with self._lock:
            return dict(self._loads)

    def snapshot_loads(self) -> Dict[str, ReplicaLoad]:
        """Fresh loads for every replica (forced poll) — what the
        autoscaler evaluates."""
        return self.poll(force=True)

    def _ready(self) -> List[Tuple[FleetReplica, ReplicaLoad]]:
        now = self._clock()
        out = []
        with self._lock:
            for name, rep in self.replicas.items():
                load = self._loads.get(name)
                if (rep.routable and load is not None and load.ready
                        and not load.stale(now, self.stale_after_s)):
                    out.append((rep, load))
        return out

    # -- placement ------------------------------------------------------
    def _affinity_key(self, prompt) -> Optional[bytes]:
        if not self.affinity:
            return None
        hashes = prefix_block_hashes(prompt, self.block_size,
                                     n_blocks=self.affinity_blocks)
        return hashes[-1] if hashes else None

    @staticmethod
    def _rendezvous(key: bytes, candidates) -> FleetReplica:
        """Highest-random-weight choice: each (key, replica) pair gets
        a deterministic pseudo-random weight; the max wins. Stable per
        key while membership holds; a leaving replica re-homes only its
        own keys."""
        def weight(rep):
            h = hashlib.blake2b(key + rep.name.encode("utf-8"),
                                digest_size=8).digest()
            return int.from_bytes(h, "big")
        return max(candidates, key=weight)

    def route(self, prompt) -> Tuple[FleetReplica, str]:
        """Pick (replica, kind) for ``prompt`` from the current load
        cache; kind ∈ {affinity, spill, least_loaded}. Raises
        :class:`FleetUnavailableError` when the ready set is empty."""
        self.poll()
        ready = self._ready()
        if not ready:
            raise FleetUnavailableError(
                "no ready replicas in the fleet",
                retry_after_s=self.poll_interval_s)
        by_name = {rep.name: (rep, load) for rep, load in ready}
        key = self._affinity_key(prompt)
        if key is not None:
            home = self._rendezvous(key, [rep for rep, _ in ready])
            load = by_name[home.name][1]
            if (load.queue_depth < self.spill_queue_depth
                    and load.occupancy < self.spill_occupancy):
                return home, "affinity"
            least = min(ready, key=lambda rl: rl[1].score())[0]
            return least, "spill"
        least = min(ready, key=lambda rl: rl[1].score())[0]
        return least, "least_loaded"

    # -- dispatch -------------------------------------------------------
    def _backoff(self, err: RetryableServingError) -> float:
        hint = getattr(err, "retry_after_s", None)
        if hint is None:
            hint = self.poll_interval_s
        return min(max(0.0, float(hint)), self.max_backoff_s)

    def _mark_dead(self, replica: FleetReplica) -> None:
        replica.mark_dead()
        with self._lock:
            self._loads.pop(replica.name, None)
            self._last_poll = float("-inf")
        self.metrics.inc("replica_deaths_seen")

    def _remaining_ms(self, t0: float,
                      timeout_ms: Optional[float]) -> Optional[float]:
        """The deadline budget LEFT for the next attempt: one request
        gets ``timeout_ms`` of wall time TOTAL, not per retry (the old
        bug: a retry-heavy request could consume ``retry_budget ×
        timeout_ms``). Exhausted → typed ``RequestTimeoutError`` (the
        never-retried class: the SLO is already blown)."""
        if timeout_ms is None:
            return None
        rem = float(timeout_ms) - (self._clock() - t0) * 1000.0
        if rem <= 0.0:
            raise RequestTimeoutError(
                f"retries outlived the request's {float(timeout_ms):.1f}"
                f" ms deadline before an attempt could finish")
        return rem

    def _register(self, prompt, max_new_tokens: int,
                  timeout_ms: Optional[float], kw: dict):
        """Assign the request id, PIN the sampling seed, and journal
        the ``submitted`` record. Seed pinning is the bit-identity
        linchpin: the server defaults an unset seed to its own local
        request id, which a cross-replica failover would not reproduce
        — the router pins it to the fleet-wide rid up front so every
        continuation redraws the same ``(seed, index)`` stream."""
        rid = (self._journal.next_request_id()
               if self._journal is not None else next(self._rid))
        if float(kw.get("temperature") or 0.0) > 0.0 \
                and kw.get("seed") is None:
            kw = dict(kw, seed=rid)
        if self._journal is not None:
            self._journal.log_submitted(
                rid, prompt, max_new_tokens, timeout_ms,
                sampling={k: kw.get(k) for k in
                          ("temperature", "top_k", "top_p",
                           "seed", "eos_id")})
        return rid, kw

    def submit(self, prompt, max_new_tokens: int = 16,
               timeout_ms: Optional[float] = None,
               on_token: Optional[Callable[[int], None]] = None, **kw):
        """Place one generation and return ``(handle, replica_name,
        retries)`` — the streaming entry point. Retries SUBMIT-time
        sheds/deaths within the budget (each attempt sees only the
        deadline budget still left); once a handle exists, failures
        surface through it (use :meth:`generate` for end-to-end retry
        and the durable/exactly-once rail). ``on_token`` is an explicit
        parameter so it composes with router internals instead of
        colliding in ``**kw``."""
        t0 = self._clock()
        attempts = 0
        while True:
            replica, kind = None, "least_loaded"
            try:
                remaining = self._remaining_ms(t0, timeout_ms)
                replica, kind = self.route(prompt)
                handle = replica.submit(prompt,
                                        max_new_tokens=max_new_tokens,
                                        timeout_ms=remaining,
                                        on_token=on_token, **kw)
                self.metrics.on_routed(kind, replica.name)
                return handle, replica.name, attempts
            except (ValueError, PoisonedRequestError, RequestTimeoutError):
                self.metrics.inc("requests_failed")
                raise
            except RetryableServingError as e:
                self.metrics.inc("sheds_seen")
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    raise
                self.metrics.inc("retries")
                self._sleep(self._backoff(e))
            except ServingError:
                # ServerClosedError / crash-typed failure: the replica
                # is gone — no sleep, next candidate immediately
                if replica is not None:
                    self._mark_dead(replica)
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    raise FleetUnavailableError(
                        f"request failed on {attempts} replicas",
                        retry_after_s=self.poll_interval_s)
                self.metrics.inc("retries")

    def generate(self, prompt, max_new_tokens: int = 16,
                 timeout_ms: Optional[float] = None,
                 on_token: Optional[Callable[[int], None]] = None,
                 **kw) -> FleetResult:
        """The blocking front door: place, stream, and return the full
        generation — retrying sheds AND mid-generation replica deaths
        within one shared budget. A death mid-stream RESUMES from the
        emitted prefix (continuation submit) instead of restarting, and
        the caller's ``on_token`` is delivered through an exactly-once
        :class:`StreamCursor`, so a failover is invisible to streaming
        consumers. With a journal attached, the request is write-ahead
        logged end to end (a router crash replays it via
        :meth:`recover`). This is the callable the fleet load generator
        drives."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        rid, kw = self._register(prompt, max_new_tokens, timeout_ms, kw)
        cursor = StreamCursor(on_token, metrics=self.durability)
        # mint the request's TraceContext: trace_id IS the fleet rid
        # (which is also the journal key and the pinned sampling seed —
        # one id names the request everywhere). Every retry, failover
        # resume, and recover() replay reuses it with a new segment.
        ctx = (self.reqtrace.begin(rid) if self.reqtrace is not None
               else TraceContext(rid))
        t0 = self._clock()
        try:
            result = self._drive(rid, prompt, max_new_tokens,
                                 timeout_ms, cursor, kw, ctx=ctx, t0=t0)
        except (ValueError, PoisonedRequestError, RequestTimeoutError) as e:
            # permanent: terminal in the journal so recover() skips it.
            # A retryable give-up (FleetUnavailableError et al.) is
            # deliberately NOT terminal — the entry stays open and a
            # restarted router replays it as a continuation.
            if self._journal is not None:
                self._journal.log_failed(rid, e)
            self._trace_outcome(ctx, cursor, t0, status=(
                "timed_out" if isinstance(e, RequestTimeoutError)
                else "failed"))
            raise
        except RetryableServingError:
            self._trace_outcome(ctx, cursor, t0, status="shed")
            raise
        if self._journal is not None:
            self._journal.log_completed(rid, len(result.tokens))
        wf = self._trace_outcome(ctx, cursor, t0, status="ok",
                                 result=result)
        if wf is not None:
            result.ttft_breakdown = ttft_breakdown(wf)
        return result

    def _trace_outcome(self, ctx: TraceContext, cursor: StreamCursor,
                       t0: float, *, status: str, result=None):
        """Terminal bookkeeping for one traced request: feed the SLO
        tracker's rolling windows and close the trace (waterfall
        assembly + head/tail keep decision). Observational only; returns
        the kept waterfall dict or None."""
        if self.slo is None and self.reqtrace is None:
            return None
        e2e = (self._clock() - t0) * 1000.0
        outcome = {
            "status": status,
            "ttft_ms": (result.ttft_ms if result is not None else None),
            "e2e_ms": e2e,
            "tokens": (len(result.tokens) if result is not None
                       else len(cursor.delivered)),
            "replica": (result.replica if result is not None else None),
            # segments minted so far count the attempts even when the
            # request died before a FleetResult existed
            "retries": (result.retries if result is not None
                        else max(0, ctx.segments_minted - 1)),
            "resumes": (result.resumes if result is not None else 0),
            "origin": ctx.origin,
        }
        if self.slo is not None:
            self.slo.record(status, ttft_ms=outcome["ttft_ms"],
                            e2e_ms=e2e, tokens=outcome["tokens"],
                            replica=outcome["replica"],
                            retries=outcome["retries"],
                            resumes=outcome["resumes"],
                            trace_id=ctx.trace_id)
        if self.reqtrace is not None:
            return self.reqtrace.finish(ctx, outcome)
        return None

    def _drive(self, rid: int, prompt, max_new_tokens: int,
               timeout_ms: Optional[float], cursor: StreamCursor,
               kw: dict, ctx: Optional[TraceContext] = None,
               t0: Optional[float] = None) -> FleetResult:
        """The retry/failover loop behind :meth:`generate` and
        :meth:`recover`: attempts start from the cursor's delivered
        prefix (empty on a fresh request, pre-seeded on a journal
        replay) and every mid-stream death resumes instead of
        restarting. Each placement attempt is one trace SEGMENT: a
        ``fleet.attempt`` span tagged trace_id/segment/kind, with the
        same context handed to the replica so the server-side spans of
        that hop carry the identity too."""
        if ctx is None:
            ctx = TraceContext(rid)
        if t0 is None:
            t0 = self._clock()
        plen = int(np.asarray(prompt).size)
        attempts = 0
        resumes = 0
        salvaged = 0
        marks: List[float] = []
        while True:
            replica, kind = None, "least_loaded"
            base = len(cursor.delivered)
            seg = ctx.next_segment()
            seg_kind = ("replay" if ctx.origin == "replay" and seg == 0
                        else "resume" if base
                        else "retry" if attempts else "initial")
            try:
                with _tracer.span("fleet.attempt", cat="fleet",
                                  trace_id=ctx.trace_id, segment=seg,
                                  kind=seg_kind) as asp:
                    remaining = self._remaining_ms(t0, timeout_ms)
                    replica, kind = self.route(prompt)
                    asp.set(replica=replica.name)
                    ordinal = itertools.count(base)

                    def _deliver(tok, _ord=ordinal):
                        idx = next(_ord)
                        if cursor.deliver(idx, tok):
                            marks.append(self._clock())
                            if self._journal is not None:
                                self._journal.append_token(
                                    rid, plen + idx, tok)

                    if base:
                        handle = replica.submit_continuation(
                            prompt, list(cursor.delivered),
                            max_new_tokens=max_new_tokens,
                            timeout_ms=remaining, on_token=_deliver,
                            trace=ctx, **kw)
                    else:
                        handle = replica.submit(
                            prompt, max_new_tokens=max_new_tokens,
                            timeout_ms=remaining, on_token=_deliver,
                            trace=ctx, **kw)
                    handle.result()
                    asp.set(outcome="ok")
                self.metrics.on_routed(kind, replica.name)
                self.metrics.inc("requests_ok")
                ttft = (marks[0] - t0) * 1000.0 if marks else None
                inter = [(b - a) * 1000.0
                         for a, b in zip(marks, marks[1:])]
                return FleetResult(tokens=list(cursor.delivered),
                                   replica=replica.name,
                                   retries=attempts, routed=kind,
                                   ttft_ms=ttft, intertoken_ms=inter,
                                   resumes=resumes,
                                   tokens_salvaged=salvaged,
                                   trace_id=ctx.trace_id)
            except (ValueError, PoisonedRequestError):
                self.metrics.inc("requests_failed")
                raise
            except RequestTimeoutError:
                self.metrics.inc("requests_timed_out")
                raise
            except RetryableServingError as e:
                self.metrics.inc("sheds_seen")
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    self.metrics.inc("requests_failed")
                    raise
                self.metrics.inc("retries")
                self._sleep(self._backoff(e))
            except ServingError:
                if replica is not None:
                    self._mark_dead(replica)
                # durability point: whatever streamed before the death
                # must be on disk before the continuation goes out
                if self._journal is not None:
                    self._journal.flush(rid)
                attempts += 1
                if attempts > self.retry_budget:
                    self.metrics.inc("retry_giveups")
                    self.metrics.inc("requests_failed")
                    raise FleetUnavailableError(
                        f"request failed on {attempts} replicas",
                        retry_after_s=self.poll_interval_s)
                self.metrics.inc("retries")
                if cursor.delivered:
                    # the retry will be a continuation: every already-
                    # delivered token is decode work the old restart-
                    # from-scratch path would have thrown away
                    resumes += 1
                    salvaged += len(cursor.delivered)
                    self.durability.inc("resumes")
                    self.durability.inc("tokens_salvaged",
                                        len(cursor.delivered))

    def recover(self, journal: Optional[RequestJournal] = None) -> dict:
        """Router-crash recovery: replay every INCOMPLETE journal entry
        as a resume-from-emitted-prefix continuation. Idempotent by
        request id — completed/failed entries are skipped by the
        journal scan, and each replay is journaled terminal the moment
        it lands, so a crash DURING recovery re-replays only what is
        still open. Returns ``{rid: FleetResult}`` for the requests
        completed by this call; entries that shed retryably stay open
        for the next recover, permanent failures are journaled
        ``failed``."""
        jn = journal if journal is not None else self._journal
        if jn is None:
            raise ValueError("recover() needs a journal (pass one or "
                             "construct the router with journal=...)")
        if self._journal is None:
            # adopt: post-recovery traffic journals into the same WAL
            self._journal = jn
            if jn.metrics is None:
                jn.metrics = self.durability
        elif jn is not self._journal:
            raise ValueError("recover() got a different journal than "
                             "the one this router writes to")
        results: dict = {}
        for rid, entry in sorted(jn.incomplete().items()):
            prompt = np.asarray(entry["prompt"], np.int32)
            emitted = entry["emitted"]
            cursor = StreamCursor(None, metrics=self.durability,
                                  preload=emitted)
            self.durability.inc("recovered_requests")
            if emitted:
                self.durability.inc("resumes")
                self.durability.inc("tokens_salvaged", len(emitted))
            kw = {k: v for k, v in entry["sampling"].items()
                  if v is not None}
            # a replay keeps the ORIGINAL trace_id (the rid) — the
            # recovered segments join the same trace, tagged replay
            ctx = (self.reqtrace.begin(rid, origin="replay")
                   if self.reqtrace is not None
                   else TraceContext(rid, origin="replay"))
            t0 = self._clock()
            try:
                res = self._drive(rid, prompt, entry["max_new_tokens"],
                                  entry["timeout_ms"], cursor, kw,
                                  ctx=ctx, t0=t0)
            except (ValueError, PoisonedRequestError,
                    RequestTimeoutError) as e:
                jn.log_failed(rid, e)
                self._trace_outcome(ctx, cursor, t0, status=(
                    "timed_out" if isinstance(e, RequestTimeoutError)
                    else "failed"))
                continue
            except RetryableServingError:
                self._trace_outcome(ctx, cursor, t0, status="shed")
                continue        # still open: the NEXT recover retries
            jn.log_completed(rid, len(res.tokens))
            wf = self._trace_outcome(ctx, cursor, t0, status="ok",
                                     result=res)
            if wf is not None:
                res.ttft_breakdown = ttft_breakdown(wf)
            results[rid] = res
        return results

    # -- observability --------------------------------------------------
    def publish(self, storage) -> None:
        """Append the current ``{"type": "fleet"}`` record to a
        ``StatsStorage`` (the report/registry feed)."""
        storage.put(self.metrics.to_record())


__all__ = ["FleetResult", "FleetRouter", "FleetUnavailableError"]
