"""serving — ParallelInference-style model serving.

Reference parity: deeplearning4j-parallelwrapper's ParallelInference
layer (the L7 serving tier of the reference ecosystem's map, PAPER.md
§1), redesigned for a jit-compiled runtime:

- ``inference``: :class:`ParallelInference` — thread-safe submit/observe
  front-end with SEQUENTIAL / BATCHED / INPLACE modes over any
  MultiLayerNetwork or ComputationGraph.
- ``batching``: dynamic batcher coalescing requests up to
  ``max_batch_size``/``max_delay_ms``, padded to power-of-two shape
  buckets so the server compiles O(buckets) XLA programs, not
  O(request shapes).
- ``queue``: bounded request queue — admission backpressure
  (:class:`ServerOverloadedError`), per-request deadlines
  (:class:`RequestTimeoutError`), graceful drain on shutdown.
- ``metrics``: counters + latency histograms exported through
  ``ui.stats.StatsStorage`` records (``{"type": "serving", ...}``).
- ``resilience``: the serving resilience rail — SLO admission control
  (shed doomed requests at ``submit()`` with
  ``ServerOverloadedError(retry_after_s=...)``), a circuit breaker on
  consecutive exec failures surfaced through /healthz, supervised
  workers with exactly-once crash requeue, bisecting poisoned-batch
  isolation (``PoisonedRequestError``), and checkpoint-driven hot
  reload (``ParallelInference.reload_from`` with canary + rollback).
- ``generative``: continuous-batching autoregressive serving
  (:class:`GenerativeServer`) — slotted KV cache slabs in HBM,
  step-boundary admission into free slots, ONE compiled decode step
  advancing every active slot, pow2 prefill buckets, streaming token
  delivery, SLO admission on p99 decode-step time, and supervised
  crash recovery (requeue at prefill, exactly once).
- ``loadgen``: closed/open-loop load generator for tests and examples,
  plus a generative traffic mode (mixed prompt/output lengths, TTFT +
  inter-token percentiles).

See docs/serving.md for the full knob reference.
"""
from deeplearning4j_tpu.serving.batching import (
    Batch, BucketSpec, DynamicBatcher, pad_to_bucket, pow2_buckets)
from deeplearning4j_tpu.serving.generative import (
    GenerationCancelled, GenerationHandle, GenerativeMetrics,
    GenerativeServer, GenerativeSpec, SlotAllocator, greedy_decode)
from deeplearning4j_tpu.serving.inference import (
    InferenceMode, ParallelInference, ServingSpec)
from deeplearning4j_tpu.serving.loadgen import (
    FleetLoadGenerator, GenerativeLoadGenerator, LoadGenerator, LoadResult)
from deeplearning4j_tpu.serving.metrics import (
    LatencyHistogram, ServingMetrics)
from deeplearning4j_tpu.serving.queue import (
    InferenceRequest, RequestQueue, RequestTimeoutError, ServerClosedError,
    ServerOverloadedError, ServingError, ServingTimeoutError)
from deeplearning4j_tpu.serving.resilience import (
    AdmissionController, CircuitBreaker, PoisonedRequestError,
    ReloadFailedError, ResilienceConfig, RetryableServingError,
    WorkerSupervisor)
from deeplearning4j_tpu.serving.sampling import sample_token

__all__ = [
    "ParallelInference", "InferenceMode", "ServingSpec",
    "DynamicBatcher", "Batch", "BucketSpec", "pow2_buckets",
    "pad_to_bucket",
    "RequestQueue", "InferenceRequest",
    "ServingError", "RetryableServingError", "ServerOverloadedError",
    "RequestTimeoutError", "ServerClosedError", "ServingTimeoutError",
    "ServingMetrics", "LatencyHistogram",
    "ResilienceConfig", "AdmissionController", "CircuitBreaker",
    "WorkerSupervisor", "PoisonedRequestError", "ReloadFailedError",
    "LoadGenerator", "LoadResult", "GenerativeLoadGenerator",
    "FleetLoadGenerator",
    "GenerativeServer", "GenerativeSpec", "GenerativeMetrics",
    "GenerationHandle", "GenerationCancelled", "SlotAllocator",
    "greedy_decode", "sample_token",
]
