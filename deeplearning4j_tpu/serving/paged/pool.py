"""KV block pool: free-list block allocation + block-granularity
prefix caching over one preallocated paged slab.

The memory tier under the paged serving path (vLLM's PagedAttention
allocator role, Kwon et al. SOSP '23): the slab is carved into
fixed-size token blocks, requests hold per-request BLOCK TABLES of
block ids, and capacity is proportional to tokens actually held — not
to ``max_slots * max_seq`` as with dense slabs. This module is pure
host-side bookkeeping (the device arrays never move); it generalizes
``serving/generative.SlotAllocator``'s free-list + freed-exactly-once
discipline to refcounted, content-addressed blocks:

- **block 0 is the NULL block** — never allocated, the target of every
  unused table entry and every inactive decode lane's write, so the
  compiled gather/scatter step needs no masking of table indices.
- **refcounts** — a block is held by every request whose table points
  at it; prefix-cache hits retain shared blocks, so one block serves
  many requests. ``release()`` of a block not currently held raises
  (the double-free invariant, enforced here like ``SlotAllocator``).
- **prefix cache** — full blocks of a prompt are content-addressed by
  a CHAIN hash (each block's hash folds in its predecessor's, so equal
  hashes mean equal whole prefixes, not just equal block contents).
  A cached block whose refcount drops to zero becomes EVICTABLE (its
  K/V stay valid in the slab) and parks in an LRU; allocation evicts
  from that LRU only when the free list is empty, so caching never
  reduces usable capacity.
- **leak detection** — :meth:`check_invariant` asserts
  ``free + held + evictable == num_blocks - 1`` and (given the active
  block tables) that every refcount equals the number of tables
  holding the block; the paged server runs it every scheduler step
  under ``debug_leaks=True`` (tests/test_paged.py).
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.serving.queue import ServerOverloadedError

#: the reserved null/trash block id (see module docstring)
NULL_BLOCK = 0


class PoolExhaustedError(ServerOverloadedError):
    """Typed capacity shed: the block pool cannot hold the request's
    worst-case token footprint right now. A
    :class:`~deeplearning4j_tpu.serving.queue.ServerOverloadedError`,
    so clients back off with ``retry_after_s`` exactly as for a full
    queue — pool pressure is load, not a crash."""


def prefix_block_hashes(tokens: np.ndarray, block_size: int,
                        n_blocks: Optional[int] = None) -> List[bytes]:
    """Chain hashes of the FULL blocks of ``tokens``: entry ``u`` is
    ``H(entry[u-1] || tokens[u*bs:(u+1)*bs])``, so two requests share
    hash ``u`` iff their first ``(u+1)*block_size`` tokens are
    identical — the content address of a reusable KV block. Partial
    trailing blocks are never hashed (their KV rows are still being
    appended to)."""
    toks = np.asarray(tokens, np.int32).reshape(-1)
    full = int(toks.size) // int(block_size)
    if n_blocks is not None:
        full = min(full, int(n_blocks))
    out: List[bytes] = []
    h_prev = b""
    for u in range(full):
        block = toks[u * block_size:(u + 1) * block_size]
        h = hashlib.blake2b(h_prev + block.tobytes(),
                            digest_size=16).digest()
        out.append(h)
        h_prev = h
    return out


class BlockPool:
    """Refcounted free-list allocator + prefix cache over
    ``num_blocks`` KV blocks of ``block_size`` tokens each.

    Block states (block 0 excluded — it is the permanent null block):

    - *free*: on the free list, contents meaningless;
    - *held*: refcount >= 1 — referenced by that many live block
      tables (a private block has refcount 1, a shared cached prefix
      block has one per reader);
    - *evictable*: refcount 0 but registered in the prefix cache — its
      K/V rows are intact and a future prefix hit revives it for free;
      reclaimed LRU-first when the free list runs dry.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"need at least 2 blocks (1 null + 1 usable), "
                f"got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # pop() hands out block 1 first — block 0 is never listed
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._refs: Dict[int, int] = {}
        # content addressing: hash -> block id, block id -> hash
        self._by_hash: Dict[bytes, int] = {}
        self._hash_of: Dict[int, bytes] = {}
        # zero-ref cached blocks, oldest-released first
        self._evictable: "OrderedDict[int, None]" = OrderedDict()
        self.evictions = 0

    # -- capacity -------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Usable blocks (the null block is overhead)."""
        return self.num_blocks - 1

    def free_count(self) -> int:
        """Blocks on the free list proper."""
        return len(self._free)

    def usable_free_count(self) -> int:
        """Blocks allocatable RIGHT NOW: free + evictable-cached."""
        return len(self._free) + len(self._evictable)

    def held_count(self) -> int:
        return len(self._refs)

    def cached_count(self) -> int:
        """Blocks with live cache registrations (held or evictable)."""
        return len(self._by_hash)

    # -- allocation -----------------------------------------------------
    def alloc(self) -> int:
        """Pop a free block (evicting the LRU cached block if the free
        list is empty). The caller holds one reference. Raises
        :class:`PoolExhaustedError` when nothing is reclaimable."""
        if not self._free:
            if not self._evictable:
                raise PoolExhaustedError(
                    f"KV block pool exhausted: all {self.capacity} "
                    f"blocks held by live requests", retry_after_s=0.1)
            b, _ = self._evictable.popitem(last=False)      # LRU
            self._uncache(b)
            self.evictions += 1
            self._free.append(b)
        b = self._free.pop()
        self._refs[b] = 1
        return b

    def retain(self, b: int) -> None:
        """Take one more reference on a held or evictable block (the
        prefix-cache hit path revives evictable blocks here)."""
        if b == NULL_BLOCK:
            raise ValueError("the null block cannot be retained")
        if b in self._refs:
            self._refs[b] += 1
        elif b in self._evictable:
            del self._evictable[b]
            self._refs[b] = 1
        else:
            raise RuntimeError(f"block {b} retained while free")

    def release(self, b: int) -> None:
        """Drop one reference. At zero the block returns to the free
        list — or parks evictable when it is a registered prefix block.
        Releasing an unheld block raises (the double-free invariant)."""
        refs = self._refs.get(b)
        if refs is None:
            raise RuntimeError(
                f"block {b} released twice (or never allocated)")
        if refs > 1:
            self._refs[b] = refs - 1
            return
        del self._refs[b]
        if b in self._hash_of:
            self._evictable[b] = None       # newest at the MRU end
        else:
            self._free.append(b)

    # -- prefix cache ---------------------------------------------------
    def lookup(self, hashes: Sequence[bytes],
               max_blocks: Optional[int] = None) -> List[int]:
        """Longest cached prefix of ``hashes`` (bounded by
        ``max_blocks``), each returned block RETAINED for the caller —
        chain hashing makes a per-position match imply the whole
        prefix matches."""
        out: List[int] = []
        limit = len(hashes) if max_blocks is None \
            else min(len(hashes), int(max_blocks))
        for u in range(limit):
            b = self._by_hash.get(hashes[u])
            if b is None:
                break
            self.retain(b)
            out.append(b)
        return out

    def register(self, h: bytes, b: int) -> bool:
        """Content-address a HELD block the caller just filled. A block
        already registered under another hash, or a hash already naming
        another block (a concurrent fill of the same prefix), leaves
        the cache unchanged — the caller's block stays private."""
        if b == NULL_BLOCK or b not in self._refs:
            raise RuntimeError(f"block {b} must be held to register")
        if h in self._by_hash or b in self._hash_of:
            return False
        self._by_hash[h] = b
        self._hash_of[b] = h
        return True

    def _uncache(self, b: int) -> None:
        h = self._hash_of.pop(b, None)
        if h is not None:
            self._by_hash.pop(h, None)

    def flush_cache(self) -> int:
        """Drop every prefix-cache registration — the hot-reload path:
        cached blocks content-address K/V computed with superseded
        weights, so no FUTURE lookup may reuse them. Evictable blocks
        (refcount 0, kept alive only by their registration) return to
        the free list; held shared blocks keep their refcounts so
        in-flight readers finish — the same accepted in-flight
        staleness as the dense server's ``update_model`` — and, now
        unregistered, go straight back to the free list on their last
        release. Returns the number of registrations dropped."""
        dropped = len(self._by_hash)
        self._by_hash.clear()
        self._hash_of.clear()
        self._free.extend(self._evictable)
        self._evictable.clear()
        return dropped

    # -- lifecycle ------------------------------------------------------
    def reset(self) -> None:
        """Forget everything — the crash-recovery path: a respawned
        worker's slab contents are mid-dispatch garbage, so every held
        block is released and the prefix cache (which addresses slab
        CONTENTS) is dropped wholesale."""
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self._refs.clear()
        self._by_hash.clear()
        self._hash_of.clear()
        self._evictable.clear()

    # -- leak detection -------------------------------------------------
    def check_invariant(
            self,
            tables: Optional[Iterable[Sequence[int]]] = None) -> None:
        """Assert pool accounting is exact: every usable block is in
        exactly one of {free, held, evictable}, and — when the live
        block ``tables`` are provided — every refcount equals the
        number of tables holding that block. Raises AssertionError on
        any leak or double-count (satellite 1's debug-flag check)."""
        free = set(self._free)
        held = set(self._refs)
        evict = set(self._evictable)
        assert NULL_BLOCK not in free | held | evict, \
            "null block entered the pool"
        assert not (free & held), f"blocks both free and held: " \
            f"{sorted(free & held)}"
        assert not (free & evict), f"blocks both free and evictable: " \
            f"{sorted(free & evict)}"
        assert not (held & evict), f"blocks both held and evictable: " \
            f"{sorted(held & evict)}"
        n = len(free) + len(held) + len(evict)
        assert n == self.capacity, \
            (f"block leak: {len(free)} free + {len(held)} held + "
             f"{len(evict)} evictable = {n} != capacity {self.capacity}")
        for b, h in self._hash_of.items():
            assert self._by_hash.get(h) == b, \
                f"cache maps out of sync for block {b}"
        assert len(self._by_hash) == len(self._hash_of)
        if tables is not None:
            counts: Dict[int, int] = {}
            for table in tables:
                for b in table:
                    b = int(b)
                    if b != NULL_BLOCK:
                        counts[b] = counts.get(b, 0) + 1
            assert counts == dict(self._refs), \
                (f"refcounts diverge from live tables: pool="
                 f"{dict(sorted(self._refs.items()))} "
                 f"tables={dict(sorted(counts.items()))}")

    def stats(self) -> Dict[str, int]:
        return {"capacity": self.capacity,
                "free": len(self._free),
                "held": len(self._refs),
                "evictable": len(self._evictable),
                "cached": len(self._by_hash),
                "evictions": self.evictions}


def blocks_for_tokens(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` KV rows."""
    return -(-int(n_tokens) // int(block_size))


__all__ = ["BlockPool", "PoolExhaustedError", "NULL_BLOCK",
           "prefix_block_hashes", "blocks_for_tokens"]
