"""Paged KV serving: block-pooled KV caches, prefix caching, and
tensor-parallel prefill/decode over the continuous-batching scheduler.

See docs/serving.md "Paged KV & prefix caching" and the module
docstrings of :mod:`.pool` (the allocator/prefix-cache bookkeeping) and
:mod:`.server` (the server itself).
"""
from deeplearning4j_tpu.serving.paged.pool import (NULL_BLOCK, BlockPool,
                                                   PoolExhaustedError,
                                                   blocks_for_tokens,
                                                   prefix_block_hashes)
from deeplearning4j_tpu.serving.paged.server import (PagedGenerativeServer,
                                                     PagedGenerativeSpec,
                                                     PagedMetrics)

__all__ = ["BlockPool", "PoolExhaustedError", "NULL_BLOCK",
           "prefix_block_hashes", "blocks_for_tokens",
           "PagedGenerativeSpec", "PagedGenerativeServer", "PagedMetrics"]
