"""Paged-KV generative serving: block pool + prefix cache +
tensor-parallel dispatch over the continuous-batching scheduler.

The memory tier vLLM proved out (PagedAttention, Kwon et al. SOSP '23)
under the Orca-style step scheduler PR 15 built: instead of one dense
``[layers, max_slots, heads, max_seq, head_dim]`` row per slot, K/V
live in fixed-size token BLOCKS carved from one preallocated slab
``[layers, num_blocks, heads, block_size, head_dim]``, and each request
holds a BLOCK TABLE grown one block at a time at decode-step
boundaries. Capacity is proportional to tokens actually held — a
12-token chat costs one block, not a ``max_seq`` row — so the same HBM
serves several times the concurrent requests (bench.py serving_paged).

Three layers, all riding :class:`GenerativeServer`'s scheduler/queue/
resilience plumbing unchanged:

- **block pool** (``pool.py``) — refcounted free-list allocator with
  the null-block-0 convention; admission is gated on BLOCKS two ways:
  ``submit`` reserves each request's worst-case block footprint against
  pool capacity (shedding typed :class:`PoolExhaustedError` with a
  ``retry_after_s`` hint when the pool cannot ever hold it — the
  reservation is released exactly once via the request future's done
  callback), and ``_can_place`` holds a queued request at the FRONT
  until enough blocks are actually free. The conservative reservation
  means a placed request can never fail a block allocation mid-decode.
- **prefix caching** — full prompt blocks are content-addressed by
  chain hash; a repeated system prompt/few-shot prefix prefills only
  its SUFFIX (``hist`` cached tokens skip straight to reused blocks),
  so repeated-prefix TTFT approaches one decode step. Refcounts release
  exactly once on completion, shed, cancel AND crash-recovery requeue
  (``pool.reset()`` on worker respawn — the slab is mid-dispatch
  garbage, so the cache addressing its contents drops wholesale); a
  hot reload (``update_model``) fences the cache too — cached K/V
  belong to the superseded weights, so the worker flushes every
  registration at its next step boundary before admitting anyone.
- **tensor parallel** — ``tp > 1`` builds a ``{model: tp}`` mesh from
  the PR-7 :class:`~deeplearning4j_tpu.parallel.sharding.ShardingSpec`
  ("transformer" preset: qkv/fc column, proj row, wte vocab-sharded),
  shards both KV slabs on the HEADS axis, replicates the tiny host io
  (tables, tokens, positions), and lets GSPMD propagate through the
  jitted step — a model larger than one chip's HBM serves, and greedy
  tokens still match the single-chip server (tests/test_paged.py).

Correctness contract: with ``max_blocks_per_req * block_size ==
max_seq`` the gathered paged context is elementwise identical to the
dense slab context (zoo/gpt.py ``gpt_paged_decode_fns``), so greedy
output is bit-identical to :func:`~deeplearning4j_tpu.serving.
generative.greedy_decode` — paged vs dense is a memory-layout change,
not a numerics change. See docs/serving.md "Paged KV & prefix caching".
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.compilecache.aot import AOTDispatch, ph_shape_sig
from deeplearning4j_tpu.serving.generative import (GenerationHandle,
                                                   GenerationRequest,
                                                   GenerativeMetrics,
                                                   GenerativeServer,
                                                   SlotAllocator, _trace_args)
from deeplearning4j_tpu.serving.metrics import safe_ratio
from deeplearning4j_tpu.serving.paged.pool import (NULL_BLOCK, BlockPool,
                                                   PoolExhaustedError,
                                                   blocks_for_tokens,
                                                   prefix_block_hashes)


@dataclass
class PagedGenerativeSpec:
    """A model's PAGED generative-serving contract (produced by e.g.
    ``zoo.gpt.gpt_paged_spec``) — the block-table analogue of
    :class:`~deeplearning4j_tpu.serving.generative.GenerativeSpec`.

    - ``params()`` pulls the current trained parameter arrays by name.
    - ``make_fns(block_size, max_blocks_per_req)`` builds the pure
      ``(prefill_fn, decode_fn)`` pair — or ``(prefill_fn, decode_fn,
      verify_fn)`` triple when the model supports speculative decoding
      — for one block geometry (the server memoizes the jitted
      dispatchers per geometry, so every server over the same model +
      geometry shares one compile set). Io contracts are documented on
      ``zoo.gpt.gpt_paged_decode_fns``.
    - ``kv_shape(num_blocks, block_size)`` is the shape of ONE slab —
      required layout ``[layers, num_blocks, heads, block_size,
      head_dim]`` (the tensor-parallel path shards axis 2, the heads).
    """

    params: Callable[[], Dict[str, object]]
    make_fns: Callable[[int, int], tuple]
    kv_shape: Callable[[int, int], tuple]
    vocab_size: int
    max_seq_len: int
    num_heads: int
    kv_dtype: str = "float32"
    eos_id: Optional[int] = None


def _paged_dispatchers(spec: PagedGenerativeSpec, kv_shape: tuple,
                       block_size: int, max_blocks: int,
                       mesh_key) -> Dict[str, AOTDispatch]:
    """One (decode, prefill) dispatcher pair per (spec, slab geometry,
    mesh), memoized on the spec object — the paged analogue of
    ``generative._spec_dispatchers``. ``make_fns`` builds fresh closure
    objects each call, so without this memo a second server (a restart,
    a canary) would recompile every program; the mesh key keeps AOT
    executables lowered for one device layout from colliding with a
    differently-sharded server's identical io signature."""
    cache = getattr(spec, "_disp_cache", None)
    if cache is None:
        cache = {}
        spec._disp_cache = cache
    key = (tuple(int(d) for d in kv_shape), int(block_size),
           int(max_blocks), mesh_key)
    pair = cache.get(key)
    if pair is None:
        import jax
        fns = spec.make_fns(int(block_size), int(max_blocks))
        prefill_fn, decode_fn = fns[0], fns[1]
        verify_fn = fns[2] if len(fns) > 2 else None
        pair = {
            "decode": AOTDispatch(
                jax.jit(decode_fn, donate_argnums=(1, 2)), ph_arg=3),
            "prefill": AOTDispatch(
                jax.jit(prefill_fn, donate_argnums=(1, 2)), ph_arg=3)}
        if verify_fn is not None:
            pair["verify"] = AOTDispatch(
                jax.jit(verify_fn, donate_argnums=(1, 2)), ph_arg=3)
        cache[key] = pair
    return pair


class PagedMetrics(GenerativeMetrics):
    """GenerativeMetrics plus the paged lanes: pool occupancy (held
    blocks per decode step over capacity), prefix-cache hit rate,
    blocks-per-retired-request, alloc/release/eviction counters. All
    ratios are :func:`~deeplearning4j_tpu.serving.metrics.safe_ratio`
    — 0.0 at cold start, never NaN (the fold_serving/ui contract)."""

    def __init__(self, max_slots: int = 0, num_blocks: int = 0,
                 block_size: int = 0):
        super().__init__(max_slots)
        self.num_blocks = int(num_blocks)     # usable (non-null) blocks
        self.block_size = int(block_size)
        for c in ("prefix_lookups", "prefix_hits", "prefix_blocks_hit",
                  "prefix_cache_flushes",
                  "blocks_allocated", "blocks_released",
                  "blocks_held_sum", "pool_samples",
                  "request_blocks_sum", "requests_retired"):
            self.counters[c] = 0
        self._pool_stats: Dict[str, int] = {}

    def observe_pool(self, held: int, stats: Optional[dict] = None) -> None:
        """One per-decode-step occupancy sample (held blocks)."""
        with self._lock:
            self.counters["blocks_held_sum"] += int(held)
            self.counters["pool_samples"] += 1
            if stats is not None:
                self._pool_stats = dict(stats)

    def observe_prefix(self, looked_up: bool, blocks_hit: int) -> None:
        with self._lock:
            if looked_up:
                self.counters["prefix_lookups"] += 1
            if blocks_hit > 0:
                self.counters["prefix_hits"] += 1
                self.counters["prefix_blocks_hit"] += int(blocks_hit)

    def observe_blocks(self, allocated: int = 0, released: int = 0) -> None:
        with self._lock:
            self.counters["blocks_allocated"] += int(allocated)
            self.counters["blocks_released"] += int(released)

    def observe_request_blocks(self, n: int) -> None:
        with self._lock:
            self.counters["request_blocks_sum"] += int(n)
            self.counters["requests_retired"] += 1

    def to_record(self) -> dict:
        rec = super().to_record()
        with self._lock:
            c = self.counters
            rec["paged"] = {
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "pool_occupancy": round(safe_ratio(
                    c["blocks_held_sum"],
                    c["pool_samples"] * self.num_blocks), 4),
                "prefix_hit_rate": round(safe_ratio(
                    c["prefix_hits"], c["prefix_lookups"]), 4),
                "prefix_blocks_hit": c["prefix_blocks_hit"],
                "blocks_per_request": round(safe_ratio(
                    c["request_blocks_sum"], c["requests_retired"]), 3),
                "blocks_allocated": c["blocks_allocated"],
                "blocks_released": c["blocks_released"],
                "prefix_cache_flushes": c["prefix_cache_flushes"],
                "evictions": self._pool_stats.get("evictions", 0),
                "cached_blocks": self._pool_stats.get("cached", 0),
                "held_blocks": self._pool_stats.get("held", 0)}
        return rec

    def stats(self) -> str:
        rec = self.to_record()
        p = rec["paged"]
        return "\n".join([
            super().stats(),
            f"  paged: {p['num_blocks']} blocks x {p['block_size']} "
            f"tokens, occupancy {p['pool_occupancy']:.1%}, prefix hit "
            f"rate {p['prefix_hit_rate']:.1%} "
            f"({p['prefix_blocks_hit']} blocks), "
            f"{p['blocks_per_request']} blocks/request, "
            f"{p['evictions']} evictions"])


class PagedGenerativeServer(GenerativeServer):
    """Continuous-batching server over a paged KV block pool.

    ::

        spec = zoo.gpt.gpt_paged_spec(sd, cfg)
        srv = PagedGenerativeServer(spec, max_slots=8, block_size=16,
                                    kv_hbm_bytes=1 << 30)
        tokens = srv.generate([1, 2, 3], max_new_tokens=32)

    - ``block_size``: tokens per KV block (16 is the vLLM default —
      small enough that a short chat wastes < block_size rows, large
      enough that table gathers stay coarse).
    - ``num_blocks`` / ``kv_hbm_bytes``: pool size, directly or as an
      HBM budget (``num_blocks = budget // bytes_per_block``). Default:
      the dense-equivalent worst case (``max_slots`` requests at full
      ``max_seq``) — same capacity floor as the dense server, but
      short requests release what they don't use.
    - ``tp``: tensor-parallel ways over the ``model`` mesh axis
      (params sharded per the "transformer" preset, KV slabs sharded
      on heads; requires ``num_heads % tp == 0``).
    - ``prefix_cache=False`` disables content-addressed block reuse
      (every prefill allocates fresh blocks).
    - ``debug_leaks=True`` runs the pool's full accounting invariant
      against the live block tables after EVERY decode step (test/CI
      flag; O(blocks) per step).

    Everything else (admission, queueing, SLO shed, streaming,
    supervision, crash requeue) is inherited from
    :class:`GenerativeServer` unchanged.
    """

    def __init__(self, spec, max_slots: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 kv_hbm_bytes: Optional[int] = None,
                 max_blocks_per_req: Optional[int] = None,
                 tp: int = 1, devices: Optional[Sequence] = None,
                 prefix_cache: bool = True, debug_leaks: bool = False,
                 **kw):
        if int(block_size) < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if int(tp) < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        # subclass knobs FIRST: super().__init__ calls the _make_metrics
        # and _init_kv hooks below, which read them
        self.block_size = int(block_size)
        self._num_blocks_arg = num_blocks
        self._kv_hbm_bytes_arg = kv_hbm_bytes
        self._maxb_arg = max_blocks_per_req
        self.tp = int(tp)
        self._devices_arg = devices
        self.prefix_cache_enabled = bool(prefix_cache)
        self.debug_leaks = bool(debug_leaks)
        self._strategy = None
        self._kv_sharding = None
        self._commit_lock = threading.Lock()
        self._committed = 0          # reserved worst-case blocks
        # hot-reload fence: set by update_model(), consumed by the
        # worker at its next step boundary (the pool is worker-owned)
        self._prefix_flush_pending = threading.Event()
        super().__init__(spec, max_slots=max_slots, **kw)

    # -- hook overrides -------------------------------------------------
    def _coerce_spec(self, spec):
        if not isinstance(spec, PagedGenerativeSpec):
            if hasattr(spec, "paged_spec"):
                spec = spec.paged_spec()
            else:
                raise TypeError(
                    f"{type(spec).__name__} is not paged-servable: pass "
                    f"a PagedGenerativeSpec (e.g. from "
                    f"zoo.gpt.gpt_paged_spec)")
        return spec

    def _make_metrics(self) -> PagedMetrics:
        # pool geometry is resolved later in _init_kv, which backfills
        # num_blocks/block_size on this instance
        return PagedMetrics(self.max_slots, 0, self.block_size)

    def _init_kv(self) -> None:
        """Allocate the paged memory tier: one K + one V slab shaped
        ``[layers, num_blocks, heads, block_size, head_dim]`` (block 0
        reserved as the null block), the block pool, per-slot block
        tables, and the geometry-memoized dispatchers. With ``tp > 1``
        also builds the mesh and shards params + slabs."""
        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.memory import AllocationsTracker
        from deeplearning4j_tpu.monitor import memstats
        from deeplearning4j_tpu.ndarray.dtype import DataType
        spec = self.spec
        BS = self.block_size
        self._maxb = int(self._maxb_arg) if self._maxb_arg is not None \
            else blocks_for_tokens(self.max_seq_len, BS)
        if self._maxb * BS < self.max_seq_len:
            raise ValueError(
                f"max_blocks_per_req {self._maxb} x block_size {BS} "
                f"cannot hold max_seq_len {self.max_seq_len}")
        self._kv_dtype = DataType.from_any(spec.kv_dtype).jnp
        itemsize = jnp.zeros((), self._kv_dtype).dtype.itemsize
        per_block_shape = tuple(spec.kv_shape(1, BS))
        self.bytes_per_block = 2 * int(np.prod(per_block_shape)) * itemsize
        if self._num_blocks_arg is not None:
            num_blocks = int(self._num_blocks_arg)
        elif self._kv_hbm_bytes_arg is not None:
            num_blocks = max(2, int(self._kv_hbm_bytes_arg)
                             // self.bytes_per_block)
        else:
            # dense-equivalent floor: every slot at full max_seq fits
            num_blocks = 1 + self.max_slots * self._maxb
        shape = tuple(spec.kv_shape(num_blocks, BS))
        self.kv_slab_bytes = 2 * int(np.prod(shape)) * itemsize
        memstats.check_headroom(
            self.kv_slab_bytes,
            f"paged KV slabs ({num_blocks} blocks x {BS} tokens)")
        mesh_key = None
        if self.tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            from deeplearning4j_tpu.parallel.mesh import MODEL_AXIS
            from deeplearning4j_tpu.parallel.sharding import ShardingSpec
            if spec.num_heads % self.tp:
                raise ValueError(
                    f"tp={self.tp} must divide num_heads "
                    f"{spec.num_heads} (the KV slab shards on the "
                    f"heads axis)")
            devices = list(self._devices_arg
                           if self._devices_arg is not None
                           else jax.devices())
            sspec = ShardingSpec(axes={MODEL_AXIS: self.tp},
                                 preset="transformer", batch_axes=())
            sspec.validate(
                params={n: tuple(np.shape(a))
                        for n, a in self._params.items()},
                device_count=len(devices))
            self._strategy = strat = sspec.build(devices=devices)
            self._params = {
                n: jax.device_put(a, strat.param_sharding(n, np.ndim(a)))
                for n, a in self._params.items()}
            # slab layout contract: axis 2 is heads
            self._kv_sharding = NamedSharding(
                strat.mesh.mesh,
                PartitionSpec(None, None, MODEL_AXIS, None, None))
            self._io_sharding = NamedSharding(strat.mesh.mesh,
                                              PartitionSpec())
            mesh_key = (self.tp,
                        tuple(str(d) for d in strat.mesh.mesh.devices.flat))
        self._kc = self._fresh_slab(shape)
        self._vc = self._fresh_slab(shape)
        AllocationsTracker.get_instance().allocate("kv_slab",
                                                   self.kv_slab_bytes)
        # host scheduler state (worker thread owns mutation)
        self.pool = BlockPool(num_blocks, BS)
        self.metrics.num_blocks = self.pool.capacity
        self.metrics.block_size = BS
        self._slots = SlotAllocator(self.max_slots)
        self._slot_reqs: List[Optional[GenerationRequest]] = \
            [None] * self.max_slots
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._positions = np.zeros(self.max_slots, np.int32)
        self._active = np.zeros(self.max_slots, bool)
        self._tables = np.zeros((self.max_slots, self._maxb), np.int32)
        self._nblocks = np.zeros(self.max_slots, np.int32)
        disp = _paged_dispatchers(spec, shape, BS, self._maxb, mesh_key)
        self._decode_disp = disp["decode"]
        self._prefill_disp = disp["prefill"]
        self._verify_disp = disp.get("verify")

    def _fresh_slab(self, shape=None):
        import jax
        import jax.numpy as jnp
        if shape is None:
            shape = tuple(self._kc.shape)
        slab = jnp.zeros(shape, self._kv_dtype)
        if self._kv_sharding is not None:
            slab = jax.device_put(slab, self._kv_sharding)
        return slab

    # -- block-commitment admission (submit thread) ---------------------
    def _worst_case_blocks(self, prompt_len: int,
                           max_new_tokens: int) -> int:
        return blocks_for_tokens(
            min(int(prompt_len) + int(max_new_tokens), self.max_seq_len),
            self.block_size)

    def _uncommit(self, n: int) -> None:
        with self._commit_lock:
            self._committed -= int(n)

    def submit(self, prompt, max_new_tokens: int = 16,
               **kw) -> GenerationHandle:
        """:meth:`GenerativeServer.submit` plus block-pool admission:
        the request's WORST-CASE block footprint (prompt + full token
        budget) is reserved against pool capacity up front, so a placed
        request can never fail a block allocation mid-decode. A request
        the pool cannot ever hold alongside the committed load sheds
        typed — :class:`PoolExhaustedError` with a ``retry_after_s``
        backoff hint — instead of crashing a worker later. The
        reservation is released exactly once, whenever the request's
        future resolves (success, failure, timeout, shed, cancel, or a
        second-crash fail — every resolution path sets the future).

        Validation runs BEFORE the commitment: a request that could
        never run (empty/over-long/out-of-vocab prompt, zero token
        budget) raises its permanent ValueError even when the pool is
        fully committed, instead of masquerading as a retryable
        overload shed."""
        p = self._validate_submit(prompt, max_new_tokens)
        need = self._worst_case_blocks(p.size, max_new_tokens)
        with self._commit_lock:
            if self._committed + need > self.pool.capacity:
                self.metrics.inc("requests_submitted")
                self.metrics.inc("requests_shed")
                hint = (self.admission.retry_hint_s(
                            self._queue.pending() + 1)
                        if self.admission is not None else 0.25)
                raise PoolExhaustedError(
                    f"KV block pool cannot hold the request: needs "
                    f"{need} blocks worst-case, {self._committed} of "
                    f"{self.pool.capacity} already committed — shed at "
                    f"admission", retry_after_s=hint)
            self._committed += need
        try:
            handle = super().submit(p, max_new_tokens, **kw)
        except BaseException:
            self._uncommit(need)
            raise
        handle._req.future.add_done_callback(
            lambda _f, n=need: self._uncommit(n))
        return handle

    def _can_place(self, req: GenerationRequest) -> bool:
        """Step-boundary gate: hold a queued request at the FRONT until
        its prefill's blocks are actually free (free list + evictable
        cached blocks). The submit-side commitment makes this
        eventually true without failing anything."""
        need = blocks_for_tokens(int(req.prefix().size), self.block_size)
        return self.pool.usable_free_count() >= need

    # -- worker: prefill / decode / retire ------------------------------
    def _consume_prefix_flush(self) -> None:
        """Hot-reload fence, worker side: update_model() swapped the
        weights, so every cached block addresses K/V the OLD model
        computed. Consumed on the worker thread (which owns the pool)
        at every step boundary AND immediately before each prefill's
        cache lookup — the lookup check matters because ``_admit``
        blocks on the queue *inside* a step, so a request submitted
        after the reload can reach prefill before the next boundary.
        In-flight holders keep their refcounts and finish (the same
        accepted in-flight staleness as the dense update_model)."""
        if self._prefix_flush_pending.is_set():
            self._prefix_flush_pending.clear()
            self.pool.flush_cache()
            self.metrics.inc("prefix_cache_flushes")

    def _step(self, slot) -> bool:
        self._consume_prefix_flush()
        return super()._step(slot)

    def _prefill(self, s: int, req: GenerationRequest) -> None:
        prefix = req.prefix()
        L = int(prefix.size)
        if L > self.max_seq_len - 1:
            # crash-requeued request whose prefix already fills the
            # sequence: nothing left to decode
            self._retire(s)
            return
        BS = self.block_size
        hashes: List[bytes] = []
        hit: List[int] = []
        if self.prefix_cache_enabled:
            self._consume_prefix_flush()
            hashes = prefix_block_hashes(prefix, BS)
            # reuse is capped one block short of the full prefix: at
            # least one suffix token must run through prefill (the
            # logits at the LAST prompt position produce the first
            # generated token)
            hit = self.pool.lookup(hashes, max_blocks=(L - 1) // BS)
            self.metrics.observe_prefix(True, len(hit))
        hist = len(hit) * BS
        suffix = prefix[hist:]
        Ls = L - hist
        fresh: List[int] = []
        try:
            for _ in range(blocks_for_tokens(L, BS) - len(hit)):
                fresh.append(self.pool.alloc())
        except PoolExhaustedError:
            # roll back BOTH the fresh allocations and the cache-hit
            # retains — the request fails typed without leaking a block
            for b in fresh + hit:
                self.pool.release(b)
            raise
        blocks = hit + fresh
        self.metrics.observe_blocks(allocated=len(fresh))
        self._tables[s, :] = NULL_BLOCK
        self._tables[s, :len(blocks)] = blocks
        self._nblocks[s] = len(blocks)
        bucket = self._buckets.bucket_for(Ls)
        padded = np.zeros(bucket, np.int32)
        padded[:Ls] = suffix
        io = {"tokens": padded, "length": np.int32(Ls),
              "hist": np.int32(hist), "table": self._tables[s].copy()}
        t0 = time.perf_counter()
        out = self._dispatch(self._prefill_disp, io, "serving.prefill",
                             bucket=bucket, slot=s, hist=hist,
                             **_trace_args(req))
        tok = self._resolve_token(req, int(out[2]), out[3])
        self.metrics.observe_prefill((time.perf_counter() - t0) * 1000.0)
        if self.prefix_cache_enabled:
            # content-address the freshly FILLED full blocks (indices
            # [len(hit), L // BS) — the trailing partial block is still
            # being appended to and never registers)
            for u in range(len(hit), min(len(hashes), L // BS)):
                self.pool.register(hashes[u], int(blocks[u]))
        self._positions[s] = L
        self._tokens[s] = tok
        self._active[s] = True
        self._emit(s, req, tok)
        # the draft has no prefix cache: it prefills the FULL prefix
        # into its own dense slabs (base-class helper)
        self._draft_prefill(s, prefix, L)

    def _decode_once(self, slot) -> None:
        BS = self.block_size
        # block-table growth at the step boundary: a lane whose next
        # write position crosses into an unallocated block gets one.
        # The submit-side commitment guarantees this cannot fail for a
        # placed request; the typed retire is the defensive belt
        for s in np.flatnonzero(self._active):
            s = int(s)
            u = int(self._positions[s]) // BS
            if u >= int(self._nblocks[s]):
                try:
                    b = self.pool.alloc()
                except PoolExhaustedError as e:   # pragma: no cover
                    self._retire(s, error=e)
                    continue
                self._tables[s, u] = b
                self._nblocks[s] = u + 1
                self.metrics.observe_blocks(allocated=1)
        if not self._active.any():
            return
        n_active = self._n_active()
        act = self._active.copy()
        wb = np.full(self.max_slots, NULL_BLOCK, np.int32)
        wo = np.zeros(self.max_slots, np.int32)
        for s in np.flatnonzero(act):
            s = int(s)
            pos = int(self._positions[s])
            wb[s] = self._tables[s, pos // BS]
            wo[s] = pos % BS
        io = {"tokens": self._tokens.copy(),
              "positions": self._positions.copy(),
              "active": act,
              "tables": self._tables.copy(),
              "write_block": wb, "write_off": wo}
        t0 = time.perf_counter()
        _, _, nxt_d, logits_d = self._dispatch(self._decode_disp, io,
                                               "serving.decode",
                                               **self._batch_span_args(n_active))
        nxt = np.asarray(nxt_d)
        ms = (time.perf_counter() - t0) * 1000.0
        self.metrics.observe_decode_step(n_active, ms)
        self.metrics.observe_pool(self.pool.held_count(),
                                  stats=self.pool.stats())
        if self.admission is not None:
            self.admission.observe(ms)
        self._maybe_memory_record()
        lg = np.asarray(logits_d) if self._sampled_active() else None
        for s in np.flatnonzero(act):
            req = self._slot_reqs[int(s)]
            if req is None:
                continue
            s = int(s)
            tok = self._resolve_token(req, int(nxt[s]),
                                      lg[s] if lg is not None else None)
            self._positions[s] += 1
            self._tokens[s] = tok
            self._emit(s, req, tok)
        if self.debug_leaks:
            self.pool.check_invariant(tables=[
                self._tables[s, :int(self._nblocks[s])]
                for s in range(self.max_slots)
                if self._slot_reqs[s] is not None])

    # -- speculative decoding over the paged tier -----------------------
    def _spec_ready(self) -> bool:
        """Paged readiness additionally grows every active lane's block
        table UP FRONT to cover the verify window's live rows (those
        within the lane's remaining token budget — rows the submit-side
        worst-case commitment already reserved blocks for). If the pool
        defensively cannot (commitment math should make this
        impossible), the round falls back to plain single-step decode,
        whose one-block-at-a-time growth path handles it."""
        if not super()._spec_ready():
            return False
        BS = self.block_size
        W = self.speculate_k
        for s in np.flatnonzero(self._active):
            s = int(s)
            req = self._slot_reqs[s]
            rem = (req.max_new_tokens - len(req.generated)
                   if req is not None else 0)
            usable = min(W, max(rem, 0))
            if usable < 1:
                continue
            last = int(self._positions[s]) + usable - 1
            need = last // BS + 1
            while int(self._nblocks[s]) < need:
                try:
                    b = self.pool.alloc()
                except PoolExhaustedError:    # pragma: no cover
                    return False
                self._tables[s, int(self._nblocks[s])] = b
                self._nblocks[s] = int(self._nblocks[s]) + 1
                self.metrics.observe_blocks(allocated=1)
        return True

    def _verify_io(self, window: np.ndarray, positions: np.ndarray,
                   active: np.ndarray) -> dict:
        """Window write coordinates for the paged verify program:
        per-slot [S, W] (block, offset) pairs. Window rows beyond a
        lane's remaining token budget — writes no future step can ever
        read, because the lane retires exactly at its budget — are
        dumped to the null block, so speculation never writes a block
        the submit-side commitment didn't reserve. A rejected tail
        needs no rollback: the block-table cursor (``_nblocks``) only
        ever grew to committed rows, and positions simply do not
        advance over rejected columns."""
        BS = self.block_size
        S, W = window.shape
        wb = np.full((S, W), NULL_BLOCK, np.int32)
        wo = np.zeros((S, W), np.int32)
        for s in np.flatnonzero(active):
            s = int(s)
            req = self._slot_reqs[s]
            rem = (req.max_new_tokens - len(req.generated)
                   if req is not None else 0)
            usable = min(W, max(rem, 0))
            for j in range(usable):
                p = int(positions[s]) + j
                wb[s, j] = self._tables[s, p // BS]
                wo[s, j] = p % BS
        return {"tokens": window, "positions": positions.copy(),
                "active": active.copy(), "tables": self._tables.copy(),
                "write_block": wb, "write_off": wo}

    def _observe_round(self) -> None:
        self.metrics.observe_pool(self.pool.held_count(),
                                  stats=self.pool.stats())
        if self.debug_leaks:
            self.pool.check_invariant(tables=[
                self._tables[s, :int(self._nblocks[s])]
                for s in range(self.max_slots)
                if self._slot_reqs[s] is not None])

    def _retire(self, s: int, error: Optional[BaseException] = None,
                timed_out: bool = False, cancelled: bool = False) -> None:
        """Release slot ``s``'s blocks (decrementing shared prefix
        refcounts) exactly once, then the base retirement. Exactness
        rides the same free-list discipline as slots: a second release
        of any block raises in the pool."""
        req = self._slot_reqs[s]
        if req is not None:
            if (error is None and not cancelled
                    and self.prefix_cache_enabled and req.generated):
                self._register_generated(s, req)
            n = int(self._nblocks[s])
            for u in range(n):
                self.pool.release(int(self._tables[s, u]))
            self.metrics.observe_blocks(released=n)
            self.metrics.observe_request_blocks(n)
            self._tables[s, :] = NULL_BLOCK
            self._nblocks[s] = 0
        super()._retire(s, error=error, timed_out=timed_out,
                        cancelled=cancelled)

    def _register_generated(self, s: int, req) -> None:
        """Content-address the GENERATED span's full blocks at clean
        retirement, not just the prompt's (the prefill path already
        registered those): a resume-from-emitted-prefix continuation
        (fleet failover / journal replay) prefills ``prompt + emitted``
        and now hits cache over the whole already-decoded span. Must
        run BEFORE the release loop — registration requires the block
        held. Only blocks whose every position was written to KV
        qualify: the written region is ``[0, positions[s])`` (the final
        emitted token is never written back — the slot retires before
        its decode step), so exactly ``positions // block_size`` blocks
        are full. Blocks already registered (a prefill cache hit, or a
        concurrent fill of the same prefix) are left as-is."""
        BS = self.block_size
        n_full = min(int(self._positions[s]) // BS,
                     int(self._nblocks[s]))
        if n_full <= 0:
            return
        hashes = prefix_block_hashes(req.prefix(), BS, n_blocks=n_full)
        for u, h in enumerate(hashes):
            self.pool.register(h, int(self._tables[s, u]))

    def _reset_state(self) -> None:
        """Crash-recovery respawn: fresh slabs, a hard pool reset
        (every held block released ONCE, the prefix cache dropped — it
        content-addresses slab rows that are now garbage), clean
        tables. The requeued requests keep their submit-side block
        commitment (their futures are unresolved) and re-enter at
        prefill."""
        self._kc = self._fresh_slab()
        self._vc = self._fresh_slab()
        self._reset_draft_slabs()
        self.pool.reset()
        # the wholesale reset already dropped the prefix cache — a
        # pending hot-reload flush is thereby satisfied
        self._prefix_flush_pending.clear()
        self._slots.reset()
        self._slot_reqs = [None] * self.max_slots
        self._tokens[:] = 0
        self._positions[:] = 0
        self._active[:] = False
        self._tables[:] = NULL_BLOCK
        self._nblocks[:] = 0

    # -- AOT warmup -----------------------------------------------------
    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """Paged analogue of :meth:`GenerativeServer.warmup`: one
        decode shape + one prefill shape per bucket, lowered with the
        mesh shardings when ``tp > 1`` so the AOT executables match the
        live sharded arguments (a mismatch would silently fall back to
        lazy jit — the AOTDispatch ValueError path)."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.compilecache import (COMPILE_STATS,
                                                     install_compile_watcher)
        from deeplearning4j_tpu.environment import environment
        from deeplearning4j_tpu.monitor import memstats
        from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
        environment().apply_compilation_cache()
        install_compile_watcher()
        bucket_list = sorted({int(b) for b in buckets}) \
            if buckets is not None else list(self._buckets.buckets)

        def _abs(shape, dtype, sharding=None):
            if sharding is not None:
                return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                            sharding=sharding)
            return jax.ShapeDtypeStruct(tuple(shape), dtype)

        io_sh = self._io_sharding if self.tp > 1 else None
        params_abs = {
            n: _abs(np.shape(a), a.dtype,
                    self._strategy.param_sharding(n, np.ndim(a))
                    if self._strategy is not None else None)
            for n, a in self._params.items()}
        kv_abs = _abs(self._kc.shape, self._kc.dtype, self._kv_sharding)
        S, MAXB = self.max_slots, self._maxb
        mark = COMPILE_STATS.mark()
        t0 = _time.perf_counter()

        def _build(disp, io_abs, label, params_abs=params_abs,
                   kv_abs=kv_abs, role="target"):
            sig = ph_shape_sig(io_abs)
            with self._exec_lock:
                if sig not in disp.aot:
                    with _tracer.span("compile.precompile", cat="compile",
                                      target=label):
                        disp.aot[sig] = disp.lower(
                            params_abs, kv_abs, kv_abs, io_abs).compile()
                    memstats.capture_plan(label, sig,
                                          compiled=disp.aot[sig])
                if (role, sig) not in self._shapes_seen:
                    self._shapes_seen.add((role, sig))
                    self.metrics.inc("warmup_compiles")

        _build(self._decode_disp,
               {"tokens": _abs((S,), jnp.int32, io_sh),
                "positions": _abs((S,), jnp.int32, io_sh),
                "active": _abs((S,), jnp.bool_, io_sh),
                "tables": _abs((S, MAXB), jnp.int32, io_sh),
                "write_block": _abs((S,), jnp.int32, io_sh),
                "write_off": _abs((S,), jnp.int32, io_sh)},
               f"paged_decode_s{S}")
        for b in bucket_list:
            _build(self._prefill_disp,
                   {"tokens": _abs((int(b),), jnp.int32, io_sh),
                    "length": _abs((), jnp.int32, io_sh),
                    "hist": _abs((), jnp.int32, io_sh),
                    "table": _abs((MAXB,), jnp.int32, io_sh)},
                   f"paged_prefill_b{int(b)}")
        if self.draft_spec is not None:
            W = self.speculate_k
            _build(self._verify_disp,
                   {"tokens": _abs((S, W), jnp.int32, io_sh),
                    "positions": _abs((S,), jnp.int32, io_sh),
                    "active": _abs((S,), jnp.bool_, io_sh),
                    "tables": _abs((S, MAXB), jnp.int32, io_sh),
                    "write_block": _abs((S, W), jnp.int32, io_sh),
                    "write_off": _abs((S, W), jnp.int32, io_sh)},
                   f"paged_verify_s{S}w{W}")
            # the draft runs DENSE and unsharded, whatever the target's
            # layout — its abstract args carry no mesh shardings
            dparams_abs = {
                n: _abs(np.shape(a), np.asarray(a).dtype)
                for n, a in self._draft_params.items()}
            dkv_abs = _abs(self._dkc.shape, self._dkc.dtype)
            _build(self._draft_decode_disp,
                   {"tokens": _abs((S,), jnp.int32),
                    "positions": _abs((S,), jnp.int32),
                    "active": _abs((S,), jnp.bool_)},
                   f"draft_decode_s{S}", params_abs=dparams_abs,
                   kv_abs=dkv_abs, role="draft")
            for b in bucket_list:
                _build(self._draft_prefill_disp,
                       {"tokens": _abs((int(b),), jnp.int32),
                        "length": _abs((), jnp.int32),
                        "slot": _abs((), jnp.int32)},
                       f"draft_prefill_b{int(b)}", params_abs=dparams_abs,
                       kv_abs=dkv_abs, role="draft")
        self.warmup_report = {
            "decode_slots": S,
            "prefill_buckets": bucket_list,
            "speculative": self.draft_spec is not None,
            "seconds": round(_time.perf_counter() - t0, 4),
            **{k: v for k, v in COMPILE_STATS.delta(mark).items()
               if k in ("backend_compiles", "cache_hits",
                        "cache_misses")}}
        return self.warmup_report

    def update_model(self) -> None:
        """Re-pull trained parameters; under ``tp > 1`` the fresh
        arrays are re-placed onto the mesh with the same shardings.

        Also fences the prefix cache: cached blocks are
        content-addressed by token ids alone, but their K/V were
        computed with the weights being replaced — reusing them would
        silently mix old-model keys/values with the new model for
        every repeated prefix. The pool is worker-thread-owned, so the
        flush is flagged here and consumed at the next step boundary
        (:meth:`_step`): evictable cached blocks return to the free
        list, held shared blocks just lose their registration so
        in-flight requests finish (dense's accepted staleness
        window)."""
        fresh = dict(self.spec.params())
        if self._strategy is not None:
            import jax
            fresh = {n: jax.device_put(
                         a, self._strategy.param_sharding(n, np.ndim(a)))
                     for n, a in fresh.items()}
        with self._exec_lock:
            self._params = fresh
        self._refresh_draft_params()
        self._prefix_flush_pending.set()

    def restore_params(self, params: dict) -> None:
        """Fleet-deploy rollback: install a ``params_snapshot()`` and
        fence the prefix cache exactly as :meth:`update_model` does —
        cached K/V were computed with the weights being replaced in
        EITHER direction of a swap."""
        super().restore_params(params)
        self._prefix_flush_pending.set()

    # -- observability --------------------------------------------------
    def _telemetry_load(self, depth: int, active: int) -> dict:
        load = super()._telemetry_load(depth, active)
        # capacity on the paged path is blocks held, not slots filled —
        # a router balancing on occupancy must see pool pressure
        load["pool_occupancy"] = round(
            self.pool.held_count() / self.pool.capacity, 4) \
            if self.pool.capacity else 0.0
        load["blocks_committed"] = self._committed
        return load

    def memory_report(self) -> dict:
        """Pool accounting for /memory + capacity planning — block
        granularity instead of the dense per-slot rows."""
        st = self.pool.stats()
        return {"kv_slab_bytes": self.kv_slab_bytes,
                "kv_slab_shape": list(self._kc.shape),
                "kv_bytes_per_block": self.bytes_per_block,
                "block_size": self.block_size,
                "num_blocks": self.pool.capacity,
                "blocks_free": st["free"],
                "blocks_held": st["held"],
                "blocks_evictable": st["evictable"],
                "blocks_cached": st["cached"],
                "blocks_committed": self._committed,
                "pool_evictions": st["evictions"],
                "tensor_parallel": self.tp,
                "max_slots": self.max_slots,
                "max_seq_len": self.max_seq_len,
                "active_slots": self._n_active()}


__all__ = ["PagedGenerativeSpec", "PagedGenerativeServer", "PagedMetrics"]
