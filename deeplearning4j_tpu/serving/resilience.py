"""Serving resilience: the detect → decide → recover rail for inference.

PR 4 gave *training* a structured fault rail (sentinel → rollback →
retry, docs/fault_tolerance.md); this module gives `ParallelInference`
the serving-side analogue, following the admission/shedding patterns of
SLO-aware serving systems (clipper-style deadline admission, orca-style
batch scheduling — PAPERS.md):

- :class:`AdmissionController` — **SLO admission control**. A request
  with a deadline is rejected at ``submit()`` when its estimated queue
  wait (pending batches ahead × rolling p95 exec time, tracked with
  :class:`~deeplearning4j_tpu.monitor.steptime.RollingPercentiles`)
  already exceeds the deadline: a doomed request is shed with a
  structured ``ServerOverloadedError(retry_after_s=...)`` instead of
  occupying queue space until it expires (the classic "fail fast at
  admission" rule).
- :class:`CircuitBreaker` — closed / open / half-open on consecutive
  exec failures. Open sheds new submits (``retry_after_s`` = time until
  the next probe window) and pauses dispatch; after ``reset_timeout_s``
  ONE probe batch goes through half-open — success closes the breaker,
  failure re-opens it. State is surfaced through ``/healthz``/``/readyz``
  (the server's telemetry health provider) and ``{"type": "faults"}``
  records, so the documented 200→503→200 transition is observable.
- :class:`WorkerSupervisor` — worker threads are supervised, not
  immortal-by-guard: a crashed worker is restarted with bounded
  exponential backoff, its in-flight requests are requeued **exactly
  once** (a request lost to two crashes fails its future instead of
  ping-ponging), and every decision lands on the PR 4 fault rail as a
  ``{"type": "faults"}`` record.
- **Poisoned-batch isolation** (driven from ``inference.py``): a failed
  batched exec — a raise, or a non-finite output row — is *bisected*:
  halves are retried, then singles, so exactly the poisoned request is
  quarantined with :class:`PoisonedRequestError` while every co-batched
  healthy request still gets its bit-identical answer (row ``i`` of a
  batched forward does not depend on row ``j``; the healthy sub-group's
  re-exec is the same program at a bucket shape).
- **Checkpoint-driven hot reload** (``ParallelInference.reload_from``):
  swap serving parameters to a committed ``CheckpointManager`` step
  between batches, canary-exec a golden input, and roll back to the
  previous parameters automatically if the canary produces non-finite
  outputs (:class:`ReloadFailedError`) — a serving process follows
  training without a restart.

See docs/serving.md ("Resilience") for the contract and the math.
"""
from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, List, Optional

from deeplearning4j_tpu.monitor.steptime import RollingPercentiles

#: breaker states, in escalation order (exported for dashboards:
#: fold_serving maps them onto the ``dl4j_serving_breaker_state`` gauge)
BREAKER_STATES = ("closed", "half_open", "open")


class ServingError(RuntimeError):
    """Base class for typed serving failures. Defined here (the
    resilience contract module) and re-exported by ``serving.queue``,
    which historically owned it — both import paths stay valid."""


#: wire-kind registry: class-name -> exception class, populated by
#: ``RetryableServingError.__init_subclass__`` so every typed shed in
#: the process round-trips through :meth:`RetryableServingError.from_wire`
#: to its concrete class. Unknown kinds (a newer replica's error type)
#: fall back to the base — the retry semantics survive even when the
#: specific subclass does not.
_WIRE_KINDS: dict = {}


class RetryableServingError(ServingError):
    """A typed, *retryable* shed: the request was rejected by a
    transient capacity condition (full queue, exhausted block pool,
    open breaker, SLO admission), not by anything wrong with the
    request itself. ``retry_after_s`` — when set — is the structured
    backoff hint: how long the shedding condition is expected to
    persist.

    This class is the routing contract the fleet tier keys on: a
    front door retries anything ``isinstance(e, RetryableServingError)``
    (honoring the hint) and never retries permanent ``ValueError``s.
    :meth:`to_wire`/:meth:`from_wire` round-trip the error as a plain
    dict so a router can transport a shed across a process boundary
    without losing its type or its ``retry_after_s``."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        _WIRE_KINDS[cls.__name__] = cls

    def to_wire(self) -> dict:
        """Serialize to a plain dict: ``{"kind", "message",
        "retry_after_s"}`` — everything a remote caller needs to back
        off correctly."""
        return {"kind": type(self).__name__,
                "message": str(self),
                "retry_after_s": self.retry_after_s}

    @staticmethod
    def from_wire(d: dict) -> "RetryableServingError":
        """Reconstruct a typed shed from :meth:`to_wire` output. The
        concrete class is looked up by ``kind``; an unknown kind
        deserializes as the base class so cross-version fleets still
        agree on "retryable with this hint"."""
        cls = _WIRE_KINDS.get(str(d.get("kind", "")), RetryableServingError)
        hint = d.get("retry_after_s")
        return cls(str(d.get("message", "")),
                   retry_after_s=None if hint is None else float(hint))


class PoisonedRequestError(ServingError):
    """This request's input makes the model fail or produce non-finite
    outputs — it was quarantined by the bisecting dispatcher instead of
    failing its co-batched neighbours. ``request_id`` names the request;
    ``__cause__`` (when set) is the exec error the bisection isolated."""

    def __init__(self, message: str, request_id: Optional[int] = None):
        super().__init__(message)
        self.request_id = request_id


class ReloadFailedError(ServingError):
    """``reload_from()`` could not safely swap parameters. When
    ``rolled_back`` is True the previous parameters were restored and
    the server keeps serving exactly what it served before the attempt;
    ``report`` carries the machine-readable reload accounting."""

    def __init__(self, message: str, report: Optional[dict] = None,
                 rolled_back: bool = False):
        super().__init__(message)
        self.report = dict(report or {})
        self.rolled_back = rolled_back


@dataclass
class ResilienceConfig:
    """Knobs for the serving resilience rail (``ParallelInference
    (resilience=...)``; ``True`` means this default config).

    - ``admission``: shed deadline-carrying requests whose estimated
      wait (queued batches ahead × rolling ``percentile`` exec time)
      already exceeds their deadline. Estimation starts after
      ``min_exec_samples`` observed execs (cold servers never shed on
      garbage estimates); ``window`` bounds the rolling sample.
    - ``breaker_failure_threshold``: consecutive exec failures that
      open the circuit (0 disables the breaker);
      ``breaker_reset_s``: open → half-open probe delay.
    - ``supervise``: run workers under a :class:`WorkerSupervisor`.
      ``worker_max_consecutive_errors`` unexpected worker-loop errors
      kill the worker (the supervisor restarts it with backoff between
      ``worker_backoff_base_s`` and ``worker_backoff_max_s``).
    - ``isolate_poisoned``: bisect failed batched execs down to the
      poisoned request; ``check_finite_outputs`` extends "failed" to
      any non-finite output row (how a NaN input actually manifests —
      XLA does not raise on it); ``single_retries``: extra attempts a
      lone *raising* request gets before it is declared poisoned
      (absorbs a transient exec fault landing on a singleton; a
      non-finite output is deterministic and is quarantined at once).
    """

    admission: bool = True
    min_exec_samples: int = 8
    percentile: float = 95.0
    window: int = 256
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 2.0
    supervise: bool = True
    worker_backoff_base_s: float = 0.05
    worker_backoff_max_s: float = 2.0
    worker_max_consecutive_errors: int = 3
    isolate_poisoned: bool = True
    check_finite_outputs: bool = True
    single_retries: int = 1

    @staticmethod
    def normalize(value) -> Optional["ResilienceConfig"]:
        """None/False → None (rail off); True → defaults; a config
        passes through."""
        if value is None or value is False:
            return None
        if value is True:
            return ResilienceConfig()
        if isinstance(value, ResilienceConfig):
            return value
        raise TypeError(f"resilience= expects None/bool/ResilienceConfig, "
                        f"got {type(value).__name__}")


class CircuitBreaker:
    """Closed / open / half-open breaker over consecutive exec failures.

    Thread-safe; transitions invoke ``on_transition(old, new)`` OUTSIDE
    the internal lock (the callback publishes records / pokes metrics
    and must not deadlock against probes). ``clock`` is injectable for
    deterministic tests.
    """

    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 2.0,
                 on_transition: Optional[Callable[[str, str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold <= 0:
            raise ValueError("failure_threshold must be positive")
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout_s = float(reset_timeout_s)
        self.on_transition = on_transition
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._consecutive = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_locked(self, new: str) -> Optional[tuple]:
        old = self._state
        if old == new:
            return None
        self._state = new
        return (old, new)

    def _notify(self, transition: Optional[tuple]) -> None:
        if transition is not None and self.on_transition is not None:
            self.on_transition(*transition)

    # -- submit side ----------------------------------------------------
    def reject_for(self) -> Optional[float]:
        """Seconds a new submit should back off, or None to admit.
        Open rejects until the probe window; half-open admits (the
        queued request is what the probe will serve)."""
        with self._lock:
            if self._state != "open":
                return None
            remaining = self.reset_timeout_s - (self._clock()
                                                - self._opened_at)
            if remaining > 0:
                return remaining
            return None          # probe window reached: admit

    # -- dispatch side --------------------------------------------------
    def acquire(self):
        """Worker gate before popping a batch: returns
        ``(allowed, wait_s)``. Open → ``(False, seconds-until-probe)``;
        the FIRST caller after the reset timeout transitions to
        half-open and owns the probe (others keep waiting). A caller
        that acquired but dispatched nothing must :meth:`release`."""
        transition = None
        try:
            with self._lock:
                if self._state == "closed":
                    return True, 0.0
                now = self._clock()
                if self._state == "open":
                    remaining = self.reset_timeout_s - (now - self._opened_at)
                    if remaining > 0:
                        return False, remaining
                    transition = self._set_locked("half_open")
                    self._probe_inflight = True
                    return True, 0.0
                # half-open: exactly one probe at a time
                if not self._probe_inflight:
                    self._probe_inflight = True
                    return True, 0.0
                return False, 0.05
        finally:
            self._notify(transition)

    def release(self) -> None:
        """Give back an acquired probe that dispatched nothing."""
        with self._lock:
            if self._state == "half_open":
                self._probe_inflight = False

    # -- outcomes -------------------------------------------------------
    def on_success(self) -> None:
        transition = None
        with self._lock:
            self._consecutive = 0
            if self._state == "half_open":
                self._probe_inflight = False
                transition = self._set_locked("closed")
        self._notify(transition)

    def on_failure(self) -> None:
        transition = None
        with self._lock:
            self._consecutive += 1
            if self._state == "half_open":
                self._probe_inflight = False
                self._opened_at = self._clock()
                transition = self._set_locked("open")
            elif self._state == "closed" and \
                    self._consecutive >= self.failure_threshold:
                self._opened_at = self._clock()
                transition = self._set_locked("open")
        self._notify(transition)


class AdmissionController:
    """SLO admission math: estimated queue wait from a rolling exec-time
    percentile.

    ``observe(exec_ms)`` feeds every dispatch's exec time;
    ``estimate_wait_ms(pending_rows, rows_per_dispatch)`` returns the
    expected wall wait for a request behind ``pending_rows`` queued rows
    (including its own) on a serially-executing device:
    ``ceil(pending_rows / rows_per_dispatch) × p<percentile>(exec_ms)``
    — or None while fewer than ``min_samples`` execs have been seen
    (no shedding on a cold estimator)."""

    def __init__(self, window: int = 256, percentile: float = 95.0,
                 min_samples: int = 8):
        self.percentile = float(percentile)
        self.min_samples = int(min_samples)
        self._pcts = RollingPercentiles(window=int(window))
        self._lock = threading.Lock()

    def observe(self, exec_ms: float) -> None:
        with self._lock:
            self._pcts.add(float(exec_ms))

    def exec_ms(self, p: Optional[float] = None) -> float:
        with self._lock:
            return self._pcts.percentile(self.percentile if p is None
                                         else p)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pcts)

    def estimate_wait_ms(self, pending_rows: int,
                         rows_per_dispatch: int) -> Optional[float]:
        with self._lock:
            if len(self._pcts) < self.min_samples:
                return None
            dispatches = math.ceil(max(0, int(pending_rows))
                                   / max(1, int(rows_per_dispatch)))
            return dispatches * self._pcts.percentile(self.percentile)

    def retry_hint_s(self, pending_rows: int = 1,
                     rows_per_dispatch: int = 1,
                     floor_s: float = 0.05) -> float:
        """Backoff hint (seconds) for a typed capacity shed — the
        ``retry_after_s`` a ``ServerOverloadedError`` (queue full, KV
        block pool exhausted) carries to the client. Derived from the
        rolling exec percentile when warm, clamped to ``floor_s`` so a
        cold estimator still tells clients to back off rather than
        hot-loop."""
        est = self.estimate_wait_ms(pending_rows, rows_per_dispatch)
        if est is None:
            return float(floor_s)
        return round(max(float(floor_s), est / 1000.0), 3)


class InflightSlot:
    """Per-worker visibility into popped-but-unresolved requests — what
    the supervisor requeues when the worker dies mid-dispatch. Plain
    attribute assignment (atomic under the GIL); the supervisor only
    reads it after the owning thread is dead."""

    def __init__(self):
        self.requests: Optional[List] = None
        self.exited = False             # clean loop return (don't restart)
        self.crashed: Optional[BaseException] = None
        self.progressed = False         # served at least one dispatch —
        #                                 the supervisor's evidence for
        #                                 resetting the crash-streak
        #                                 backoff (mere liveness is not)


class WorkerSupervisor:
    """Restarts crashed serving workers with bounded backoff and
    requeues their in-flight requests exactly once.

    ``spawn(index, slot)`` must create AND start a worker thread running
    the serving loop with ``slot`` as its in-flight window. The
    supervisor polls thread liveness; a dead thread whose slot is not
    ``exited`` is a crash: its in-flight requests are requeued (a
    request already requeued once fails its future — no infinite
    ping-pong), a ``{"type": "faults"}`` ``fault`` record is published,
    the worker is respawned after bounded exponential backoff, and a
    ``recovered`` record closes the episode (the /healthz 503 window).
    """

    def __init__(self, spawn: Callable[[int, InflightSlot], threading.Thread],
                 n_workers: int, queue, metrics,
                 backoff_base_s: float = 0.05, backoff_max_s: float = 2.0,
                 poll_s: float = 0.02,
                 publish: Optional[Callable[..., None]] = None,
                 on_crash: Optional[Callable[[], None]] = None):
        self._spawn = spawn
        self._queue = queue
        self._metrics = metrics
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.poll_s = float(poll_s)
        self._publish = publish or (lambda event, **kw: None)
        # run per crash BEFORE requeue — the server uses it to release
        # a half-open breaker probe the dead worker may have been
        # holding (a leaked probe would gate dispatch forever)
        self._on_crash = on_crash or (lambda: None)
        self._stopping = False
        self._lock = threading.Lock()
        self._entries: List[dict] = []
        for i in range(max(1, int(n_workers))):
            slot = InflightSlot()
            self._entries.append({"index": i, "slot": slot,
                                  "thread": self._spawn(i, slot),
                                  "restarts": 0, "consecutive": 0})
        self.restarts_total = 0
        self._thread = threading.Thread(target=self._run,
                                        name="ServingSupervisor",
                                        daemon=True)
        self._thread.start()

    @property
    def threads(self) -> List[threading.Thread]:
        with self._lock:
            return [e["thread"] for e in self._entries]

    # ------------------------------------------------------------------
    def _requeue(self, reqs: List) -> None:
        _SE = ServingError
        # reversed: requeue() puts each at the FRONT, so walking newest-
        # first leaves the queue in the original FIFO order (oldest at
        # the head, keeping its deadline odds)
        for req in reversed(reqs or []):
            if req.future.done():
                continue
            if getattr(req, "requeues", 0) >= 1:
                # exactly-once: a request that already survived one
                # crash does not get a third dispatch
                err = _SE(f"request {req.id} lost to a crashed worker "
                          f"twice; giving up")
                req.fail(err)
                self._metrics.record_failure(err, cause="worker_crash")
                continue
            req.requeues = getattr(req, "requeues", 0) + 1
            try:
                self._queue.requeue(req)
                self._metrics.inc("requests_requeued")
            except Exception as e:        # closed non-drain queue
                req.fail(e)

    def _handle_crash(self, entry: dict) -> None:
        slot: InflightSlot = entry["slot"]
        inflight = list(slot.requests or [])
        err = slot.crashed
        self._metrics.inc("worker_restarts")
        self.restarts_total += 1
        entry["consecutive"] += 1
        entry["restarts"] += 1
        self._publish("fault", cause="worker_crash",
                      worker=entry["index"],
                      error=repr(err) if err is not None else None,
                      inflight=len(inflight))
        try:
            self._on_crash()
        except Exception:       # noqa: BLE001 — recovery must proceed
            pass
        self._requeue(inflight)
        backoff = min(self.backoff_max_s,
                      self.backoff_base_s * (2 ** (entry["consecutive"] - 1)))
        deadline = time.monotonic() + backoff
        while time.monotonic() < deadline and not self._stopping:
            time.sleep(min(self.poll_s, 0.01))
        if self._stopping:
            return
        new_slot = InflightSlot()
        entry["slot"] = new_slot
        entry["thread"] = self._spawn(entry["index"], new_slot)
        self._publish("recovered", cause="worker_restart",
                      worker=entry["index"], restarts=entry["restarts"],
                      backoff_s=round(backoff, 4))

    def _run(self) -> None:
        while not self._stopping:
            with self._lock:
                entries = list(self._entries)
            for entry in entries:
                t, slot = entry["thread"], entry["slot"]
                if t.is_alive():
                    if entry["consecutive"] and slot.progressed:
                        # the restarted worker actually SERVED work —
                        # its crash streak is over (mere liveness is
                        # not evidence: a crash-looping worker is alive
                        # for a few guard sleeps before re-dying, and
                        # resetting on that would pin the backoff at
                        # its base forever)
                        entry["consecutive"] = 0
                    continue
                if slot.exited or self._stopping:
                    continue
                self._handle_crash(entry)
            if self._queue.finished and all(
                    not e["thread"].is_alive() for e in entries):
                return
            time.sleep(self.poll_s)

    # ------------------------------------------------------------------
    def stop(self, timeout: Optional[float] = None) -> None:
        """Stop restarting, join the supervisor and every worker. Call
        AFTER closing the queue (workers exit on drain completion)."""
        self._stopping = True
        self._thread.join(timeout=timeout if timeout is not None else 10.0)
        for t in self.threads:
            t.join(timeout=timeout)


__all__ = ["AdmissionController", "BREAKER_STATES", "CircuitBreaker",
           "InflightSlot", "PoisonedRequestError", "ReloadFailedError",
           "ResilienceConfig", "RetryableServingError", "ServingError",
           "WorkerSupervisor"]
