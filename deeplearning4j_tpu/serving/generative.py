"""Continuous-batching generative serving: slotted KV caches,
step-boundary admission, streaming decode.

The decoder-LM serving tier (ROADMAP item 1): ``ParallelInference``
batches fixed-shape forwards, but an autoregressive request is a LOOP —
one token per model invocation, sequence lengths unknown in advance. A
static batcher ("wait for a full batch, run it to completion") lets one
long generation hold every co-batched short request hostage and leaves
finished slots idle; the mechanism proven by Orca's iteration-level
scheduling (Yu et al., OSDI '22) and vLLM's slot-based KV memory (Kwon
et al., SOSP '23) is to keep the decode batch full by admitting new
requests **at step boundaries** into preallocated KV slots:

- **KV slabs** — two HBM arrays (K and V), shaped
  ``[layers, max_slots, heads, max_seq, head_dim]``, allocated ONCE at
  construction (headroom-guarded via ``monitor/memstats``) and donated
  through every dispatch so the cache is updated in place — no
  per-request allocation, no fragmentation.
- **ONE decode program** — a single jitted step advances *all* active
  slots per dispatch (active-slot mask + per-slot position indices);
  its shapes never change, so the decode path compiles exactly once.
- **pow2 prefill buckets** — a new request's prompt runs through a
  bucket-padded prefill program that fills its slot's KV rows and emits
  the first token (TTFT = queue wait + one prefill); the bucket ladder
  reuses ``serving/batching.py``'s machinery, so mixed prompt lengths
  cost ≤ log2(max_seq) compiled shapes.
- **continuous batching** — the scheduler admits queued requests into
  free slots at every step boundary, streams each token to its
  request's iterator/callback as it resolves, and retires finished
  slots (EOS / ``max_new_tokens`` / deadline / cancel / sequence
  capacity) immediately, so the next queued request starts on the very
  next step.
- **SLO admission** — a rolling p99 of decode-step time
  (``serving/resilience.AdmissionController``) turns queue depth into a
  TTFT estimate; a deadline-carrying request that cannot make it is
  shed typed (``ServerOverloadedError(retry_after_s=...)``) before it
  occupies a slot.
- **crash recovery** — the decode worker runs under the PR-9
  ``WorkerSupervisor``: a crashed worker's in-flight generations are
  requeued at the FRONT exactly once and re-enter at prefill with
  ``prompt + tokens-generated-so-far`` (greedy decode is deterministic,
  so the continuation matches; already-streamed tokens are not
  re-streamed), and the respawned worker starts from fresh slabs.

Correctness contract (tests/test_generative.py): greedy tokens are
identical to :func:`greedy_decode` (the unbatched single-request
reference) for every request in a mixed-length run; a retired slot's
cache — even poisoned with NaNs — can never influence its successor
(masked positions have their V rows zeroed *under the mask*, see
``zoo/gpt.py gpt_decode_fns``), so slot reuse is bit-exact vs a fresh
server. See docs/serving.md "Generative serving".
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from queue import SimpleQueue
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.compilecache.aot import AOTDispatch, ph_shape_sig
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.serving.batching import BucketSpec, pow2_buckets
from deeplearning4j_tpu.serving.metrics import (LatencyHistogram,
                                                ServingMetrics, safe_ratio)
from deeplearning4j_tpu.serving.sampling import sample_token
from deeplearning4j_tpu.serving.queue import (
    InferenceRequest, RequestQueue, ServerClosedError, ServerOverloadedError,
    ServingError, ServingTimeoutError)
from deeplearning4j_tpu.serving.resilience import (AdmissionController,
                                                   InflightSlot,
                                                   ResilienceConfig,
                                                   WorkerSupervisor)


class GenerationCancelled(ServingError):
    """The request was cancelled by its client; ``tokens`` holds what
    was generated before the cancel took effect at a step boundary."""

    def __init__(self, message: str, tokens: Optional[List[int]] = None):
        super().__init__(message)
        self.tokens = list(tokens or [])


@dataclass
class GenerativeSpec:
    """A model's generative-serving contract — the decode-mode analogue
    of :class:`~deeplearning4j_tpu.serving.inference.ServingSpec`
    (produced by e.g. ``zoo.gpt.gpt_generative_spec``).

    - ``params()`` pulls the current trained parameter arrays (by-name
      sync from the training graph; ``GenerativeServer.update_model()``
      re-pulls).
    - ``prefill(params, kc, vc, io)`` with ``io = {"tokens": [L] int32,
      "length": (), "slot": ()}`` fills slot ``io["slot"]``'s KV rows
      from a bucket-padded prompt and returns
      ``(kc, vc, next_token, last_logits)``.
    - ``decode(params, kc, vc, io)`` with ``io = {"tokens": [S],
      "positions": [S], "active": [S] bool}`` advances every active
      slot one token and returns ``(kc, vc, next_tokens, logits)``.
    - ``kv_shape(max_slots, max_seq)`` is the shape of ONE slab (K and
      V are two arrays of this shape).
    - ``verify`` (optional) scores a K-token window per slot in one
      dispatch for speculative decoding: ``io = {"tokens": [S, W],
      "positions": [S], "active": [S] bool}`` returns ``(kc, vc,
      out_tokens [S, W], logits [S, W, vocab])`` where ``out[s, j]`` is
      the greedy token after consuming window columns ``0..j`` —
      column 0 is the slot's last emitted token, so ``out[s, 0]`` is
      bit-identical to what ``decode`` would have produced.

    All functions must be pure and shape-static so the server can jit
    them with donated slabs and AOT-precompile every shape it will ever
    dispatch (docs/cold_start.md).
    """

    params: Callable[[], Dict[str, object]]
    prefill: Callable
    decode: Callable
    kv_shape: Callable[[int, int], tuple]
    vocab_size: int
    max_seq_len: int
    kv_dtype: str = "float32"
    eos_id: Optional[int] = None
    verify: Optional[Callable] = None


class SlotAllocator:
    """Free-list allocator over ``n`` KV slots. ``free()`` of a slot
    that is not currently allocated raises — the slot-lifecycle
    invariant ("freed exactly once") is enforced here, not hoped for."""

    def __init__(self, n: int):
        if n <= 0:
            raise ValueError("need at least one slot")
        self.n = int(n)
        self._free = list(range(self.n - 1, -1, -1))   # pop() -> slot 0 first
        self._inuse: set = set()

    def alloc(self) -> int:
        if not self._free:
            raise RuntimeError("no free slots")
        s = self._free.pop()
        self._inuse.add(s)
        return s

    def free(self, s: int) -> None:
        if s not in self._inuse:
            raise RuntimeError(f"slot {s} freed twice (or never allocated)")
        self._inuse.discard(s)
        self._free.append(s)

    def free_count(self) -> int:
        return len(self._free)

    def in_use(self) -> set:
        return set(self._inuse)

    def reset(self) -> None:
        self._free = list(range(self.n - 1, -1, -1))
        self._inuse.clear()


_STREAM_DONE = object()


def _trace_args(req: "GenerationRequest") -> dict:
    """The span args tying a per-request serving span to its fleet
    trace — empty for untraced requests, so local (non-fleet) traffic
    records byte-identical spans to the pre-tracing tier."""
    if req.trace_id is None:
        return {}
    return {"trace_id": req.trace_id, "segment": req.trace_seg}


@dataclass
class GenerationRequest(InferenceRequest):
    """One queued generation: prompt + budget + the per-token stream.
    Rides the existing :class:`RequestQueue` (deadlines expire queued
    requests, ``requeue`` puts crash-recovered ones back at the front)
    and the :class:`WorkerSupervisor`'s exactly-once requeue contract
    (``requeues``)."""

    prompt: np.ndarray = None
    max_new_tokens: int = 16
    eos_id: Optional[int] = None
    on_token: Optional[Callable[[int], None]] = None
    # sampling knobs: temperature 0 = exact greedy (device argmax);
    # otherwise serving/sampling.py draws from the target logits with
    # the (seed, absolute-token-index) fold — reproducible per request
    # whatever shares the batch, including after a crash requeue
    temperature: float = 0.0
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: Optional[int] = None
    # request tracing (monitor/reqtrace.py): the fleet-wide trace id +
    # segment this attempt serves under, snapshotted at submit; tags
    # every serving.* span the request touches. None = untraced (the
    # spans carry no trace args, exactly the pre-tracing shape)
    trace_id: Optional[int] = None
    trace_seg: int = 0
    generated: List[int] = field(default_factory=list)
    cancelled: bool = False
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    _stream: SimpleQueue = field(default_factory=SimpleQueue)

    def prefix(self) -> np.ndarray:
        """Prompt + tokens generated so far — what a crash-requeued
        request re-prefills with (greedy decode is deterministic, so
        the continuation is the one the dead worker would have
        produced; already-streamed tokens are not re-emitted)."""
        if not self.generated:
            return np.asarray(self.prompt, np.int32)
        return np.concatenate([np.asarray(self.prompt, np.int32),
                               np.asarray(self.generated, np.int32)])

    # stream closure rides every resolution path (success, failure,
    # queued-deadline expiry) so a consumer iterating tokens() can
    # never hang on a finished request
    def close_stream(self, error: Optional[BaseException] = None) -> None:
        self._stream.put((_STREAM_DONE, error))

    def emit(self, token: int) -> None:
        self.generated.append(int(token))
        self._stream.put((int(token), None))

    def succeed(self) -> None:
        if not self.future.done():
            self.future.set_result(list(self.generated))
        self.close_stream()

    def fail(self, exc: BaseException) -> None:
        super().fail(exc)
        self.close_stream(exc)

    def time_out(self) -> None:
        super().time_out()
        self.close_stream(self.future.exception()
                          if self.future.done() else None)


class GenerationHandle:
    """Client view of one generation: a Future of the full token list
    plus a streaming iterator of tokens as they resolve."""

    def __init__(self, req: GenerationRequest):
        self._req = req
        self.future = req.future

    @property
    def id(self) -> int:
        return self._req.id

    def result(self, timeout: Optional[float] = None) -> List[int]:
        return self.future.result(timeout)

    def partial(self) -> List[int]:
        """Tokens generated so far (snapshot)."""
        return list(self._req.generated)

    def cancel(self) -> None:
        """Request cancellation; takes effect at the next step boundary
        (the slot is freed, the future resolves to the partial token
        list, the stream closes cleanly)."""
        self._req.cancelled = True

    def tokens(self, timeout: Optional[float] = None):
        """Iterate tokens as they are generated. Raises the request's
        failure (deadline, crash, ...) at the point the stream closed
        on it; a clean finish (EOS/max_new_tokens/cancel) just ends
        the iteration. ``timeout`` bounds the wait for EACH token: a
        gap longer than that raises the builtin :class:`TimeoutError`
        (the generation itself is unaffected — iterating again resumes
        from the next undelivered token)."""
        from queue import Empty
        while True:
            try:
                token, err = self._req._stream.get(timeout=timeout)
            except Empty:
                raise TimeoutError(
                    f"no token from generation {self._req.id} within "
                    f"{timeout}s (the request is still in flight; "
                    f"re-iterate to resume the stream)") from None
            if token is _STREAM_DONE:
                if err is not None and \
                        not isinstance(err, GenerationCancelled):
                    raise err
                return
            yield token

    def __iter__(self):
        return self.tokens()


class GenerativeMetrics(ServingMetrics):
    """ServingMetrics plus the generative lanes: TTFT (submit → first
    streamed token), inter-token latency, prefill time, token/step
    counters and slot occupancy. The extra counters/lanes export
    through the existing generic folds (``fold_serving`` →
    ``dl4j_serving_*``) without new record types."""

    def __init__(self, max_slots: int = 0):
        super().__init__()
        self.max_slots = int(max_slots)
        self.ttft_ms = LatencyHistogram()
        self.intertoken_ms = LatencyHistogram()
        self.prefill_ms = LatencyHistogram()
        for c in ("tokens_generated", "prefills", "decode_steps",
                  "slots_active_sum", "requests_cancelled",
                  "spec_rounds", "draft_tokens", "draft_accepted",
                  "draft_rejected"):
            self.counters[c] = 0

    def observe_ttft(self, ms: float) -> None:
        with self._lock:
            self.ttft_ms.record(ms)

    def observe_intertoken(self, ms: float) -> None:
        with self._lock:
            self.intertoken_ms.record(ms)

    def observe_prefill(self, ms: float) -> None:
        with self._lock:
            self.counters["prefills"] += 1
            self.prefill_ms.record(ms)

    def observe_spec_round(self, drafted: int, accepted: int) -> None:
        """One speculative round: ``drafted`` proposals across the
        batch, ``accepted`` of them matched by the target. Every
        EMITTED token (accepted drafts included) is counted in
        ``tokens_generated`` by the emission path exactly once;
        rejected drafts only ever land here — they never inflate
        throughput."""
        with self._lock:
            self.counters["spec_rounds"] += 1
            self.counters["draft_tokens"] += int(drafted)
            self.counters["draft_accepted"] += int(accepted)
            self.counters["draft_rejected"] += int(drafted) - int(accepted)

    def observe_decode_step(self, active: int, ms: float) -> None:
        with self._lock:
            self.counters["decode_steps"] += 1
            self.counters["slots_active_sum"] += int(active)
            self.counters["batches_dispatched"] += 1
            self.counters["rows_served"] += int(active)
            self.counters["rows_padded"] += max(0, self.max_slots
                                                - int(active))
            self.batch_sizes[int(active)] = \
                self.batch_sizes.get(int(active), 0) + 1
            self.exec_ms.record(ms)

    def to_record(self) -> dict:
        rec = super().to_record()
        with self._lock:
            rec["latency_ms"]["ttft"] = self.ttft_ms.summary()
            rec["latency_ms"]["intertoken"] = self.intertoken_ms.summary()
            rec["latency_ms"]["prefill"] = self.prefill_ms.summary()
            steps = self.counters["decode_steps"]
            occ = (self.counters["slots_active_sum"]
                   / (steps * self.max_slots)) \
                if steps and self.max_slots else 0.0
            uptime = max(time.time() - self._start_t, 1e-9)
            rec["generative"] = {
                "max_slots": self.max_slots,
                "tokens_generated": self.counters["tokens_generated"],
                "prefills": self.counters["prefills"],
                "decode_steps": steps,
                "slot_occupancy": round(occ, 4),
                "tokens_per_sec": round(
                    self.counters["tokens_generated"] / uptime, 3),
                "spec_rounds": self.counters["spec_rounds"],
                "draft_tokens": self.counters["draft_tokens"],
                "draft_accepted": self.counters["draft_accepted"],
                "draft_rejected": self.counters["draft_rejected"],
                "draft_acceptance_rate": round(safe_ratio(
                    self.counters["draft_accepted"],
                    self.counters["draft_tokens"]), 4)}
        return rec

    def stats(self) -> str:
        rec = self.to_record()
        g = rec["generative"]
        lines = [super().stats(),
                 f"  generative: {g['tokens_generated']} tokens "
                 f"({g['tokens_per_sec']} tok/s lifetime), "
                 f"{g['prefills']} prefills, {g['decode_steps']} decode "
                 f"steps, slot occupancy {g['slot_occupancy']:.1%} of "
                 f"{g['max_slots']} slots"]
        if g["spec_rounds"]:
            lines.append(
                f"  speculative: {g['spec_rounds']} rounds, acceptance "
                f"{g['draft_acceptance_rate']:.1%} "
                f"({g['draft_accepted']}/{g['draft_tokens']} drafts)")
        for name in ("ttft", "intertoken", "prefill"):
            s = rec["latency_ms"][name]
            lines.append(f"  {name:<10} p50 {s['p50']:.3f} ms  "
                         f"p95 {s['p95']:.3f} ms  p99 {s['p99']:.3f} ms  "
                         f"max {s['max']:.3f} ms  (n={s['count']})")
        return "\n".join(lines)


def _spec_dispatchers(spec: GenerativeSpec,
                      kv_shape: tuple) -> Dict[str, AOTDispatch]:
    """One (decode, prefill) dispatcher pair per (spec, KV slab shape),
    memoized on the spec object: every consumer of the same model AND
    slab geometry — servers, restarts, the :func:`greedy_decode`
    reference — shares one compile set. Keyed by the slab shape, not
    just the spec: AOT executables are looked up by the io-dict shape
    signature alone, so two servers differing only in ``max_seq_len``
    would otherwise collide on the same decode signature and the
    second would silently fall off the warmed path onto lazy compiles
    (the aval-mismatch fallback) under live traffic."""
    cache = getattr(spec, "_disp_cache", None)
    if cache is None:
        cache = {}
        spec._disp_cache = cache
    key = tuple(int(d) for d in kv_shape)
    pair = cache.get(key)
    if pair is None:
        import jax
        pair = {
            "decode": AOTDispatch(
                jax.jit(spec.decode, donate_argnums=(1, 2)), ph_arg=3),
            "prefill": AOTDispatch(
                jax.jit(spec.prefill, donate_argnums=(1, 2)), ph_arg=3)}
        if getattr(spec, "verify", None) is not None:
            pair["verify"] = AOTDispatch(
                jax.jit(spec.verify, donate_argnums=(1, 2)), ph_arg=3)
        cache[key] = pair
    return pair


class GenerativeServer:
    """Continuous-batching autoregressive model server.

    ::

        spec = zoo.gpt.gpt_generative_spec(sd, cfg)
        srv = GenerativeServer(spec, max_slots=8, max_seq_len=128)
        handle = srv.submit([1, 2, 3], max_new_tokens=32)
        for tok in handle.tokens():      # streams as decoded
            ...
        tokens = handle.result()         # or the full list
        srv.shutdown()

    ``admit="continuous"`` (default) fills free slots from the queue at
    every step boundary; ``admit="static"`` is the wait-for-full-batch
    baseline (a new wave is admitted only when every slot is free) —
    kept for the benchmark comparison, not for production.

    ``warmup=True`` AOT-precompiles the decode program and every
    prefill bucket before the worker starts (compiles stay 0 under
    traffic; with a persistent compilation cache a warm restart serves
    with 0 backend compiles — docs/cold_start.md). ``resilience=True``
    arms SLO admission (p99 decode-step TTFT estimates) and worker
    supervision (crash requeue at prefill, exactly once).
    """

    def __init__(self, spec, max_slots: int = 8,
                 max_seq_len: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_queue_len: int = 256,
                 default_timeout_ms: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 stats_storage=None,
                 telemetry_port: Optional[int] = None,
                 resilience=True,
                 warmup: bool = True,
                 admit: str = "continuous",
                 memory_sample_every: Optional[int] = 64,
                 draft_spec=None,
                 speculate_k: int = 4,
                 start: bool = True):
        spec = self._coerce_spec(spec)
        if admit not in ("continuous", "static"):
            raise ValueError(f"admit= must be 'continuous' or 'static', "
                             f"got {admit!r}")
        self.spec = spec
        self.max_slots = int(max_slots)
        self.max_seq_len = int(max_seq_len or spec.max_seq_len)
        if self.max_seq_len > spec.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"positional capacity {spec.max_seq_len}")
        # speculative decoding: a small DRAFT model proposes K-1 tokens
        # per slot per round, the target verifies the whole window in
        # one dispatch. The draft always runs DENSE (its slabs are tiny)
        # even under a paged target. Misconfigurations that can never
        # work fail here, not mid-decode (analyze/servingpass.py lints
        # the same contract statically)
        self.speculate_k = int(speculate_k)
        self.draft_spec = None
        self.draft_slab_bytes = 0
        if draft_spec is not None:
            if not isinstance(draft_spec, GenerativeSpec):
                if hasattr(draft_spec, "generative_spec"):
                    draft_spec = draft_spec.generative_spec()
                else:
                    raise TypeError(
                        f"{type(draft_spec).__name__} is not usable as "
                        f"a draft: pass a dense GenerativeSpec (the "
                        f"draft always runs dense, even under a paged "
                        f"target)")
            if int(draft_spec.vocab_size) != int(spec.vocab_size):
                raise ValueError(
                    f"draft vocab_size {draft_spec.vocab_size} != "
                    f"target vocab_size {spec.vocab_size}: speculation "
                    f"compares token ids, the vocabularies must match")
            if int(draft_spec.max_seq_len) < self.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {draft_spec.max_seq_len} < "
                    f"served max_seq_len {self.max_seq_len}: the draft "
                    f"must cover every position the target can reach")
            if self.speculate_k < 2:
                raise ValueError(
                    f"speculate_k must be >= 2, got {self.speculate_k} "
                    f"(a window of 1 holds only the already-emitted "
                    f"token and drafts nothing)")
            self.draft_spec = draft_spec
        self.admit_mode = admit
        self.eos_id = eos_id if eos_id is not None else spec.eos_id
        self.default_timeout_ms = default_timeout_ms
        self.max_queue_len = int(max_queue_len)
        self.stats_storage = stats_storage
        self.metrics = self._make_metrics()
        # pow2 prefill bucket ladder (serving/batching.py machinery):
        # halving down from max_seq_len to 1 — ≤ log2(max_seq)+1
        # compiled prefill shapes for ANY prompt-length mix
        self._buckets = BucketSpec(
            buckets if buckets is not None
            else pow2_buckets(self.max_seq_len,
                              n_buckets=int(self.max_seq_len).bit_length()))
        if self._buckets.max_rows > self.max_seq_len:
            raise ValueError(
                f"largest prefill bucket {self._buckets.max_rows} exceeds "
                f"max_seq_len {self.max_seq_len}: its KV rows would not "
                f"fit the slab")
        # resilience (serving/resilience.py): the generative tier uses
        # p99 decode-step time for TTFT estimates (ISSUE 15 / Orca-style
        # step scheduling makes tail steps the binding constraint)
        if resilience is True:
            resilience = ResilienceConfig(percentile=99.0)
        self.resilience = ResilienceConfig.normalize(resilience)
        self.admission: Optional[AdmissionController] = None
        if self.resilience is not None and self.resilience.admission:
            self.admission = AdmissionController(
                window=self.resilience.window,
                percentile=self.resilience.percentile,
                min_samples=self.resilience.min_exec_samples)
        self._queue = RequestQueue(
            self.max_queue_len,
            on_timeout=lambda req: self.metrics.record_timeout("deadline"))
        self._exec_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._shapes_seen: set = set()
        self._req_id = 0
        self._id_lock = threading.Lock()
        self._closed = False
        self._killed = False         # abort(): fail in-flight, no drain
        self._dirty = False          # a respawned worker must reset state
        self._mem_every = (max(1, int(memory_sample_every))
                           if memory_sample_every else None)
        # parameters: by-name sync from the training graph, cached as
        # one dict so every dispatch shares the same device arrays
        self._params = dict(spec.params())
        # KV slabs + host scheduler state + dispatchers — the memory
        # tier. Overridden by serving/paged's PagedGenerativeServer,
        # which replaces the dense per-slot slabs with a block pool and
        # admits on free BLOCKS rather than free slots
        self._init_kv()
        self._init_draft()
        self.telemetry = None
        if telemetry_port is not None:
            from deeplearning4j_tpu.monitor.server import TelemetryServer
            self.telemetry = TelemetryServer(storage=stats_storage,
                                             port=telemetry_port)
            self.telemetry.add_scrape_hook(
                lambda reg: reg.fold_serving(self.metrics))
            self.telemetry.add_health_provider("generative",
                                               self._telemetry_health)
        self.warmup_report: Optional[dict] = None
        if warmup:
            self.warmup()
        self._workers: List[threading.Thread] = []
        self._supervisor: Optional[WorkerSupervisor] = None
        # gate on the CONFIG, not self._supervisor: the supervisor's
        # constructor spawns the worker before the attribute assignment
        # completes (the PR-9 construction race)
        self._supervised = (self.resilience is not None
                            and self.resilience.supervise)
        self._cur_slot: Optional[InflightSlot] = None
        self._started = False
        if start:
            self.start()

    # -- subclass hooks (serving/paged/server.py overrides) -------------
    def _coerce_spec(self, spec):
        if not isinstance(spec, GenerativeSpec):
            if hasattr(spec, "generative_spec"):
                spec = spec.generative_spec()
            else:
                raise TypeError(
                    f"{type(spec).__name__} is not generatively servable: "
                    f"pass a GenerativeSpec (e.g. from "
                    f"zoo.gpt.gpt_generative_spec)")
        return spec

    def _make_metrics(self) -> GenerativeMetrics:
        return GenerativeMetrics(self.max_slots)

    def _init_kv(self) -> None:
        """Allocate the KV memory tier + host scheduler state.

        Dense layout: two ``[layers, max_slots, heads, max_seq,
        head_dim]`` slabs allocated ONCE, headroom-guarded, donated
        through every dispatch (docs/serving.md "Generative serving").
        """
        spec = self.spec
        shape = tuple(spec.kv_shape(self.max_slots, self.max_seq_len))
        import jax.numpy as jnp
        from deeplearning4j_tpu.memory import AllocationsTracker
        from deeplearning4j_tpu.monitor import memstats
        from deeplearning4j_tpu.ndarray.dtype import DataType
        self._kv_dtype = DataType.from_any(spec.kv_dtype).jnp
        itemsize = jnp.zeros((), self._kv_dtype).dtype.itemsize
        self.kv_slab_bytes = 2 * int(np.prod(shape)) * itemsize
        memstats.check_headroom(
            self.kv_slab_bytes,
            f"generative KV slabs ({self.max_slots} slots x "
            f"{self.max_seq_len} positions)")
        self._kc = jnp.zeros(shape, self._kv_dtype)
        self._vc = jnp.zeros(shape, self._kv_dtype)
        AllocationsTracker.get_instance().allocate("kv_slab",
                                                   self.kv_slab_bytes)
        # host-side slot state (the worker thread owns mutation)
        self._slots = SlotAllocator(self.max_slots)
        self._slot_reqs: List[Optional[GenerationRequest]] = \
            [None] * self.max_slots
        self._tokens = np.zeros(self.max_slots, np.int32)
        self._positions = np.zeros(self.max_slots, np.int32)
        self._active = np.zeros(self.max_slots, bool)
        # dispatchers: lazy jit + AOT executables keyed by io shapes;
        # slabs (args 1, 2) donated so KV updates are in place. Shared
        # per (spec, slab shape): a second server over the same model
        # and geometry — a restart, a canary — reuses every compiled
        # program instead of paying XLA again
        disp = _spec_dispatchers(spec, shape)
        self._decode_disp = disp["decode"]
        self._prefill_disp = disp["prefill"]
        self._verify_disp = disp.get("verify")

    def _init_draft(self) -> None:
        """Speculative-decoding memory + dispatchers: the draft model
        gets its own DENSE per-slot KV slabs (one row per target slot,
        kept position-synced with the target through partial
        acceptance) and its own decode/prefill dispatcher pair. A
        no-op without ``draft_spec``."""
        ds = self.draft_spec
        self._draft_decode_disp = None
        self._draft_prefill_disp = None
        self._draft_params = None
        self._dkc = self._dvc = None
        if ds is None:
            return
        if self._verify_disp is None:
            raise ValueError(
                "speculative decoding needs a target spec exposing a "
                "verify program — rebuild the spec with a current "
                "zoo.gpt.gpt_generative_spec / gpt_paged_spec")
        import jax.numpy as jnp

        from deeplearning4j_tpu.memory import AllocationsTracker
        from deeplearning4j_tpu.monitor import memstats
        from deeplearning4j_tpu.ndarray.dtype import DataType
        shape = tuple(ds.kv_shape(self.max_slots, self.max_seq_len))
        self._draft_kv_dtype = DataType.from_any(ds.kv_dtype).jnp
        itemsize = jnp.zeros((), self._draft_kv_dtype).dtype.itemsize
        self.draft_slab_bytes = 2 * int(np.prod(shape)) * itemsize
        memstats.check_headroom(
            self.draft_slab_bytes,
            f"draft KV slabs (speculative decoding, {self.max_slots} "
            f"slots x {self.max_seq_len} positions)")
        self._dkc = jnp.zeros(shape, self._draft_kv_dtype)
        self._dvc = jnp.zeros(shape, self._draft_kv_dtype)
        AllocationsTracker.get_instance().allocate("kv_slab",
                                                   self.draft_slab_bytes)
        ddisp = _spec_dispatchers(ds, shape)
        self._draft_decode_disp = ddisp["decode"]
        self._draft_prefill_disp = ddisp["prefill"]
        self._draft_params = dict(ds.params())

    def _reset_draft_slabs(self) -> None:
        if self.draft_spec is None:
            return
        import jax.numpy as jnp
        shape = tuple(self.draft_spec.kv_shape(self.max_slots,
                                               self.max_seq_len))
        self._dkc = jnp.zeros(shape, self._draft_kv_dtype)
        self._dvc = jnp.zeros(shape, self._draft_kv_dtype)

    def _refresh_draft_params(self) -> None:
        if self.draft_spec is None:
            return
        fresh = dict(self.draft_spec.params())
        with self._exec_lock:
            self._draft_params = fresh

    def _can_place(self, req: GenerationRequest) -> bool:
        """Whether the memory tier can hold ``req``'s prefill right
        now. Dense slabs: a free slot IS the capacity (the ``_admit``
        loop already gates on one). The paged subclass gates on free
        KV *blocks* — a request it cannot place goes back to the front
        of the queue until a retirement frees blocks."""
        return True

    # ------------------------------------------------------------------
    def start(self) -> None:
        """Start the decode worker (a no-op when already started).
        ``GenerativeServer(..., start=False)`` + queued submits + a late
        ``start()`` makes admission order deterministic for tests."""
        if self._started or self._closed:
            return
        self._started = True
        if self._supervised:
            self._supervisor = WorkerSupervisor(
                spawn=self._spawn_worker, n_workers=1, queue=self._queue,
                metrics=self.metrics,
                backoff_base_s=self.resilience.worker_backoff_base_s,
                backoff_max_s=self.resilience.worker_backoff_max_s,
                publish=self._publish_fault)
        else:
            self._workers.append(self._spawn_worker(0, InflightSlot()))

    def _next_id(self) -> int:
        with self._id_lock:
            self._req_id += 1
            return self._req_id

    # -- AOT warmup (compilecache/, docs/cold_start.md) -----------------
    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """AOT-precompile the decode program and every prefill bucket so
        live traffic never waits on XLA: one decode shape + ≤
        log2(max_seq)+1 prefill shapes. With a persistent compilation
        cache configured every entry is a cache hit on a warm restart
        and warmup is ~free. Returns (and stores as ``warmup_report``)
        the shape list, wall seconds and the compile/cache-hit deltas."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from deeplearning4j_tpu.compilecache import (COMPILE_STATS,
                                                     install_compile_watcher)
        from deeplearning4j_tpu.environment import environment
        from deeplearning4j_tpu.monitor import memstats
        environment().apply_compilation_cache()
        install_compile_watcher()
        bucket_list = sorted({int(b) for b in buckets}) \
            if buckets is not None else list(self._buckets.buckets)
        params_abs = {n: jax.ShapeDtypeStruct(tuple(a.shape), a.dtype)
                      for n, a in self._params.items()}
        kv_abs = jax.ShapeDtypeStruct(tuple(self._kc.shape),
                                      self._kc.dtype)
        S = self.max_slots
        mark = COMPILE_STATS.mark()
        t0 = _time.perf_counter()

        def _build(disp, io_abs, label, params_abs=params_abs,
                   kv_abs=kv_abs, role="target"):
            sig = ph_shape_sig(io_abs)
            with self._exec_lock:
                if sig not in disp.aot:
                    with _tracer.span("compile.precompile", cat="compile",
                                      target=label):
                        disp.aot[sig] = disp.lower(
                            params_abs, kv_abs, kv_abs, io_abs).compile()
                    memstats.capture_plan(label, sig,
                                          compiled=disp.aot[sig])
                # mark INSIDE the lock hold: a live dispatch between
                # compile and mark must not count a spurious lazy
                # compile for a just-warmed shape (PR-6 round-6 rule).
                # Keyed by role: the draft's decode/prefill signatures
                # are identical to the target's
                if (role, sig) not in self._shapes_seen:
                    self._shapes_seen.add((role, sig))
                    self.metrics.inc("warmup_compiles")

        _build(self._decode_disp,
               {"tokens": jax.ShapeDtypeStruct((S,), jnp.int32),
                "positions": jax.ShapeDtypeStruct((S,), jnp.int32),
                "active": jax.ShapeDtypeStruct((S,), jnp.bool_)},
               f"generative_decode_s{S}")
        for b in bucket_list:
            _build(self._prefill_disp,
                   {"tokens": jax.ShapeDtypeStruct((int(b),), jnp.int32),
                    "length": jax.ShapeDtypeStruct((), jnp.int32),
                    "slot": jax.ShapeDtypeStruct((), jnp.int32)},
                   f"generative_prefill_b{int(b)}")
        if self.draft_spec is not None:
            W = self.speculate_k
            _build(self._verify_disp,
                   {"tokens": jax.ShapeDtypeStruct((S, W), jnp.int32),
                    "positions": jax.ShapeDtypeStruct((S,), jnp.int32),
                    "active": jax.ShapeDtypeStruct((S,), jnp.bool_)},
                   f"generative_verify_s{S}w{W}")
            dparams_abs = {n: jax.ShapeDtypeStruct(tuple(np.shape(a)),
                                                   np.asarray(a).dtype)
                           for n, a in self._draft_params.items()}
            dkv_abs = jax.ShapeDtypeStruct(tuple(self._dkc.shape),
                                           self._dkc.dtype)
            _build(self._draft_decode_disp,
                   {"tokens": jax.ShapeDtypeStruct((S,), jnp.int32),
                    "positions": jax.ShapeDtypeStruct((S,), jnp.int32),
                    "active": jax.ShapeDtypeStruct((S,), jnp.bool_)},
                   f"draft_decode_s{S}", params_abs=dparams_abs,
                   kv_abs=dkv_abs, role="draft")
            for b in bucket_list:
                _build(self._draft_prefill_disp,
                       {"tokens": jax.ShapeDtypeStruct((int(b),),
                                                       jnp.int32),
                        "length": jax.ShapeDtypeStruct((), jnp.int32),
                        "slot": jax.ShapeDtypeStruct((), jnp.int32)},
                       f"draft_prefill_b{int(b)}", params_abs=dparams_abs,
                       kv_abs=dkv_abs, role="draft")
        self.warmup_report = {
            "decode_slots": S,
            "prefill_buckets": bucket_list,
            "speculative": self.draft_spec is not None,
            "seconds": round(_time.perf_counter() - t0, 4),
            **{k: v for k, v in COMPILE_STATS.delta(mark).items()
               if k in ("backend_compiles", "cache_hits",
                        "cache_misses")}}
        return self.warmup_report

    # -- client API -----------------------------------------------------
    def _validate_submit(self, prompt, max_new_tokens: int) -> np.ndarray:
        """The cheap permanent-error checks every submit path runs
        BEFORE any capacity accounting, returning the coerced prompt.
        Shared so the paged subclass can validate ahead of its block
        commitment: an invalid request must surface its ValueError (a
        permanent rejection) even under pool pressure, never a
        retryable overload shed."""
        if self._closed:
            raise ServerClosedError("GenerativeServer is shut down")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size > self.max_seq_len - 1:
            raise ValueError(
                f"prompt of {prompt.size} tokens leaves no room to "
                f"generate within max_seq_len {self.max_seq_len}")
        if prompt.min() < 0 or prompt.max() >= self.spec.vocab_size:
            raise ValueError(
                f"prompt token ids must be in [0, {self.spec.vocab_size})")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        return prompt

    def submit(self, prompt, max_new_tokens: int = 16,
               timeout_ms: Optional[float] = None,
               on_token: Optional[Callable[[int], None]] = None,
               eos_id: Optional[int] = None,
               temperature: float = 0.0,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               seed: Optional[int] = None,
               trace=None) -> GenerationHandle:
        """Enqueue one generation; returns a :class:`GenerationHandle`
        streaming tokens as they decode. Sheds typed at the call site:
        :class:`ServerOverloadedError` when the queue is full or the
        estimated TTFT (queue depth × rolling p99 decode-step time)
        already exceeds the deadline.

        ``temperature`` 0 (default) is exact greedy; > 0 samples from
        the target logits with optional ``top_k``/``top_p`` truncation,
        seeded by ``(seed, absolute token index)`` so the continuation
        is reproducible per request regardless of co-batching or a
        crash requeue. ``seed`` defaults to the request id (stable for
        the request's whole lifetime, including requeues).

        ``trace`` is an optional request-trace context (anything with
        ``trace_id``/``segment`` ints — the fleet router passes a
        ``monitor.reqtrace.TraceContext``); its identity is snapshotted
        onto the request and tags every span it touches. Purely
        observational: tokens are bit-identical with or without it."""
        prompt = self._validate_submit(prompt, max_new_tokens)
        temperature = float(temperature)
        if not np.isfinite(temperature) or temperature < 0.0:
            raise ValueError(
                f"temperature must be a finite float >= 0, "
                f"got {temperature}")
        if top_k is not None and int(top_k) < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < float(top_p) <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        self.metrics.inc("requests_submitted")
        timeout_ms = timeout_ms if timeout_ms is not None \
            else self.default_timeout_ms
        self._admit_check(timeout_ms)
        deadline = time.monotonic() + timeout_ms / 1000.0 \
            if timeout_ms is not None else None
        from concurrent.futures import Future
        rid = self._next_id()
        req = GenerationRequest(
            x=[prompt], future=Future(), rows=1, deadline=deadline,
            id=rid, prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            eos_id=eos_id if eos_id is not None else self.eos_id,
            on_token=on_token,
            temperature=temperature,
            top_k=int(top_k) if top_k is not None else None,
            top_p=float(top_p) if top_p is not None else None,
            seed=int(seed) if seed is not None else rid,
            trace_id=(int(trace.trace_id) if trace is not None
                      else None),
            trace_seg=(int(trace.segment) if trace is not None else 0))
        with _tracer.span("serving.enqueue", cat="serving", id=req.id,
                          prompt=int(prompt.size), **_trace_args(req)):
            try:
                self._queue.put(req)
            except ServerOverloadedError:
                self.metrics.inc("requests_rejected")
                raise
        return GenerationHandle(req)

    def submit_continuation(self, prompt, emitted,
                            max_new_tokens: int = 16,
                            timeout_ms: Optional[float] = None,
                            on_token: Optional[Callable[[int], None]]
                            = None,
                            eos_id: Optional[int] = None,
                            temperature: float = 0.0,
                            top_k: Optional[int] = None,
                            top_p: Optional[float] = None,
                            seed: Optional[int] = None,
                            trace=None) -> GenerationHandle:
        """Resume a generation from its already-emitted prefix — the
        fleet's failover/replay primitive. ``prompt + emitted`` becomes
        the prefill (on the paged server that span hits the prefix
        cache), the token budget is decremented by ``len(emitted)``,
        and the handle streams/returns only the REMAINING tokens.

        Bit-identity contract: sampling keys on ``(seed, absolute
        token index)`` and the index is prompt length + generated
        ordinal, so a continuation prefilled with the emitted prefix
        lands every remaining draw on exactly the indices the
        uninterrupted run would have used. That only holds if the seed
        crosses the hop — a sampled continuation therefore REQUIRES an
        explicit ``seed`` (the original request's), because the
        server-local default (the request id) differs per replica.

        A continuation that is already finished (budget spent, EOS
        emitted, or context full) resolves immediately to an empty
        token list without occupying a slot."""
        temperature = float(temperature)
        if temperature > 0.0 and seed is None:
            raise ValueError(
                "a sampled continuation needs the original request's "
                "seed — without it the remaining draws cannot land on "
                "the same (seed, index) stream and bit-identity is "
                "lost")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        emitted = [int(t) for t in
                   np.asarray(emitted, np.int64).reshape(-1)]
        remaining = int(max_new_tokens) - len(emitted)
        eos = eos_id if eos_id is not None else self.eos_id
        prefix = (np.concatenate([prompt,
                                  np.asarray(emitted, np.int32)])
                  if emitted else prompt)
        done = (remaining < 1
                or (eos is not None and emitted and emitted[-1] == eos)
                or int(prefix.size) >= self.max_seq_len)
        if done:
            # nothing left to decode: the interrupted generation had in
            # fact finished — resolve without queueing (an empty-result
            # handle; the caller stitches it onto the emitted prefix)
            if self._closed:
                raise ServerClosedError(
                    "GenerativeServer is shut down")
            from concurrent.futures import Future
            req = GenerationRequest(
                x=[prefix], future=Future(), rows=1,
                id=self._next_id(), prompt=prefix,
                max_new_tokens=max(1, remaining),
                eos_id=eos, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed,
                trace_id=(int(trace.trace_id) if trace is not None
                          else None),
                trace_seg=(int(trace.segment) if trace is not None
                           else 0))
            req.succeed()
            return GenerationHandle(req)
        return self.submit(prefix, remaining, timeout_ms=timeout_ms,
                           on_token=on_token, eos_id=eos_id,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed, trace=trace)

    def generate(self, prompt, max_new_tokens: int = 16,
                 timeout_ms: Optional[float] = None) -> List[int]:
        """Blocking convenience around :meth:`submit`."""
        return self.submit(prompt, max_new_tokens,
                           timeout_ms=timeout_ms).result()

    def _admit_check(self, timeout_ms: Optional[float]) -> None:
        """SLO admission: TTFT estimate = (queue depth + 1) × rolling
        p99 decode-step time. A deadline the estimate already exceeds
        is shed typed, with the estimate as the backoff hint."""
        if self.admission is None or timeout_ms is None:
            return
        est = self.admission.estimate_wait_ms(self._queue.pending() + 1, 1)
        if est is not None and est > timeout_ms:
            self.metrics.inc("requests_shed")
            raise ServerOverloadedError(
                f"estimated TTFT {est:.1f} ms exceeds the "
                f"{timeout_ms:.1f} ms deadline — shed at admission "
                f"(queue depth x p{self.admission.percentile:g} "
                f"decode-step time)", retry_after_s=round(est / 1000.0, 3))

    def update_model(self) -> None:
        """Re-pull trained parameters from the spec's source graph
        between dispatches (the ``ParallelInference.update_model``
        analogue)."""
        fresh = dict(self.spec.params())
        with self._exec_lock:
            self._params = fresh
        self._refresh_draft_params()

    def params_snapshot(self) -> dict:
        """The currently-installed serving parameters — the rollback
        token a canaried fleet deploy takes BEFORE ``update_model`` so
        a failed gate can restore exactly what served before."""
        with self._exec_lock:
            return self._params

    def restore_params(self, params: dict) -> None:
        """Install a :meth:`params_snapshot` between dispatches — the
        fleet-deploy rollback path (same in-flight staleness contract
        as ``update_model``)."""
        with self._exec_lock:
            self._params = dict(params)

    # -- worker ---------------------------------------------------------
    def _spawn_worker(self, index: int, slot: InflightSlot
                      ) -> threading.Thread:
        t = threading.Thread(target=self._worker_main, args=(slot,),
                             name=f"GenerativeServer-{index}", daemon=True)
        t.start()
        return t

    def _worker_main(self, slot: InflightSlot) -> None:
        self._cur_slot = slot
        try:
            if self._dirty:
                # a respawned worker after a crash: the in-flight
                # requests were requeued (they re-enter at prefill) and
                # the donated slabs may be mid-dispatch garbage — start
                # from fresh slabs + a clean slot table
                self._reset_state()
            self._dirty = True
            self._worker_loop(slot)
            slot.exited = True
        except BaseException as e:      # noqa: BLE001 — supervisor's cue
            slot.crashed = e
            if not self._supervised:
                # no supervisor to requeue them: in-flight generations
                # must not hang their clients forever
                for r in list(slot.requests or []):
                    r.fail(e)
                self.metrics.record_failure(
                    e, cause="worker_crash",
                    n=max(1, len(slot.requests or [])))

    def _reset_state(self) -> None:
        import jax.numpy as jnp
        shape = tuple(self.spec.kv_shape(self.max_slots, self.max_seq_len))
        self._kc = jnp.zeros(shape, self._kv_dtype)
        self._vc = jnp.zeros(shape, self._kv_dtype)
        self._reset_draft_slabs()
        self._slots.reset()
        self._slot_reqs = [None] * self.max_slots
        self._tokens[:] = 0
        self._positions[:] = 0
        self._active[:] = False

    def _worker_loop(self, slot: InflightSlot) -> None:
        while True:
            if self._killed:
                # abort(): a killed process completes nothing — fail
                # the in-flight generations typed at this step boundary
                # and exit CLEANLY (the supervisor must not respawn or
                # requeue: the futures are already resolved)
                self._abort_inflight()
                return
            progressed = self._step(slot)
            if progressed:
                slot.progressed = True
            elif self._queue.finished and not self._active.any():
                return

    def _abort_inflight(self) -> None:
        for s in range(self.max_slots):
            req = self._slot_reqs[s]
            if req is not None:
                self._retire(s, error=ServerClosedError(
                    f"server killed with generation {req.id} in "
                    f"flight after {len(req.generated)} tokens"))

    def _n_active(self) -> int:
        return int(self._active.sum())

    def _sync_inflight(self, slot: InflightSlot) -> None:
        """Keep the supervisor's crash-requeue window exact: every
        popped-but-unresolved generation, at all times."""
        reqs = [r for r in self._slot_reqs if r is not None]
        slot.requests = reqs or None

    def _step(self, slot: InflightSlot) -> bool:
        progressed = self._admit(slot)
        if not self._active.any():
            return progressed
        if self._spec_ready():
            self._speculate_once(slot)
        else:
            self._decode_once(slot)
        return True

    def _admit(self, slot: InflightSlot) -> bool:
        """Step-boundary admission: fill free slots from the queue
        (continuous batching). In ``static`` mode a new wave is only
        admitted when every slot is free — the wait-for-full-batch
        baseline the benchmark compares against."""
        # static (wait-for-full-batch) baseline: a new WAVE is only
        # admitted once every slot is free — decided once per boundary,
        # then the whole wave fills (not one request per boundary)
        if self.admit_mode == "static" and self._n_active() > 0:
            return False
        admitted = False
        while self._slots.free_count() > 0:
            # block briefly only when idle — an active decode batch
            # must not stall at the boundary waiting for new work
            block = not self._active.any() and not admitted
            reqs = self._queue.take(1, timeout=0.05 if block else 0.0)
            if not reqs:
                break
            req = reqs[0]
            if req.cancelled:
                # same accounting as a slot-occupying cancel (_retire):
                # cancelled, not served
                req.future.set_result(list(req.generated))
                req.close_stream()
                self.metrics.inc("requests_cancelled")
                continue
            if not self._can_place(req):
                # memory-tier backpressure (paged: not enough free KV
                # blocks): back to the FRONT — it keeps its place in
                # line — and stop admitting until a retirement frees
                # capacity. Does not consume the crash-requeue budget
                self._queue.requeue(req)
                break
            s = self._slots.alloc()
            self._slot_reqs[s] = req
            self._sync_inflight(slot)
            try:
                self._prefill(s, req)
                admitted = True
            except Exception as e:      # noqa: BLE001 — per-request fail
                # already OOM-wrapped by _dispatch; a failing prompt
                # fails ITS request, not the decode worker
                self._retire(s, error=e)
        return admitted

    def _prefill(self, s: int, req: GenerationRequest) -> None:
        prefix = req.prefix()
        L = int(prefix.size)
        if L > self.max_seq_len - 1:
            # a crash-requeued request whose prefix already fills the
            # sequence: nothing left to decode — finish with what it has
            self._retire(s)
            return
        bucket = self._buckets.bucket_for(L)
        padded = np.zeros(bucket, np.int32)
        padded[:L] = prefix
        io = {"tokens": padded, "length": np.int32(L), "slot": np.int32(s)}
        t0 = time.perf_counter()
        out = self._dispatch(self._prefill_disp, io, "serving.prefill",
                             bucket=bucket, slot=s, **_trace_args(req))
        tok = self._resolve_token(req, int(out[2]), out[3])
        self.metrics.observe_prefill((time.perf_counter() - t0) * 1000.0)
        self._positions[s] = L
        self._tokens[s] = tok
        self._active[s] = True
        self._emit(s, req, tok)
        self._draft_prefill(s, prefix, L)

    def _draft_prefill(self, s: int, prefix: np.ndarray, L: int) -> None:
        """Fill the DRAFT model's KV rows for a freshly admitted slot
        — always the FULL prefix from scratch (the draft has no prefix
        cache, even under a paged target). Its first-token output is
        discarded: the target's prefill already emitted the real one,
        and the draft only needs its cache position-synced before the
        first speculative round."""
        if self.draft_spec is None or not self._active[s]:
            return
        bucket = self._buckets.bucket_for(L)
        padded = np.zeros(bucket, np.int32)
        padded[:L] = prefix
        io = {"tokens": padded, "length": np.int32(L),
              "slot": np.int32(s)}
        self._dispatch(self._draft_prefill_disp, io, "serving.draft",
                       draft=True, phase="prefill", bucket=bucket, slot=s)

    def _resolve_token(self, req: GenerationRequest, device_tok: int,
                       logits_row) -> int:
        """The target's own next token for one slot: the device argmax
        at temperature 0 (bit-identical to the greedy-only path),
        otherwise a seeded host sample from the target logits at this
        request's absolute token index. The (seed, index) fold makes
        the draw independent of co-batching, admission order and
        crash-requeue re-entry; under speculation the emitted token is
        ALWAYS the target's own, so output never depends on draft
        quality — only throughput does."""
        if not req.temperature or req.temperature <= 0.0:
            return int(device_tok)
        seed = req.seed if req.seed is not None else req.id
        return sample_token(np.asarray(logits_row),
                            temperature=req.temperature,
                            top_k=req.top_k, top_p=req.top_p,
                            seed=seed,
                            index=int(np.asarray(req.prompt).size)
                            + len(req.generated))

    def _sampled_active(self) -> bool:
        return any(r is not None and r.temperature > 0
                   for r in self._slot_reqs)

    def _trace_slots(self) -> dict:
        """The slot -> trace_id occupancy map a batch-level dispatch
        span records: ONE decode dispatch serves every active slot at
        once, so per-request attribution needs to know who shared it
        (``monitor.reqtrace.assemble`` divides the span's duration by
        the map size). Only traced requests appear; call sites attach
        the map only while the tracer is recording."""
        out = {}
        for s, r in enumerate(self._slot_reqs):
            if r is not None and r.trace_id is not None:
                out[s] = r.trace_id
        return out

    def _batch_span_args(self, n_active: int, **extra) -> dict:
        attrs = dict(extra, active=n_active)
        if _tracer.enabled:
            slots = self._trace_slots()
            if slots:
                attrs["slots"] = slots
        return attrs

    def _decode_once(self, slot: InflightSlot) -> None:
        n_active = self._n_active()
        io = {"tokens": self._tokens.copy(),
              "positions": self._positions.copy(),
              "active": self._active.copy()}
        t0 = time.perf_counter()
        _, _, nxt_d, logits_d = self._dispatch(
            self._decode_disp, io, "serving.decode",
            **self._batch_span_args(n_active))
        nxt = np.asarray(nxt_d)
        ms = (time.perf_counter() - t0) * 1000.0
        self.metrics.observe_decode_step(n_active, ms)
        if self.admission is not None:
            self.admission.observe(ms)
        self._maybe_memory_record()
        lg = np.asarray(logits_d) if self._sampled_active() else None
        for s in np.flatnonzero(io["active"]):
            req = self._slot_reqs[int(s)]
            if req is None:
                continue
            s = int(s)
            tok = self._resolve_token(req, int(nxt[s]),
                                      lg[s] if lg is not None else None)
            self._positions[s] += 1
            self._tokens[s] = tok
            self._emit(s, req, tok)

    # -- speculative decoding (draft K, verify once) --------------------
    def _spec_ready(self) -> bool:
        """Whether the next round can run speculatively: a draft is
        armed and every active slot has a full verify window of
        positions left in the slab. The paged subclass additionally
        grows block tables to cover the window up front, falling back
        to a plain step when the pool cannot."""
        if self._draft_decode_disp is None:
            return False
        act = np.flatnonzero(self._active)
        if act.size == 0:
            return False
        return bool(np.all(self._positions[act].astype(np.int64)
                           + self.speculate_k <= self.max_seq_len))

    def _verify_io(self, window: np.ndarray, positions: np.ndarray,
                   active: np.ndarray) -> dict:
        return {"tokens": window, "positions": positions.copy(),
                "active": active.copy()}

    def _observe_round(self) -> None:
        """Post-round memory-tier bookkeeping hook (paged: pool
        occupancy sample + leak invariant)."""

    def _speculate_once(self, slot: InflightSlot) -> None:
        """One draft-K / verify-once speculative round (Leviathan et
        al., "Fast Inference from Transformers via Speculative
        Decoding"): K sequential DRAFT decode dispatches propose a
        token window per active slot, then the TARGET scores the whole
        window in ONE batched verify dispatch — one read of the target
        weights for up to K emitted tokens. Acceptance is exact: every
        emitted token is the target's own (:meth:`_resolve_token`), so
        output is independent of draft quality; the draft only decides
        how many positions the single verify dispatch resolves. A
        rejected tail needs no KV rollback — positions simply never
        advance over it, and rows above a slot's position are masked
        until overwritten (the same discipline that makes slot reuse
        safe). The draft's own KV stays row-synced because dispatch m
        feeds window column m-1 (the token that, if the round reaches
        that column, is exactly what was accepted there)."""
        W = self.speculate_k
        active = self._active.copy()
        positions = self._positions.copy()
        n_active = int(active.sum())
        window = np.zeros((self.max_slots, W), np.int32)
        window[:, 0] = self._tokens
        reqs = list(self._slot_reqs)
        act_idx = [int(s) for s in np.flatnonzero(active)
                   if reqs[int(s)] is not None]
        sampled = any(reqs[s].temperature > 0 for s in act_idx)
        t0 = time.perf_counter()
        # draft loop: dispatch m feeds window column m-1 at position
        # pos0+m-1, writing that draft-KV row and proposing column m.
        # The W-th dispatch exists only for its KV write (the draft
        # cache must cover the full window before the NEXT round); its
        # proposal is discarded
        d_tokens = window[:, 0].copy()
        for m in range(1, W + 1):
            dio = {"tokens": d_tokens.copy(),
                   "positions": (positions + np.int32(m - 1)
                                 * active).astype(np.int32),
                   "active": active.copy()}
            _, _, dnxt, dlg = self._dispatch(
                self._draft_decode_disp, dio, "serving.draft",
                draft=True, **self._batch_span_args(n_active, step=m))
            if m >= W:
                break
            dnxt = np.asarray(dnxt)
            dlg_h = np.asarray(dlg) if sampled else None
            for s in act_idx:
                req = reqs[s]
                d = int(dnxt[s])
                if req.temperature and req.temperature > 0:
                    # the draft proposal consumes the SAME (seed,
                    # index) draw the target will use to resolve this
                    # position — close distributions then agree on the
                    # sampled token, maximizing acceptance, while the
                    # emitted token remains the target's own
                    d = sample_token(
                        dlg_h[s], temperature=req.temperature,
                        top_k=req.top_k, top_p=req.top_p,
                        seed=req.seed if req.seed is not None
                        else req.id,
                        index=int(np.asarray(req.prompt).size)
                        + len(req.generated) + m - 1)
                window[s, m] = d
            d_tokens = window[:, m].copy()
        vio = self._verify_io(window, positions, active)
        _, _, out_d, vlg_d = self._dispatch(
            self._verify_disp, vio, "serving.verify",
            **self._batch_span_args(n_active, window=W))
        out = np.asarray(out_d)
        ms = (time.perf_counter() - t0) * 1000.0
        self.metrics.observe_decode_step(n_active, ms)
        if self.admission is not None:
            self.admission.observe(ms)
        self._maybe_memory_record()
        lg = np.asarray(vlg_d) if sampled else None
        drafted = accepted = 0
        for s in act_idx:
            req = reqs[s]
            drafted += W - 1
            pos0 = int(positions[s])
            for j in range(W):
                tok = self._resolve_token(
                    req, int(out[s, j]),
                    lg[s, j] if lg is not None else None)
                self._positions[s] = pos0 + j + 1
                self._tokens[s] = tok
                self._emit(s, req, tok)
                if not self._active[s]:
                    break     # retired: EOS / budget / deadline / cancel
                if j + 1 >= W:
                    break
                if int(window[s, j + 1]) != tok:
                    break     # draft rejected: the window tail is invalid
                accepted += 1
        self.metrics.observe_spec_round(drafted, accepted)
        self._observe_round()

    def _dispatch(self, disp: AOTDispatch, io: dict, span: str,
                  draft: bool = False, **attrs):
        """One device dispatch of prefill/decode/verify with the shared
        plumbing: exec lock, span, stall-watchdog guard, compile
        accounting, OOM forensics, and slab rebinding (the old slab
        buffers are donated into the call). ``draft=True`` routes to
        the draft model's params + slabs; the shapes-seen key carries
        the role because draft and target share io signatures."""
        sig = ("draft" if draft else "target", ph_shape_sig(io))
        with self._exec_lock, _tracer.span(span, cat="serving", **attrs):
            first = sig not in self._shapes_seen
            if first:
                self._shapes_seen.add(sig)
                self.metrics.inc("compiles")
            from deeplearning4j_tpu.integrity.watchdog import \
                guard as _wd_guard
            try:
                with _wd_guard("generative_step", first=first):
                    if draft:
                        kc, vc, nxt, logits = disp(
                            self._draft_params, self._dkc, self._dvc, io)
                    else:
                        kc, vc, nxt, logits = disp(
                            self._params, self._kc, self._vc, io)
            except Exception as e:
                raise self._wrap_exec_error(e, span) from e
            if draft:
                self._dkc, self._dvc = kc, vc
            else:
                self._kc, self._vc = kc, vc
        return kc, vc, nxt, logits

    def _wrap_exec_error(self, e: BaseException, what: str):
        from deeplearning4j_tpu.monitor import memstats
        if memstats.is_resource_exhausted(e):
            err = memstats.oom_error(e, program=f"generative_{what}")
            self._publish_fault("oom", program=f"generative_{what}",
                                error=repr(e))
            return err
        return e

    def _maybe_memory_record(self) -> None:
        if self._mem_every is None or self.stats_storage is None:
            return
        if self.metrics.counters["decode_steps"] % self._mem_every != 0:
            return
        from deeplearning4j_tpu.monitor import memstats
        try:
            self.stats_storage.put(memstats.memory_record(source="serving"))
        except Exception:
            pass            # a broken stats sink must not fail requests

    # -- token delivery + retirement ------------------------------------
    def _emit(self, s: int, req: GenerationRequest, tok: int) -> None:
        """Deliver one decoded token to its request's stream at the
        step boundary it resolved, then retire the slot if this token
        finished the generation (EOS / budget / capacity / deadline /
        cancel) — a freed slot is admissible on the very next step."""
        now = time.monotonic()
        # deadline re-checked at DELIVERY time (the serving tier's
        # reply-time deadline rule): a generation that outlived its
        # deadline mid-decode surfaces as a timeout, not a stale stream
        if req.expired(now):
            err = ServingTimeoutError(
                f"generation {req.id} missed its deadline after "
                f"{len(req.generated)} tokens")
            err.tokens = list(req.generated)
            self.metrics.record_timeout("deadline")
            self._retire(s, error=err, timed_out=True)
            return
        if req.cancelled:
            self._retire(s, cancelled=True)
            return
        with _tracer.span("serving.reply", cat="serving", id=req.id,
                          **_trace_args(req)):
            req.emit(tok)
        self.metrics.inc("tokens_generated")
        if req.first_token_t is None:
            req.first_token_t = now
            self.metrics.observe_ttft((now - req.enqueue_t) * 1000.0)
        else:
            self.metrics.observe_intertoken(
                (now - req.last_token_t) * 1000.0)
        req.last_token_t = now
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception as e:      # noqa: BLE001 — user callback
                self._retire(s, error=e)
                return
        done = (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)
                or int(self._positions[s]) + 1 >= self.max_seq_len)
        if done:
            self._retire(s)

    def _retire(self, s: int, error: Optional[BaseException] = None,
                timed_out: bool = False, cancelled: bool = False) -> None:
        """Free slot ``s`` exactly once and resolve its request."""
        req = self._slot_reqs[s]
        self._slot_reqs[s] = None
        self._active[s] = False
        self._slots.free(s)
        if req is not None:
            now = time.monotonic()
            if error is not None:
                req.fail(error)
                if not timed_out:
                    self.metrics.record_failure(error)
            elif cancelled:
                # resolve the future BEFORE closing the stream: a
                # consumer that sees the stream end must find the
                # result already set (no result(timeout=0) race)
                if not req.future.done():
                    req.future.set_result(list(req.generated))
                req.close_stream(GenerationCancelled(
                    f"generation {req.id} cancelled",
                    tokens=req.generated))
                self.metrics.inc("requests_cancelled")
            else:
                req.succeed()
                self.metrics.observe_request(
                    queue_wait_ms=((req.first_token_t or now)
                                   - req.enqueue_t) * 1000.0,
                    e2e_ms=(now - req.enqueue_t) * 1000.0)
        # keep the supervisor's crash-requeue window exact
        if self._cur_slot is not None:
            self._sync_inflight(self._cur_slot)

    # -- observability --------------------------------------------------
    def memory_report(self) -> dict:
        """KV slab accounting for /memory + capacity planning."""
        per_slot = self.kv_slab_bytes // max(1, self.max_slots)
        return {"kv_slab_bytes": self.kv_slab_bytes,
                "kv_slab_shape": list(self._kc.shape),
                "kv_bytes_per_slot": per_slot,
                "max_slots": self.max_slots,
                "max_seq_len": self.max_seq_len,
                "active_slots": self._n_active()}

    def _publish_fault(self, event: str, **fields) -> None:
        if self.stats_storage is None:
            return
        try:
            self.stats_storage.put({"type": "faults", "event": event,
                                    "t": time.time(), "origin": "serving",
                                    **fields})
        except Exception:
            pass        # a broken stats sink must not take a worker down

    def _telemetry_health(self) -> dict:
        depth = self._queue.pending()
        active = self._n_active()
        healthy = not self._closed
        return {"queue_depth": depth,
                "queue_capacity": self.max_queue_len,
                "active_slots": active,
                "max_slots": self.max_slots,
                "ready": healthy and depth < self.max_queue_len,
                "healthy": healthy,
                # the one-scrape routing signal: health_snapshot merges
                # this sub-dict into /readyz's top-level "load" key
                "load": self._telemetry_load(depth, active)}

    def _telemetry_load(self, depth: int, active: int) -> dict:
        step_ms = 0.0
        if self.admission is not None:
            try:
                step_ms = float(self.admission.exec_ms())
            except Exception:
                step_ms = 0.0           # cold controller: no samples yet
        return {"queue_depth": depth,
                "slot_occupancy": (active / self.max_slots)
                if self.max_slots else 0.0,
                "p99_decode_step_ms": round(step_ms, 3)}

    # -- lifecycle ------------------------------------------------------
    def abort(self, timeout: Optional[float] = None) -> None:
        """The chaos kill switch: fail queued AND in-flight generations
        with :class:`ServerClosedError` instead of letting active slots
        finish — what a SIGKILL looks like to clients holding handles
        (``shutdown(drain=False)`` only fails the QUEUE; in-flight work
        still completes). The in-flight failure lands at the worker's
        next step boundary; tokens already emitted stay emitted — the
        fleet's continuation failover resumes from exactly those. Must
        be called from outside the decode worker (it joins the worker
        thread); the mid-stream chaos injector trips ``_killed`` from
        the emit hook and calls this from a side thread."""
        self._killed = True
        self.shutdown(drain=False, timeout=timeout)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop intake; with ``drain`` (default) finish queued and
        in-flight generations, otherwise fail queued futures
        immediately (in-flight slots still finish their current
        generation). Idempotent."""
        if self._closed:
            return
        self._closed = True
        # a server that was never start()ed has no worker to drain —
        # leaving queued futures pending would hang their clients
        # forever, so they fail typed instead
        self._queue.close(drain=drain and self._started)
        if self._supervisor is not None:
            self._supervisor.stop(timeout=timeout)
        for t in self._workers:
            t.join(timeout=timeout)
        from deeplearning4j_tpu.memory import AllocationsTracker
        AllocationsTracker.get_instance().release("kv_slab",
                                                  self.kv_slab_bytes)
        if self.draft_slab_bytes:
            AllocationsTracker.get_instance().release(
                "kv_slab", self.draft_slab_bytes)
        if self.stats_storage is not None:
            self.metrics.publish(self.stats_storage)
        if self.telemetry is not None:
            self.telemetry.close()

    def __enter__(self) -> "GenerativeServer":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(drain=exc_type is None)


def greedy_decode(spec: GenerativeSpec, prompt, max_new_tokens: int = 16,
                  eos_id: Optional[int] = None,
                  max_seq_len: Optional[int] = None,
                  buckets: Optional[Sequence[int]] = None) -> List[int]:
    """Unbatched single-request greedy decode — the REFERENCE the
    continuous-batching server is pinned against: fresh one-slot slabs,
    the same pow2 prefill bucketing (bucket choice is a deterministic
    function of the prompt length, so both paths run the same prefill
    program), then one decode step per token. Greedy tokens from the
    server match this for every request in a mixed run
    (tests/test_generative.py)."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.ndarray.dtype import DataType
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    msl = int(max_seq_len or spec.max_seq_len)
    bspec = BucketSpec(buckets if buckets is not None
                       else pow2_buckets(msl, n_buckets=msl.bit_length()))
    dt = DataType.from_any(spec.kv_dtype).jnp
    kc = jnp.zeros(spec.kv_shape(1, msl), dt)
    vc = jnp.zeros(spec.kv_shape(1, msl), dt)
    params = dict(spec.params())
    disp = _spec_dispatchers(spec, tuple(spec.kv_shape(1, msl)))
    prefill_j, decode_j = disp["prefill"], disp["decode"]
    L = int(prompt.size)
    if not 1 <= L <= msl - 1:
        raise ValueError(f"prompt length {L} not in [1, {msl - 1}]")
    bucket = bspec.bucket_for(L)
    padded = np.zeros(bucket, np.int32)
    padded[:L] = prompt
    kc, vc, nxt, _ = prefill_j(params, kc, vc,
                               {"tokens": padded, "length": np.int32(L),
                                "slot": np.int32(0)})
    out = [int(nxt)]
    pos = L
    while (len(out) < int(max_new_tokens)
           and not (eos_id is not None and out[-1] == eos_id)
           and pos + 1 < msl):
        io = {"tokens": np.asarray([out[-1]], np.int32),
              "positions": np.asarray([pos], np.int32),
              "active": np.asarray([True])}
        kc, vc, nxt, _ = decode_j(params, kc, vc, io)
        pos += 1
        out.append(int(np.asarray(nxt)[0]))
    return out


__all__ = ["GenerativeSpec", "GenerativeServer", "GenerativeMetrics",
           "GenerationHandle", "GenerationRequest", "GenerationCancelled",
           "SlotAllocator", "greedy_decode"]
