"""Bounded request queue with backpressure, deadlines and graceful drain.

Reference parity: ParallelInference's ObservablesProvider + the
BlockingQueue feeding its worker threads
(parallelism/ParallelInference.java:54, observers/BasicInferenceObserver).
The reference queue is unbounded and can OOM under overload; this one is
the serving-grade version: a hard ``max_queue_len`` past which ``put``
raises :class:`ServerOverloadedError` (load shedding at admission — the
caller gets a typed signal to back off instead of unbounded latency),
per-request deadlines that expire AT DISPATCH (a request that already
missed its deadline is never sent to the device), and a two-phase
``close``: drain (stop intake, finish queued work) or abort (fail
pending futures with :class:`ServerClosedError`).

All coordination is one lock + one condition; consumers block in
:meth:`take`, which is also where coalescing row-budget logic lives so
every consumer (sequential worker or dynamic batcher) shares the same
expiry and shutdown behavior.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional

# the typed-failure contract lives in serving/resilience.py;
# ``ServingError`` is re-exported here because this module historically
# owned it (every ``from serving.queue import ServingError`` stays valid)
from deeplearning4j_tpu.serving.resilience import (RetryableServingError,
                                                   ServingError)


class ServerOverloadedError(RetryableServingError):
    """Admission rejected: the queue is at ``max_queue_len``, the SLO
    admission controller estimates the request cannot meet its deadline,
    or the circuit breaker is open (serving/resilience.py).

    A :class:`~deeplearning4j_tpu.serving.resilience.RetryableServingError`:
    ``retry_after_s`` — when set — is the structured backoff hint (how
    long the shedding condition is expected to persist: estimated queue
    drain, or the breaker's time-to-probe), and the error round-trips
    across process boundaries via ``to_wire()``/``from_wire()``."""


class RequestTimeoutError(ServingError):
    """The request's deadline passed before it was dispatched."""


class ServingTimeoutError(RequestTimeoutError):
    """The request's deadline passed DURING execution: the result
    arrived, but past the SLO — surfaced as a timeout instead of a
    stale success (the reply-time deadline re-check)."""


class ServerClosedError(ServingError):
    """Submitted after ``shutdown()`` (or aborted by a non-drain close)."""


def _now() -> float:
    return time.monotonic()


def collapse_outputs(outputs, squeeze: bool):
    """Shape a request's per-output row arrays into its result: drop the
    row dim for single-example submits, collapse one-output models to a
    bare array. The ONE place defining the result contract for all
    modes (BATCHED scatter, SEQUENTIAL, INPLACE)."""
    sl = [o[0] for o in outputs] if squeeze else list(outputs)
    return sl if len(sl) > 1 else sl[0]


@dataclass
class InferenceRequest:
    """One queued unit of work: a (rows, ...) feature array + its future."""

    x: object                       # array or per-input list; leading
                                    # dim of each array = rows
    future: Future
    rows: int
    enqueue_t: float = field(default_factory=_now)
    deadline: Optional[float] = None    # absolute time.monotonic(), or None
    squeeze: bool = False               # single-example submit: drop row dim
    id: int = 0
    requeues: int = 0                   # crash-recovery requeues (max 1)

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else _now()) > self.deadline

    def time_out(self) -> None:
        if not self.future.done():
            self.future.set_exception(RequestTimeoutError(
                f"request {self.id} expired after "
                f"{(_now() - self.enqueue_t) * 1000:.1f} ms in queue"))

    def fail(self, exc: BaseException) -> None:
        if not self.future.done():
            self.future.set_exception(exc)

    def complete(self, outputs) -> bool:
        """Resolve with this request's row slices (see collapse_outputs)
        — unless the deadline passed while the batch executed: a request
        that expires DURING exec must not complete as a stale success,
        so its future gets :class:`ServingTimeoutError` instead and
        this returns False (the caller records the timeout)."""
        if self.expired():
            if not self.future.done():
                self.future.set_exception(ServingTimeoutError(
                    f"request {self.id} missed its deadline by "
                    f"{(_now() - self.deadline) * 1000:.1f} ms during "
                    f"execution"))
            return False
        if not self.future.done():
            self.future.set_result(collapse_outputs(outputs, self.squeeze))
        return True


class RequestQueue:
    """FIFO of :class:`InferenceRequest` with bounded depth.

    Producers call :meth:`put` (non-blocking; raises on overload/closed).
    Consumers call :meth:`take`, which blocks until live work, shutdown,
    or timeout, and pops greedily up to a row budget so a batcher can
    coalesce several requests in one call.
    """

    def __init__(self, max_queue_len: int = 256,
                 on_timeout=None):
        if max_queue_len <= 0:
            raise ValueError("max_queue_len must be positive")
        self.max_queue_len = int(max_queue_len)
        self._dq: deque = deque()
        self._rows = 0                  # queued rows (admission estimates)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self._drain = True
        self._timed_out = 0             # expired-at-dispatch count
        self._on_timeout = on_timeout   # callback(req) per expiry

    # -- producer side --------------------------------------------------
    def put(self, req: InferenceRequest) -> None:
        with self._lock:
            if self._closed:
                raise ServerClosedError("request queue is closed")
            if len(self._dq) >= self.max_queue_len:
                raise ServerOverloadedError(
                    f"queue full ({self.max_queue_len} pending); retry "
                    f"with backoff")
            self._dq.append(req)
            self._rows += req.rows
            self._not_empty.notify()

    def requeue(self, req: InferenceRequest) -> None:
        """Put an already-admitted request back at the FRONT of the
        queue (crash recovery: it already waited its turn). Bypasses
        the capacity check — the request was admitted once and its
        future is outstanding; a bounds rejection here would drop it.
        Allowed while a drain is in progress (queued work is still
        being served); raises :class:`ServerClosedError` only after a
        non-drain close."""
        with self._lock:
            if self._closed and not self._drain:
                raise ServerClosedError(
                    "request queue is closed without drain")
            self._dq.appendleft(req)
            self._rows += req.rows
            self._not_empty.notify()

    # -- consumer side --------------------------------------------------
    def take(self, max_rows: int, timeout: Optional[float] = None,
             strict: bool = False) -> List[InferenceRequest]:
        """Pop live requests whose total rows fit ``max_rows``.

        Blocks up to ``timeout`` seconds (None = until work or close) for
        the FIRST request; never blocks for follow-ups — it greedily pops
        already-queued requests while they fit the row budget. Requests
        whose deadline has passed are completed with
        :class:`RequestTimeoutError` and skipped. Returns ``[]`` on
        timeout or when the queue is closed and empty.

        ``strict=False`` lets a single request larger than ``max_rows``
        through as the sole result (a sequential worker must serve any
        size); ``strict=True`` never exceeds the budget (a batcher
        topping up a partially full batch must not overshoot it).

        Expired futures are completed OUTSIDE the queue lock: a user
        done-callback may re-enter the queue (e.g. submit a retry), and
        completing under the non-reentrant lock would deadlock it.
        """
        end = None if timeout is None else _now() + timeout
        while True:
            expired: List[InferenceRequest] = []
            got: List[InferenceRequest] = []
            done = False
            with self._not_empty:
                got = self._pop_live_locked(max_rows, strict, expired)
                if got or self._closed:
                    done = True
                else:
                    remaining = None if end is None else end - _now()
                    if remaining is not None and remaining <= 0:
                        done = True
                    elif not expired:
                        # nothing to report yet: block for new work
                        self._not_empty.wait(remaining)
            for req in expired:          # lock released: safe to complete
                req.time_out()
                if self._on_timeout is not None:
                    self._on_timeout(req)
            if done:
                return got

    def _pop_live_locked(self, max_rows: int, strict: bool,
                         expired: List[InferenceRequest]
                         ) -> List[InferenceRequest]:
        out: List[InferenceRequest] = []
        rows = 0
        now = _now()
        while self._dq:
            head = self._dq[0]
            if head.expired(now):
                self._dq.popleft()
                self._rows -= head.rows
                self._timed_out += 1
                expired.append(head)     # completed by take(), post-lock
                continue
            if (out or strict) and rows + head.rows > max_rows:
                break
            self._dq.popleft()
            self._rows -= head.rows
            out.append(head)
            rows += head.rows
            if rows >= max_rows:
                break
        return out

    # -- lifecycle ------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Stop intake. ``drain=True`` lets consumers finish queued work;
        ``drain=False`` fails every pending future with
        :class:`ServerClosedError` immediately (outside the lock — see
        take())."""
        aborted: List[InferenceRequest] = []
        with self._lock:
            self._closed = True
            self._drain = drain
            if not drain:
                aborted = list(self._dq)
                self._dq.clear()
                self._rows = 0
            self._not_empty.notify_all()
        for req in aborted:
            req.fail(ServerClosedError(
                "server shut down before this request was served"))

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def finished(self) -> bool:
        """Closed and nothing left to serve — consumer exit condition."""
        with self._lock:
            return self._closed and not self._dq

    def pending(self) -> int:
        with self._lock:
            return len(self._dq)

    def pending_rows(self) -> int:
        """Total rows queued — the admission controller's backlog unit
        (dispatches drain up to ``max_batch_size`` rows at a time)."""
        with self._lock:
            return self._rows

    def timed_out_count(self) -> int:
        return self._timed_out

    def __len__(self) -> int:
        return self.pending()
