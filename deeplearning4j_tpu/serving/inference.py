"""ParallelInference: a thread-safe, batching model server.

Reference parity: deeplearning4j-parallelwrapper's ParallelInference
(parallelism/ParallelInference.java:54) — the L7 layer that turns a
trained network into a shared inference service. The reference clones
the model once per worker thread and pins workers to devices; modes:

- ``SEQUENTIAL``: each request runs alone, in arrival order;
- ``BATCHED``: concurrent requests coalesce into one model invocation
  (BatchedInferenceObservable);
- ``INPLACE``: no queue — the holder model is invoked directly in the
  calling thread (lowest latency, no coalescing).

TPU-native redesign: worker replicas do NOT clone parameters — they
share ONE inference graph whose jit cache (one compiled XLA program per
bucket shape, see serving/batching.py) is the shared "replica". Device
execution is serialized behind a lock (a single XLA stream saturates
the chip; thread-level concurrency buys host-side overlap of padding /
scatter with device compute, not parallel kernels). Backpressure,
deadlines and drain come from serving/queue.py; counters and latency
histograms from serving/metrics.py; an optional per-batch
ProfilerSession drops xplane traces for the profiler/ tooling.
"""
from __future__ import annotations

import enum
import os
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, NamedTuple, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.serving.batching import (Batch, DynamicBatcher,
                                                 pad_to_bucket,
                                                 scatter_rows)
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.queue import (
    InferenceRequest, RequestQueue, RequestTimeoutError, ServerClosedError,
    ServerOverloadedError, ServingError, ServingTimeoutError,
    collapse_outputs)
from deeplearning4j_tpu.serving.resilience import (
    AdmissionController, CircuitBreaker, InflightSlot, PoisonedRequestError,
    ReloadFailedError, ResilienceConfig, WorkerSupervisor)


class InferenceMode(enum.Enum):
    """Request scheduling policy (reference: ParallelInference
    InferenceMode)."""

    SEQUENTIAL = "sequential"
    BATCHED = "batched"
    INPLACE = "inplace"


class ServingSpec(NamedTuple):
    """A network's serving contract: inference graph + IO names + the
    sync that pulls current trained parameters into it (produced by
    ``MultiLayerNetwork.serving_spec()`` / ``ComputationGraph
    .serving_spec()``)."""

    sd: object                      # inference-mode SameDiff
    input_names: List[str]
    output_names: List[str]
    sync: Callable[[], None]


def _extract_spec(model) -> ServingSpec:
    if hasattr(model, "serving_spec"):
        return ServingSpec(*model.serving_spec())
    raise TypeError(
        f"{type(model).__name__} is not servable: expected a "
        f"MultiLayerNetwork / ComputationGraph (anything exposing "
        f"serving_spec())")


class ParallelInference:
    """Shared, thread-safe inference front-end over a trained network.

    ::

        pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                               max_batch_size=32, max_delay_ms=3.0)
        y = pi.output(x)                  # blocking
        fut = pi.submit(x)                # async -> Future
        ...
        pi.shutdown()                     # drains the queue

    ``output``/``submit`` accept a (rows, *features) array, one
    unbatched example (*features), or — for multi-input graphs in
    SEQUENTIAL/INPLACE mode — a tuple of per-input arrays. Results
    mirror the wrapped model's ``output()`` (single array, or a list for
    multi-output graphs). Overload raises
    :class:`ServerOverloadedError` at submit; expired deadlines surface
    as :class:`RequestTimeoutError` from the future.

    ``warmup_buckets`` kills the serving cold-start: ``True`` AOT-
    precompiles every batching bucket shape at construction (before any
    worker serves), a sequence of ints precompiles exactly those row
    counts — so the first live request of each bucket never waits
    seconds on XLA (the p99 cliff a lazy bucket miss causes). Warmed
    shapes are bit-identical to lazily-compiled ones and the
    ``compiles`` metric stays 0 for them (``warmup_compiles`` counts
    the prebuilt set). See docs/cold_start.md.

    ``resilience=True`` (or a :class:`ResilienceConfig`) arms the
    serving resilience rail (serving/resilience.py, docs/serving.md
    "Resilience"): SLO admission shedding, a circuit breaker on
    consecutive exec failures (surfaced through /healthz and /readyz),
    supervised workers with crash requeue, and bisecting poisoned-batch
    isolation. ``reload_from(manager)`` hot-swaps parameters from a
    committed checkpoint with a canary exec and automatic rollback.
    """

    def __init__(self, model,
                 mode: InferenceMode = InferenceMode.BATCHED,
                 workers: int = 2,
                 max_batch_size: int = 32,
                 max_delay_ms: float = 5.0,
                 max_queue_len: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 default_timeout_ms: Optional[float] = None,
                 stats_storage=None,
                 profile_dir: Optional[str] = None,
                 warmup_buckets=None,
                 telemetry_port: Optional[int] = None,
                 resilience=None,
                 memory_sample_every: Optional[int] = 64,
                 analyze=True):
        self.model = model
        self.mode = InferenceMode(mode)
        self.max_batch_size = int(max_batch_size)
        if self.mode is InferenceMode.INPLACE and \
                default_timeout_ms is not None:
            raise ValueError("INPLACE mode executes synchronously in the "
                             "calling thread — there is no queue wait for "
                             "default_timeout_ms to bound")
        self.default_timeout_ms = default_timeout_ms
        self.metrics = ServingMetrics()
        self.stats_storage = stats_storage
        self.profile_dir = profile_dir
        self._spec = _extract_spec(model)
        # pre-compile static analysis of the serving graph (analyze/,
        # docs/static_analysis.md): shape/hygiene/numerics findings as
        # named diagnostics BEFORE the first bucket compiles. True =
        # warn on error findings; "strict" = raise GraphAnalysisError;
        # False = off. The report lands in self.analysis and — when a
        # stats_storage is attached — as a {"type": "analysis"} record.
        self.analysis = None
        if analyze:
            from deeplearning4j_tpu.analyze import (GraphAnalysisWarning,
                                                    analyze_inference)
            self.analysis = analyze_inference(
                self._spec.sd, outputs=self._spec.output_names,
                inputs=self._spec.input_names)
            if stats_storage is not None:
                stats_storage.put(self.analysis.to_record())
            errs = self.analysis.errors()
            if errs:
                if str(analyze).lower() == "strict":
                    self.analysis.raise_if_errors()
                import warnings as _warnings
                _warnings.warn(
                    f"serving-graph static analysis found {len(errs)} "
                    f"error(s); pi.analysis.render() has the located "
                    f"diagnostics:\n"
                    + "\n".join(f.render() for f in errs[:5]),
                    GraphAnalysisWarning, stacklevel=2)
        if self.mode is InferenceMode.BATCHED and \
                len(self._spec.input_names) != 1:
            raise ValueError(
                f"BATCHED mode needs a single-input model; "
                f"{type(model).__name__} has inputs "
                f"{self._spec.input_names} — use SEQUENTIAL or INPLACE")
        self._ph_shapes = [self._placeholder_shape(n)
                           for n in self._spec.input_names]
        self._feat_rank = (len(self._ph_shapes[0])
                           if self._ph_shapes[0] is not None else None)
        self._exec_lock = threading.Lock()
        self._shapes_seen = set()
        # HBM telemetry at serving batch boundaries (monitor/memstats):
        # every Nth _execute publishes a {"type": "memory"} record into
        # stats_storage — pure host reads, off the exec lock. None = off.
        self._mem_every = (max(1, int(memory_sample_every))
                           if memory_sample_every else None)
        self._mem_count = 0
        self._req_id = 0
        self._id_lock = threading.Lock()
        self._closed = False
        self._spec.sync()           # pull current trained params once
        self._queue = RequestQueue(
            max_queue_len,
            on_timeout=lambda req: self.metrics.record_timeout("deadline"))
        self._batcher = DynamicBatcher(
            self._queue, max_batch_size=self.max_batch_size,
            max_delay_ms=max_delay_ms, buckets=buckets) \
            if self.mode is InferenceMode.BATCHED else None
        self.max_queue_len = int(max_queue_len)
        # resilience rail (serving/resilience.py, docs/serving.md
        # "Resilience"): SLO admission + circuit breaker here, worker
        # supervision at spawn below, bisection in _exec_group
        self.resilience = ResilienceConfig.normalize(resilience)
        self.admission: Optional[AdmissionController] = None
        self.breaker: Optional[CircuitBreaker] = None
        if self.resilience is not None:
            if self.resilience.admission:
                self.admission = AdmissionController(
                    window=self.resilience.window,
                    percentile=self.resilience.percentile,
                    min_samples=self.resilience.min_exec_samples)
            if self.resilience.breaker_failure_threshold > 0:
                self.breaker = CircuitBreaker(
                    failure_threshold=(
                        self.resilience.breaker_failure_threshold),
                    reset_timeout_s=self.resilience.breaker_reset_s,
                    on_transition=self._breaker_transition)
                self.metrics.set_resilience(breaker_state="closed")
        # live telemetry endpoint (monitor/server.py): /metrics serves
        # the serving counters/latency lanes via a scrape hook (pull
        # model — no publisher thread), /readyz reports queue depth and
        # goes 503 on overload or shutdown (the SLO shed-load signal).
        # None = off; 0 = pick a free loopback port (telemetry.url).
        self.telemetry = None
        if telemetry_port is not None:
            from deeplearning4j_tpu.monitor.server import TelemetryServer
            self.telemetry = TelemetryServer(storage=stats_storage,
                                             port=telemetry_port)
            self.telemetry.add_scrape_hook(
                lambda reg: reg.fold_serving(self.metrics))
            self.telemetry.add_health_provider("serving",
                                               self._telemetry_health)
        self.warmup_report: Optional[dict] = None
        if warmup_buckets:
            # before any worker thread exists: warmed shapes must be in
            # the execution cache before the first request can race them
            self.warmup(None if warmup_buckets is True else warmup_buckets)
        self._workers: List[threading.Thread] = []
        self._supervisor: Optional[WorkerSupervisor] = None
        if self.mode is not InferenceMode.INPLACE:
            if self.resilience is not None and self.resilience.supervise:
                self._supervisor = WorkerSupervisor(
                    spawn=self._spawn_worker,
                    n_workers=max(1, int(workers)),
                    queue=self._queue, metrics=self.metrics,
                    backoff_base_s=self.resilience.worker_backoff_base_s,
                    backoff_max_s=self.resilience.worker_backoff_max_s,
                    publish=self._publish_fault,
                    # a worker that dies holding the half-open probe
                    # must not gate dispatch forever
                    on_crash=(self.breaker.release
                              if self.breaker is not None else None))
            else:
                for i in range(max(1, int(workers))):
                    self._workers.append(
                        self._spawn_worker(i, InflightSlot()))

    # ------------------------------------------------------------------
    def _placeholder_shape(self, input_name: str):
        try:
            shape = self._spec.sd._vars[input_name].shape
            return tuple(shape) if shape is not None else None
        except Exception:
            return None

    def _next_id(self) -> int:
        with self._id_lock:
            self._req_id += 1
            return self._req_id

    # -- AOT warmup (compilecache/, docs/cold_start.md) -----------------
    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """AOT-precompile the serving program for each bucket row count
        so live traffic never waits on XLA.

        ``buckets=None`` takes the batching tier's bucket spec (BATCHED
        mode) or the default pow2 ladder up to ``max_batch_size``
        (SEQUENTIAL/INPLACE — where requests execute at their own row
        count, so only warmed sizes are covered; off-ladder sizes still
        compile lazily). Requires static feature dims on every input.
        Returns (and stores as ``warmup_report``) the bucket list, wall
        seconds, and the compile/cache-hit accounting — on a warm
        restart with a persistent cache configured, every entry is a
        cache hit and warmup is ~free."""
        import time as _time
        from deeplearning4j_tpu.compilecache import (COMPILE_STATS,
                                                     install_compile_watcher)
        install_compile_watcher()
        if buckets is None:
            if self._batcher is not None:
                buckets = self._batcher.spec.buckets
            else:
                from deeplearning4j_tpu.serving.batching import pow2_buckets
                # single-example requests are the common case and run at
                # their own row count in these modes — always include
                # bucket 1 (the pow2 ladder stops halving above it for
                # large max_batch_size)
                buckets = (1,) + tuple(pow2_buckets(self.max_batch_size))
        bucket_list = sorted({int(b) for b in buckets})
        if not bucket_list or bucket_list[0] <= 0:
            raise ValueError(f"invalid warmup buckets {buckets!r}")
        for name, shp in zip(self._spec.input_names, self._ph_shapes):
            if shp is None or any(d is None or d == -1 for d in shp[1:]):
                raise ValueError(
                    f"cannot warm up input {name!r}: feature dims {shp} "
                    f"are not static — pass concrete shapes to the "
                    f"model, or skip warmup for this graph")
        mark = COMPILE_STATS.mark()
        t0 = _time.perf_counter()
        for b in bucket_list:
            ph = {name: (b,) + tuple(int(d) for d in shp[1:])
                  for name, shp in zip(self._spec.input_names,
                                       self._ph_shapes)}
            # _exec_lock: warmup() is public and may be called on a LIVE
            # server (pre-warming a new bucket) — the graph's compile
            # caches are only safe under the same lock _execute holds
            with self._exec_lock, \
                    _tracer.span("serving.warmup", cat="serving", bucket=b):
                from deeplearning4j_tpu.monitor import memstats
                self._spec.sd.precompile_output(ph,
                                                self._spec.output_names)
                # headroom guard (docs/serving.md "Resilience"): refuse
                # to mark a bucket warm whose compiled plan (temps +
                # outputs — arguments are the already-resident params)
                # exceeds the projected HBM headroom; a typed refusal
                # HERE beats a RESOURCE_EXHAUSTED on the first live
                # request that lands in the bucket. No-op where the
                # backend reports no bytes_limit (CPU). Looked up by
                # the exact shape SIGNATURE, not the label — labels
                # like "output_b4" alias across models in one process.
                plan = memstats.PLANS.get(tuple(sorted(
                    (n, tuple(shape)) for n, shape in ph.items())))
                if plan is not None:
                    need = int(plan.temp_bytes or 0) \
                        + int(plan.output_bytes or 0)
                    memstats.check_headroom(
                        need, f"serving warmup bucket {b} "
                              f"({type(self.model).__name__})")
                # mark the shape as seen (under the SAME lock hold — a
                # worker dispatching this bucket between compile and
                # mark would count a spurious lazy `compiles`) so the
                # metric counts only genuinely-unwarmed traffic
                # compiles; already-seen buckets (a repeat warmup() on
                # a live server) must not re-count
                sig = tuple(tuple(ph[n]) for n in self._spec.input_names)
                if sig not in self._shapes_seen:
                    self._shapes_seen.add(sig)
                    self.metrics.inc("warmup_compiles")
        self.warmup_report = {
            "buckets": bucket_list,
            "seconds": round(_time.perf_counter() - t0, 4),
            **{k: v for k, v in COMPILE_STATS.delta(mark).items()
               if k in ("backend_compiles", "cache_hits", "cache_misses")}}
        return self.warmup_report

    def _prepare(self, x) -> tuple:
        """-> (list of per-input arrays with a batch dim, squeeze flag)."""
        if isinstance(x, (tuple, list)):
            arrs = [np.asarray(a) for a in x]
        else:
            arrs = [np.asarray(x)]
        if len(arrs) != len(self._spec.input_names):
            raise ValueError(
                f"model has {len(self._spec.input_names)} inputs "
                f"{self._spec.input_names}; got {len(arrs)} arrays")
        squeeze = False
        if len(arrs) == 1 and self._feat_rank is not None and \
                arrs[0].ndim == self._feat_rank - 1:
            arrs = [arrs[0][None]]      # single example: add the row dim
            squeeze = True
        if arrs[0].ndim == 0:
            raise ValueError("scalar input is not a request")
        # reject wrong feature shapes at admission: a mismatched request
        # must not reach a coalesced batch (it would fail the whole
        # dispatch, or worse, a worker thread)
        for arr, ph, name in zip(arrs, self._ph_shapes,
                                 self._spec.input_names):
            if ph is None:
                continue
            if arr.ndim != len(ph) or any(
                    d is not None and d != a
                    for d, a in zip(ph[1:], arr.shape[1:])):
                raise ValueError(
                    f"input {name!r} expects shape {ph} (leading dim = "
                    f"rows); got {arr.shape}")
        return arrs, squeeze

    # -- execution core (shared by every mode/worker) -------------------
    def _execute(self, features: List[np.ndarray],
                 real_rows: Optional[int] = None) -> List[np.ndarray]:
        """Run one forward. One compiled program per distinct input
        shape, shared by all workers (the jit cache lives on the
        inference graph); the lock serializes device execution AND makes
        the graph's internal caches safe under concurrent callers."""
        sig = tuple(tuple(f.shape) for f in features)
        rows = features[0].shape[0]
        real = rows if real_rows is None else real_rows
        ph = dict(zip(self._spec.input_names, features))
        t0 = time.perf_counter()
        with self._exec_lock, \
                _tracer.span("serving.exec", cat="serving", rows=real,
                             padding=rows - real):
            first_exec = sig not in self._shapes_seen
            if first_exec:
                self._shapes_seen.add(sig)
                self.metrics.inc("compiles")
            prof = self._profiler_session()
            try:
                # blocking device boundary: the stall watchdog
                # (integrity/watchdog.py) arms an adaptive deadline so
                # a wedged exec dumps forensics + flips /healthz
                # instead of hanging the lane silently; a first
                # (compiling) shape gets the compile grace
                from deeplearning4j_tpu.integrity.watchdog import \
                    guard as _wd_guard
                with _wd_guard("serving_execute", first=first_exec):
                    res = self._spec.sd.output(ph,
                                               self._spec.output_names)
            except Exception as e:
                # RESOURCE_EXHAUSTED → structured OOM with forensics
                # (per-device usage + the bucket program) instead of a
                # raw backend crash; published on the fault rail so
                # /healthz flips 503 (docs/observability.md)
                from deeplearning4j_tpu.monitor import memstats
                if memstats.is_resource_exhausted(e):
                    err = memstats.oom_error(e, program=f"serving_b{rows}")
                    self._publish_fault("oom", program=f"serving_b{rows}",
                                        rows=rows, error=repr(e))
                    raise err from e
                raise
            finally:
                if prof is not None:
                    prof.__exit__(None, None, None)
        outs = [np.asarray(res[n].to_numpy())
                for n in self._spec.output_names]
        exec_ms = (time.perf_counter() - t0) * 1000.0
        self.metrics.observe_batch(rows=real, padding=rows - real,
                                   exec_ms=exec_ms)
        if self.admission is not None:
            self.admission.observe(exec_ms)
        if self._mem_every is not None and self.stats_storage is not None:
            with self._id_lock:     # workers race this tail concurrently
                self._mem_count += 1
                fire = self._mem_count % self._mem_every == 0
            if fire:
                from deeplearning4j_tpu.monitor import memstats
                try:
                    self.stats_storage.put(
                        memstats.memory_record(source="serving"))
                except Exception:
                    pass    # a broken stats sink must not fail requests
        return outs

    def _profiler_session(self):
        if not self.profile_dir:
            return None
        from deeplearning4j_tpu.profiler import ProfilerSession
        n = self.metrics.counters["batches_dispatched"]
        sess = ProfilerSession(
            log_dir=os.path.join(self.profile_dir, f"batch_{n:06d}"))
        try:
            return sess.__enter__()
        except Exception:
            return None             # profiling is best-effort

    # -- worker loops ---------------------------------------------------
    def _spawn_worker(self, index: int, slot: InflightSlot
                      ) -> threading.Thread:
        t = threading.Thread(target=self._worker_main, args=(slot,),
                             name=f"ParallelInference-{index}",
                             daemon=True)
        t.start()
        return t

    def _worker_main(self, slot: InflightSlot) -> None:
        try:
            self._worker_loop(slot)
            slot.exited = True          # clean drain: do not restart
        except BaseException as e:      # noqa: BLE001 — supervisor's cue
            slot.crashed = e            # the supervisor requeues slot's
            #                             in-flight and respawns; without
            #                             one the crash is at least
            #                             visible in the failure metrics

    def _worker_loop(self, slot: InflightSlot) -> None:
        if self.mode is InferenceMode.BATCHED:
            loop_body = self._batched_step
        else:
            loop_body = self._sequential_step
        # gate on the CONFIG, not self._supervisor: the supervisor's
        # constructor spawns these threads before ParallelInference's
        # `self._supervisor =` assignment completes, so reading the
        # attribute here would race to None and permanently disable the
        # die-after-N escalation for every construction-time worker
        max_con = (self.resilience.worker_max_consecutive_errors
                   if self.resilience is not None and
                   self.resilience.supervise else None)
        consecutive = 0
        while True:
            try:
                progressed = loop_body(slot)
                consecutive = 0
                if progressed:
                    # evidence for the supervisor: this worker actually
                    # dispatched (a crash-looping worker is briefly
                    # alive without ever getting here)
                    slot.progressed = True
            except Exception as e:
                # last-ditch guard: per-request failure paths live
                # inside the step fns; anything reaching here is
                # unexpected. It is RECORDED (metrics + a fault-rail
                # record), never swallowed silently — and under a
                # supervisor a persistent failure kills the worker so
                # a fresh one can take over.
                consecutive += 1
                if self.breaker is not None:
                    # the step may have died while HOLDING the half-open
                    # probe (e.g. next_batch raised after acquire) — a
                    # leaked probe gates every worker's dispatch forever
                    self.breaker.release()
                stranded = slot.requests
                slot.requests = None
                for r in stranded or []:
                    r.fail(e)       # no-op for already-resolved futures
                self.metrics.record_failure(
                    e, cause="worker_guard",
                    n=max(1, len(stranded or [])))
                self._publish_fault("worker_error", cause="worker_guard",
                                    error=repr(e), consecutive=consecutive,
                                    stranded=len(stranded or []))
                if max_con is not None and consecutive >= max_con:
                    raise
                time.sleep(0.01)
                progressed = True
            if not progressed and self._queue.finished:
                return

    def _breaker_gate(self) -> Optional[bool]:
        """Dispatch-side breaker check. None → proceed (probe acquired
        if half-open); True/False → return that from the step fn (the
        breaker is open: nothing was popped, or the drain shed)."""
        if self.breaker is None:
            return None
        allowed, wait_s = self.breaker.acquire()
        if allowed:
            return None
        if self._queue.closed:
            # drain under an open breaker: futures must not be held
            # hostage until the probe window — shed them typed
            reqs = self._queue.take(self.max_batch_size, timeout=0,
                                    strict=False)
            if not reqs:
                return False
            err = ServerOverloadedError(
                "circuit breaker open during shutdown drain",
                retry_after_s=round(wait_s, 3))
            for r in reqs:
                r.fail(err)
            self.metrics.inc("requests_shed", len(reqs))
            return True
        time.sleep(min(0.05, max(wait_s, 0.001)))
        return False

    def _batched_step(self, slot: InflightSlot) -> bool:
        gated = self._breaker_gate()
        if gated is not None:
            return gated
        # the span is discarded on an empty poll — an idle server must
        # not fill the trace ring with 50 ms waits
        with _tracer.span("serving.batch", cat="serving") as bsp:
            batch = self._batcher.next_batch(poll_timeout=0.05)
            if batch is None:
                bsp.discard()
                if self.breaker is not None:
                    self.breaker.release()      # unused half-open probe
                return False
            bsp.set(rows=batch.rows, bucket=batch.bucket,
                    requests=len(batch.requests))
        # slot stays populated if an exception ESCAPES (worker death /
        # guard): the supervisor requeues exactly what was in flight.
        # It is cleared only once every popped future is resolved.
        slot.requests = batch.requests
        if self.resilience is not None and \
                self.resilience.isolate_poisoned:
            self._exec_group(batch.requests, created_t=batch.created_t,
                             features=batch.features)
            slot.requests = None
            return True
        try:
            outs = self._execute([batch.features], real_rows=batch.rows)
        except Exception as e:
            if self.breaker is not None:
                self.breaker.on_failure()
            self.metrics.inc("exec_faults")
            self.metrics.record_failure(e, n=len(batch.requests))
            batch.fail(e)
            slot.requests = None
            return True
        if self.breaker is not None:
            self.breaker.on_success()
        self._resolve_rows(batch.requests, outs, batch.created_t)
        slot.requests = None
        return True

    def _sequential_step(self, slot: InflightSlot) -> bool:
        gated = self._breaker_gate()
        if gated is not None:
            return gated
        reqs = self._queue.take(max_rows=1, timeout=0.05)
        if not reqs:
            if self.breaker is not None:
                self.breaker.release()          # unused half-open probe
            return False
        req = reqs[0]
        slot.requests = reqs            # cleared only once resolved (see
        t_pop = time.monotonic()        # _batched_step)
        try:
            outs = self._execute(list(req.x))
        except Exception as e:
            if self.breaker is not None:
                self.breaker.on_failure()
            self.metrics.inc("exec_faults")
            self.metrics.record_failure(e)
            req.fail(e)
            slot.requests = None
            return True
        if self.breaker is not None:
            self.breaker.on_success()
        with _tracer.span("serving.reply", cat="serving", requests=1):
            completed = req.complete(outs)
        slot.requests = None
        if not completed:
            self.metrics.record_timeout("deadline")
            return True
        done = time.monotonic()
        self.metrics.observe_request(
            queue_wait_ms=(t_pop - req.enqueue_t) * 1000.0,
            e2e_ms=(done - req.enqueue_t) * 1000.0)
        return True

    # -- resilient dispatch: bisecting poisoned-batch isolation ---------
    def _resolve_rows(self, reqs: Sequence[InferenceRequest],
                      outs: List[np.ndarray], created_t: float) -> None:
        """Scatter per-request row slices to futures, re-checking each
        deadline at reply time (a request that expired during exec gets
        ServingTimeoutError, not a stale success), and record latency
        for the completed ones."""
        with _tracer.span("serving.reply", cat="serving",
                          requests=len(reqs)):
            expired_ids = {id(r) for r in scatter_rows(reqs, outs)}
        if expired_ids:
            self.metrics.record_timeout("deadline", n=len(expired_ids))
        done = time.monotonic()
        for req in reqs:
            if id(req) in expired_ids:
                continue
            self.metrics.observe_request(
                queue_wait_ms=(created_t - req.enqueue_t) * 1000.0,
                e2e_ms=(done - req.enqueue_t) * 1000.0)

    def _nonfinite_requests(self, reqs: Sequence[InferenceRequest],
                            outs: List[np.ndarray]
                            ) -> List[InferenceRequest]:
        """Requests whose output rows contain non-finite values — how a
        NaN/garbage input actually manifests (XLA does not raise on it).
        Non-floating outputs (class indices, ...) are skipped."""
        float_outs = [o for o in outs
                      if np.issubdtype(np.asarray(o).dtype, np.floating)]
        if not float_outs:
            return []
        bad: List[InferenceRequest] = []
        off = 0
        for req in reqs:
            for o in float_outs:
                if not np.all(np.isfinite(o[off:off + req.rows])):
                    bad.append(req)
                    break
            off += req.rows
        return bad

    def _group_features(self, reqs: Sequence[InferenceRequest]) -> tuple:
        rows = sum(r.rows for r in reqs)
        bucket = self._batcher.spec.bucket_for(rows)
        features = pad_to_bucket(
            [np.asarray(r.x[0] if isinstance(r.x, (list, tuple))
                        else r.x) for r in reqs], bucket)
        return features, rows

    def _exec_group(self, reqs: List[InferenceRequest], created_t: float,
                    features: Optional[np.ndarray] = None,
                    top: bool = True) -> None:
        """Bisecting dispatch: execute ``reqs`` as one padded program;
        on failure (a raise, or — with ``check_finite_outputs`` — any
        non-finite output row) split in half and retry each side, down
        to singletons, so exactly the poisoned request is quarantined
        with :class:`PoisonedRequestError` while every healthy request
        resolves bit-identically to a fault-free run (row independence
        of the batched forward + bucket padding, docs/serving.md).
        Every request's future is resolved by the time this returns.

        Only the TOP-level exec outcome feeds the circuit breaker: the
        bisection's internal retries of one poisoned raising request
        would otherwise count log2(batch)+retries consecutive
        "failures" and open the breaker on a healthy device."""
        cfg = self.resilience
        rows = sum(r.rows for r in reqs)
        if features is None:
            features, rows = self._group_features(reqs)
        exc: Optional[BaseException] = None
        outs = None
        try:
            outs = self._execute([features], real_rows=rows)
        except Exception as e:
            exc = e
            self.metrics.inc("exec_faults")
            if top and self.breaker is not None:
                self.breaker.on_failure()
        if outs is not None:
            if top and self.breaker is not None:
                self.breaker.on_success()
            bad = self._nonfinite_requests(reqs, outs) \
                if cfg.check_finite_outputs else []
            if not bad:
                self._resolve_rows(reqs, outs, created_t)
                return
        if len(reqs) == 1:
            req = reqs[0]
            if exc is not None:
                # a RAISING singleton may have hit a transient exec
                # fault rather than carrying poison — retry before
                # declaring it poisoned (a non-finite OUTPUT is a pure
                # function of the input; no retry can change it)
                for _ in range(max(0, cfg.single_retries)):
                    try:
                        outs = self._execute([features], real_rows=rows)
                    except Exception as e:
                        exc = e
                        self.metrics.inc("exec_faults")
                        continue
                    if not (cfg.check_finite_outputs and
                            self._nonfinite_requests(reqs, outs)):
                        self._resolve_rows(reqs, outs, created_t)
                        return
                    break
            err = PoisonedRequestError(
                f"request {req.id} quarantined: "
                + (f"exec fails on it alone ({exc!r})" if exc is not None
                   else "its output rows are non-finite"),
                request_id=req.id)
            err.__cause__ = exc
            req.fail(err)
            self.metrics.inc("poisoned_quarantined")
            self.metrics.record_failure(err, cause="poisoned")
            self._publish_fault(
                "quarantine", request_id=req.id,
                error=repr(exc) if exc is not None
                else "non-finite outputs")
            return
        self.metrics.inc("bisect_splits")
        mid = len(reqs) // 2
        self._exec_group(reqs[:mid], created_t, top=False)
        self._exec_group(reqs[mid:], created_t, top=False)

    # -- client API -----------------------------------------------------
    def submit(self, x, timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the model
        output rows for exactly this request. Raises
        :class:`ServerOverloadedError` (queue full) or
        :class:`ServerClosedError` (after shutdown) at the call site."""
        if self._closed:
            raise ServerClosedError("ParallelInference is shut down")
        features, squeeze = self._prepare(x)
        if self.mode is InferenceMode.BATCHED and \
                features[0].shape[0] > self.max_batch_size:
            raise ValueError(
                f"request of {features[0].shape[0]} rows exceeds "
                f"max_batch_size {self.max_batch_size}; split it or call "
                f"the model's output() directly")
        self.metrics.inc("requests_submitted")
        if self.mode is InferenceMode.INPLACE:
            if timeout_ms is not None:
                raise ValueError("INPLACE mode has no queue; timeout_ms "
                                 "is not applicable (use BATCHED or "
                                 "SEQUENTIAL for deadline-bounded "
                                 "requests)")
            return self._inplace(features, squeeze)
        timeout_ms = timeout_ms if timeout_ms is not None \
            else self.default_timeout_ms
        deadline = time.monotonic() + timeout_ms / 1000.0 \
            if timeout_ms is not None else None
        self._admit(features[0].shape[0], timeout_ms)
        fut: Future = Future()
        req = InferenceRequest(x=features, future=fut,
                               rows=features[0].shape[0], deadline=deadline,
                               squeeze=squeeze, id=self._next_id())
        with _tracer.span("serving.enqueue", cat="serving", id=req.id,
                          rows=req.rows):
            try:
                self._queue.put(req)
            except ServerOverloadedError:
                self.metrics.inc("requests_rejected")
                raise
        return fut

    def _inplace(self, features: List[np.ndarray], squeeze: bool) -> Future:
        fut: Future = Future()
        t0 = time.monotonic()
        try:
            outs = self._execute(features)
        except Exception as e:
            self.metrics.record_failure(e)
            fut.set_exception(e)
            return fut
        fut.set_result(collapse_outputs(outs, squeeze))
        self.metrics.observe_request(
            queue_wait_ms=0.0, e2e_ms=(time.monotonic() - t0) * 1000.0)
        return fut

    def output(self, x, timeout_ms: Optional[float] = None):
        """Blocking convenience around :meth:`submit` (reference:
        ParallelInference.output)."""
        return self.submit(x, timeout_ms=timeout_ms).result()

    def _admit(self, rows: int, timeout_ms: Optional[float]) -> None:
        """Resilience admission (serving/resilience.py): shed while the
        circuit breaker is open, and shed deadline-carrying requests
        whose estimated queue wait already exceeds their deadline —
        both as :class:`ServerOverloadedError` with a ``retry_after_s``
        backoff hint, at the call site, instead of letting the request
        expire in queue."""
        if self.breaker is not None:
            wait = self.breaker.reject_for()
            if wait is not None:
                self.metrics.inc("requests_shed")
                raise ServerOverloadedError(
                    f"circuit breaker open "
                    f"({self.breaker.failure_threshold} consecutive exec "
                    f"failures); next probe in {wait:.2f}s",
                    retry_after_s=round(wait, 3))
        if self.admission is None or timeout_ms is None:
            return
        if self.mode is InferenceMode.BATCHED:
            est = self.admission.estimate_wait_ms(
                self._queue.pending_rows() + rows, self.max_batch_size)
        else:           # sequential: one request per dispatch
            est = self.admission.estimate_wait_ms(
                self._queue.pending() + 1, 1)
        if est is not None and est > timeout_ms:
            self.metrics.inc("requests_shed")
            raise ServerOverloadedError(
                f"estimated queue wait {est:.1f} ms exceeds the "
                f"{timeout_ms:.1f} ms deadline — shed at admission "
                f"(queue depth x p{self.admission.percentile:g} exec "
                f"time)", retry_after_s=round(est / 1000.0, 3))

    def _publish_fault(self, event: str, **fields) -> None:
        """One ``{"type": "faults"}`` record on the PR-4 rail (shared
        with /healthz state folding). No-op without stats_storage."""
        if self.stats_storage is None:
            return
        try:
            self.stats_storage.put({"type": "faults", "event": event,
                                    "t": time.time(), "origin": "serving",
                                    **fields})
        except Exception:
            pass        # a broken stats sink must not take a worker down

    def _breaker_transition(self, old: str, new: str) -> None:
        self.metrics.set_resilience(breaker_state=new)
        if new == "open":
            self.metrics.inc("breaker_opens")
            self._publish_fault("fault", cause="breaker_open",
                                threshold=self.breaker.failure_threshold
                                if self.breaker is not None else None)
        elif new == "closed" and old in ("open", "half_open"):
            self._publish_fault("recovered", cause="breaker_closed")
        elif new == "half_open":
            self._publish_fault("breaker_probe", cause="breaker_half_open")

    def update_model(self) -> None:
        """Re-pull trained parameters into the serving graph (reference:
        ParallelInference.updateModel) — call after further fit()."""
        with self._exec_lock:
            self._spec.sync()

    # -- checkpoint-driven hot reload -----------------------------------
    def _canary_input(self, canary) -> dict:
        if canary is not None:
            if isinstance(canary, dict):
                return canary
            arrs = list(canary) if isinstance(canary, (tuple, list)) \
                else [canary]
            return {n: np.asarray(a)
                    for n, a in zip(self._spec.input_names, arrs)}
        ph = {}
        for name, shp in zip(self._spec.input_names, self._ph_shapes):
            if shp is None or any(d is None or d == -1 for d in shp[1:]):
                raise ReloadFailedError(
                    f"cannot build a default canary for input {name!r} "
                    f"(feature dims {shp} are not static) — pass canary=")
            ph[name] = np.zeros((1,) + tuple(int(d) for d in shp[1:]),
                                np.float32)
        return ph

    def reload_from(self, manager, step: Optional[int] = None,
                    canary=None, strict: bool = True,
                    headroom_guard: bool = True) -> dict:
        """Hot-swap serving parameters to a committed checkpoint, with
        no restart and no dropped requests.

        Reads ``step`` (default: the newest committed step) from a
        ``checkpoint.CheckpointManager``, swaps the matching parameter/
        state arrays into the serving graph BETWEEN batches (under the
        exec lock — in-flight dispatches finish on the old parameters,
        the next dispatch runs the new ones), then canary-execs a
        golden input (``canary=``, default zeros) and requires every
        floating output to be finite. A failed canary **rolls back** to
        the previous parameters and raises :class:`ReloadFailedError`
        (``rolled_back=True``) — the server keeps serving exactly what
        it served before the attempt. Returns the reload report dict;
        counters: ``reloads`` / ``reload_rollbacks``; a
        ``{"type": "faults"}`` ``reload`` record lands on the rail.

        The swap pours checkpoint arrays in by NAME (the same contract
        as ``update_model()``'s train→infer sync); a later
        ``update_model()`` re-syncs from the live training graph and
        overwrites a reload.

        ``headroom_guard`` (default on): refuse with a typed
        :class:`~deeplearning4j_tpu.memory.MemoryHeadroomError` —
        before anything is swapped — when the incoming arrays plus the
        canary program's temps exceed the projected HBM headroom
        (old and new parameters coexist through the swap; a mid-swap
        OOM would be strictly worse than a refusal). No-op on backends
        that report no memory limit."""
        import jax.numpy as jnp
        t0 = time.perf_counter()
        if step is None:
            res = manager.restore_latest()
            if res is None:
                raise ReloadFailedError(
                    "no committed checkpoint to reload from")
            step, state = res
        else:
            state = manager.restore(int(step))
        sd = self._spec.sd
        with self._exec_lock:
            live = set(sd.trainable_params()) | set(sd.state_vars_map())
            missing = sorted(live - set(state.arrays))
            if strict and missing:
                raise ReloadFailedError(
                    f"checkpoint step {step} does not cover serving "
                    f"parameters {missing[:5]}"
                    f"{'...' if len(missing) > 5 else ''} — the graph "
                    f"changed since the snapshot; pass strict=False to "
                    f"swap the matching subset",
                    report={"step": int(step)})
            mismatched = sorted(
                n for n, arr in state.arrays.items()
                if n in live and n in sd._arrays
                and tuple(sd._arrays[n].shape) != tuple(np.shape(arr)))
            if strict and mismatched:
                # same names, different shapes is still "the graph
                # changed since the snapshot" — silently swapping the
                # matching subset would serve a chimera of old and new
                # parameters behind a success report
                raise ReloadFailedError(
                    f"checkpoint step {step} arrays {mismatched[:5]}"
                    f"{'...' if len(mismatched) > 5 else ''} have "
                    f"different shapes than the serving graph; pass "
                    f"strict=False to swap the matching subset",
                    report={"step": int(step)})
            swap = {n: arr for n, arr in state.arrays.items()
                    if n in live and n in sd._arrays
                    and tuple(sd._arrays[n].shape) == tuple(np.shape(arr))}
            if headroom_guard:
                # old and new parameter sets coexist on-device through
                # the swap + canary (the rollback path needs the old
                # arrays alive), so the incoming bytes — plus the
                # canary program's temps — must fit the projected HBM
                # headroom. A typed refusal here (MemoryHeadroomError,
                # nothing swapped, server keeps serving) beats an OOM
                # mid-swap. No-op where no device reports a limit.
                from deeplearning4j_tpu.monitor import memstats
                incoming = sum(int(np.asarray(a).nbytes)
                               for a in swap.values())
                # the canary program's temps, when its exact shape was
                # warmed (sig lookup — a LABEL lookup would alias
                # across models in one process); a miss just omits the
                # canary term, the incoming-bytes check still applies
                canary_plan = None
                try:
                    cin = self._canary_input(canary)
                    canary_plan = memstats.PLANS.get(tuple(sorted(
                        (n, tuple(np.shape(v))) for n, v in cin.items())))
                except Exception:
                    pass
                if canary_plan is not None:
                    incoming += int(canary_plan.temp_bytes or 0) \
                        + int(canary_plan.output_bytes or 0)
                memstats.check_headroom(
                    incoming, f"hot reload of checkpoint step {step}")
            prev = {n: sd._arrays[n] for n in swap}
            with _tracer.span("serving.reload", cat="serving",
                              step=int(step), arrays=len(swap)):
                for n, arr in swap.items():
                    sd._arrays[n] = jnp.asarray(arr)
                failure = None
                try:
                    ph = self._canary_input(canary)
                    out = sd.output(ph, self._spec.output_names)
                    for n in self._spec.output_names:
                        o = np.asarray(out[n].to_numpy())
                        if np.issubdtype(o.dtype, np.floating) and \
                                not np.all(np.isfinite(o)):
                            failure = (f"canary produced non-finite "
                                       f"values in output {n!r}")
                            break
                except Exception as e:      # noqa: BLE001 — rollback path
                    failure = f"canary exec failed: {type(e).__name__}: {e}"
                if failure is not None:
                    for n, arr in prev.items():
                        sd._arrays[n] = arr
        report = {"step": int(step), "arrays_swapped": len(swap),
                  "rolled_back": failure is not None,
                  "seconds": round(time.perf_counter() - t0, 4)}
        if failure is not None:
            report["failure"] = failure
            self.metrics.inc("reload_rollbacks")
            self.metrics.set_resilience(last_reload_step=int(step),
                                        last_reload_failed=True)
            self._publish_fault("reload", step=int(step), failed=failure,
                                rolled_back=True)
            raise ReloadFailedError(
                f"hot reload of step {step} rolled back: {failure}",
                report=report, rolled_back=True)
        self.metrics.inc("reloads")
        self.metrics.set_resilience(last_reload_step=int(step),
                                    last_reload_failed=False)
        self._publish_fault("reload", step=int(step), arrays=len(swap),
                            seconds=report["seconds"])
        return report

    def _telemetry_health(self) -> dict:
        """Health-provider payload for the telemetry endpoint: serving
        queue depth vs capacity plus the circuit-breaker state. Not-
        healthy while the breaker is open (consecutive exec failures:
        the /healthz 503 window); not-ready when closed or the queue is
        full (admission would raise ServerOverloadedError — the signal
        an SLO-aware load balancer sheds on)."""
        depth = self._queue.pending()
        breaker_state = self.breaker.state if self.breaker is not None \
            else None
        healthy = not self._closed and breaker_state != "open"
        snap = {"queue_depth": depth,
                "queue_capacity": self.max_queue_len,
                "ready": healthy and depth < self.max_queue_len,
                "healthy": healthy}
        if breaker_state is not None:
            snap["breaker_state"] = breaker_state
        return snap

    # -- lifecycle ------------------------------------------------------
    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop intake; with ``drain`` (default) serve what is queued,
        otherwise fail pending futures with ServerClosedError. Further
        submits raise :class:`ServerClosedError`. Idempotent. The
        telemetry endpoint (``telemetry_port=``) stays up through the
        drain — /readyz reports not-ready immediately — and closes
        last."""
        if self._closed:
            return
        self._closed = True
        self._queue.close(drain=drain)
        if self._supervisor is not None:
            self._supervisor.stop(timeout=timeout)
        for t in self._workers:
            t.join(timeout=timeout)
        if self.stats_storage is not None:
            self.metrics.publish(self.stats_storage)
        if self.telemetry is not None:
            self.telemetry.close()

    def __enter__(self) -> "ParallelInference":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(drain=exc_type is None)


__all__ = ["InferenceMode", "ParallelInference", "ServingSpec",
           "ServingError", "ServerOverloadedError", "ServerClosedError",
           "RequestTimeoutError", "ServingTimeoutError",
           "ResilienceConfig", "PoisonedRequestError", "ReloadFailedError"]
