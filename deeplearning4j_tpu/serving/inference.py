"""ParallelInference: a thread-safe, batching model server.

Reference parity: deeplearning4j-parallelwrapper's ParallelInference
(parallelism/ParallelInference.java:54) — the L7 layer that turns a
trained network into a shared inference service. The reference clones
the model once per worker thread and pins workers to devices; modes:

- ``SEQUENTIAL``: each request runs alone, in arrival order;
- ``BATCHED``: concurrent requests coalesce into one model invocation
  (BatchedInferenceObservable);
- ``INPLACE``: no queue — the holder model is invoked directly in the
  calling thread (lowest latency, no coalescing).

TPU-native redesign: worker replicas do NOT clone parameters — they
share ONE inference graph whose jit cache (one compiled XLA program per
bucket shape, see serving/batching.py) is the shared "replica". Device
execution is serialized behind a lock (a single XLA stream saturates
the chip; thread-level concurrency buys host-side overlap of padding /
scatter with device compute, not parallel kernels). Backpressure,
deadlines and drain come from serving/queue.py; counters and latency
histograms from serving/metrics.py; an optional per-batch
ProfilerSession drops xplane traces for the profiler/ tooling.
"""
from __future__ import annotations

import enum
import os
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, NamedTuple, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.serving.batching import Batch, DynamicBatcher
from deeplearning4j_tpu.serving.metrics import ServingMetrics
from deeplearning4j_tpu.serving.queue import (
    InferenceRequest, RequestQueue, RequestTimeoutError, ServerClosedError,
    ServerOverloadedError, ServingError, collapse_outputs)


class InferenceMode(enum.Enum):
    """Request scheduling policy (reference: ParallelInference
    InferenceMode)."""

    SEQUENTIAL = "sequential"
    BATCHED = "batched"
    INPLACE = "inplace"


class ServingSpec(NamedTuple):
    """A network's serving contract: inference graph + IO names + the
    sync that pulls current trained parameters into it (produced by
    ``MultiLayerNetwork.serving_spec()`` / ``ComputationGraph
    .serving_spec()``)."""

    sd: object                      # inference-mode SameDiff
    input_names: List[str]
    output_names: List[str]
    sync: Callable[[], None]


def _extract_spec(model) -> ServingSpec:
    if hasattr(model, "serving_spec"):
        return ServingSpec(*model.serving_spec())
    raise TypeError(
        f"{type(model).__name__} is not servable: expected a "
        f"MultiLayerNetwork / ComputationGraph (anything exposing "
        f"serving_spec())")


class ParallelInference:
    """Shared, thread-safe inference front-end over a trained network.

    ::

        pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                               max_batch_size=32, max_delay_ms=3.0)
        y = pi.output(x)                  # blocking
        fut = pi.submit(x)                # async -> Future
        ...
        pi.shutdown()                     # drains the queue

    ``output``/``submit`` accept a (rows, *features) array, one
    unbatched example (*features), or — for multi-input graphs in
    SEQUENTIAL/INPLACE mode — a tuple of per-input arrays. Results
    mirror the wrapped model's ``output()`` (single array, or a list for
    multi-output graphs). Overload raises
    :class:`ServerOverloadedError` at submit; expired deadlines surface
    as :class:`RequestTimeoutError` from the future.

    ``warmup_buckets`` kills the serving cold-start: ``True`` AOT-
    precompiles every batching bucket shape at construction (before any
    worker serves), a sequence of ints precompiles exactly those row
    counts — so the first live request of each bucket never waits
    seconds on XLA (the p99 cliff a lazy bucket miss causes). Warmed
    shapes are bit-identical to lazily-compiled ones and the
    ``compiles`` metric stays 0 for them (``warmup_compiles`` counts
    the prebuilt set). See docs/cold_start.md.
    """

    def __init__(self, model,
                 mode: InferenceMode = InferenceMode.BATCHED,
                 workers: int = 2,
                 max_batch_size: int = 32,
                 max_delay_ms: float = 5.0,
                 max_queue_len: int = 256,
                 buckets: Optional[Sequence[int]] = None,
                 default_timeout_ms: Optional[float] = None,
                 stats_storage=None,
                 profile_dir: Optional[str] = None,
                 warmup_buckets=None,
                 telemetry_port: Optional[int] = None):
        self.model = model
        self.mode = InferenceMode(mode)
        self.max_batch_size = int(max_batch_size)
        if self.mode is InferenceMode.INPLACE and \
                default_timeout_ms is not None:
            raise ValueError("INPLACE mode executes synchronously in the "
                             "calling thread — there is no queue wait for "
                             "default_timeout_ms to bound")
        self.default_timeout_ms = default_timeout_ms
        self.metrics = ServingMetrics()
        self.stats_storage = stats_storage
        self.profile_dir = profile_dir
        self._spec = _extract_spec(model)
        if self.mode is InferenceMode.BATCHED and \
                len(self._spec.input_names) != 1:
            raise ValueError(
                f"BATCHED mode needs a single-input model; "
                f"{type(model).__name__} has inputs "
                f"{self._spec.input_names} — use SEQUENTIAL or INPLACE")
        self._ph_shapes = [self._placeholder_shape(n)
                           for n in self._spec.input_names]
        self._feat_rank = (len(self._ph_shapes[0])
                           if self._ph_shapes[0] is not None else None)
        self._exec_lock = threading.Lock()
        self._shapes_seen = set()
        self._req_id = 0
        self._id_lock = threading.Lock()
        self._closed = False
        self._spec.sync()           # pull current trained params once
        self._queue = RequestQueue(
            max_queue_len,
            on_timeout=lambda req: self.metrics.record_timeout("deadline"))
        self._batcher = DynamicBatcher(
            self._queue, max_batch_size=self.max_batch_size,
            max_delay_ms=max_delay_ms, buckets=buckets) \
            if self.mode is InferenceMode.BATCHED else None
        self.max_queue_len = int(max_queue_len)
        # live telemetry endpoint (monitor/server.py): /metrics serves
        # the serving counters/latency lanes via a scrape hook (pull
        # model — no publisher thread), /readyz reports queue depth and
        # goes 503 on overload or shutdown (the SLO shed-load signal).
        # None = off; 0 = pick a free loopback port (telemetry.url).
        self.telemetry = None
        if telemetry_port is not None:
            from deeplearning4j_tpu.monitor.server import TelemetryServer
            self.telemetry = TelemetryServer(storage=stats_storage,
                                             port=telemetry_port)
            self.telemetry.add_scrape_hook(
                lambda reg: reg.fold_serving(self.metrics))
            self.telemetry.add_health_provider("serving",
                                               self._telemetry_health)
        self.warmup_report: Optional[dict] = None
        if warmup_buckets:
            # before any worker thread exists: warmed shapes must be in
            # the execution cache before the first request can race them
            self.warmup(None if warmup_buckets is True else warmup_buckets)
        self._workers: List[threading.Thread] = []
        if self.mode is not InferenceMode.INPLACE:
            for i in range(max(1, int(workers))):
                t = threading.Thread(target=self._worker_loop,
                                     name=f"ParallelInference-{i}",
                                     daemon=True)
                t.start()
                self._workers.append(t)

    # ------------------------------------------------------------------
    def _placeholder_shape(self, input_name: str):
        try:
            shape = self._spec.sd._vars[input_name].shape
            return tuple(shape) if shape is not None else None
        except Exception:
            return None

    def _next_id(self) -> int:
        with self._id_lock:
            self._req_id += 1
            return self._req_id

    # -- AOT warmup (compilecache/, docs/cold_start.md) -----------------
    def warmup(self, buckets: Optional[Sequence[int]] = None) -> dict:
        """AOT-precompile the serving program for each bucket row count
        so live traffic never waits on XLA.

        ``buckets=None`` takes the batching tier's bucket spec (BATCHED
        mode) or the default pow2 ladder up to ``max_batch_size``
        (SEQUENTIAL/INPLACE — where requests execute at their own row
        count, so only warmed sizes are covered; off-ladder sizes still
        compile lazily). Requires static feature dims on every input.
        Returns (and stores as ``warmup_report``) the bucket list, wall
        seconds, and the compile/cache-hit accounting — on a warm
        restart with a persistent cache configured, every entry is a
        cache hit and warmup is ~free."""
        import time as _time
        from deeplearning4j_tpu.compilecache import (COMPILE_STATS,
                                                     install_compile_watcher)
        install_compile_watcher()
        if buckets is None:
            if self._batcher is not None:
                buckets = self._batcher.spec.buckets
            else:
                from deeplearning4j_tpu.serving.batching import pow2_buckets
                # single-example requests are the common case and run at
                # their own row count in these modes — always include
                # bucket 1 (the pow2 ladder stops halving above it for
                # large max_batch_size)
                buckets = (1,) + tuple(pow2_buckets(self.max_batch_size))
        bucket_list = sorted({int(b) for b in buckets})
        if not bucket_list or bucket_list[0] <= 0:
            raise ValueError(f"invalid warmup buckets {buckets!r}")
        for name, shp in zip(self._spec.input_names, self._ph_shapes):
            if shp is None or any(d is None or d == -1 for d in shp[1:]):
                raise ValueError(
                    f"cannot warm up input {name!r}: feature dims {shp} "
                    f"are not static — pass concrete shapes to the "
                    f"model, or skip warmup for this graph")
        mark = COMPILE_STATS.mark()
        t0 = _time.perf_counter()
        for b in bucket_list:
            ph = {name: (b,) + tuple(int(d) for d in shp[1:])
                  for name, shp in zip(self._spec.input_names,
                                       self._ph_shapes)}
            # _exec_lock: warmup() is public and may be called on a LIVE
            # server (pre-warming a new bucket) — the graph's compile
            # caches are only safe under the same lock _execute holds
            with self._exec_lock, \
                    _tracer.span("serving.warmup", cat="serving", bucket=b):
                self._spec.sd.precompile_output(ph,
                                                self._spec.output_names)
                # mark the shape as seen (under the SAME lock hold — a
                # worker dispatching this bucket between compile and
                # mark would count a spurious lazy `compiles`) so the
                # metric counts only genuinely-unwarmed traffic
                # compiles; already-seen buckets (a repeat warmup() on
                # a live server) must not re-count
                sig = tuple(tuple(ph[n]) for n in self._spec.input_names)
                if sig not in self._shapes_seen:
                    self._shapes_seen.add(sig)
                    self.metrics.inc("warmup_compiles")
        self.warmup_report = {
            "buckets": bucket_list,
            "seconds": round(_time.perf_counter() - t0, 4),
            **{k: v for k, v in COMPILE_STATS.delta(mark).items()
               if k in ("backend_compiles", "cache_hits", "cache_misses")}}
        return self.warmup_report

    def _prepare(self, x) -> tuple:
        """-> (list of per-input arrays with a batch dim, squeeze flag)."""
        if isinstance(x, (tuple, list)):
            arrs = [np.asarray(a) for a in x]
        else:
            arrs = [np.asarray(x)]
        if len(arrs) != len(self._spec.input_names):
            raise ValueError(
                f"model has {len(self._spec.input_names)} inputs "
                f"{self._spec.input_names}; got {len(arrs)} arrays")
        squeeze = False
        if len(arrs) == 1 and self._feat_rank is not None and \
                arrs[0].ndim == self._feat_rank - 1:
            arrs = [arrs[0][None]]      # single example: add the row dim
            squeeze = True
        if arrs[0].ndim == 0:
            raise ValueError("scalar input is not a request")
        # reject wrong feature shapes at admission: a mismatched request
        # must not reach a coalesced batch (it would fail the whole
        # dispatch, or worse, a worker thread)
        for arr, ph, name in zip(arrs, self._ph_shapes,
                                 self._spec.input_names):
            if ph is None:
                continue
            if arr.ndim != len(ph) or any(
                    d is not None and d != a
                    for d, a in zip(ph[1:], arr.shape[1:])):
                raise ValueError(
                    f"input {name!r} expects shape {ph} (leading dim = "
                    f"rows); got {arr.shape}")
        return arrs, squeeze

    # -- execution core (shared by every mode/worker) -------------------
    def _execute(self, features: List[np.ndarray],
                 real_rows: Optional[int] = None) -> List[np.ndarray]:
        """Run one forward. One compiled program per distinct input
        shape, shared by all workers (the jit cache lives on the
        inference graph); the lock serializes device execution AND makes
        the graph's internal caches safe under concurrent callers."""
        sig = tuple(tuple(f.shape) for f in features)
        rows = features[0].shape[0]
        real = rows if real_rows is None else real_rows
        ph = dict(zip(self._spec.input_names, features))
        t0 = time.perf_counter()
        with self._exec_lock, \
                _tracer.span("serving.exec", cat="serving", rows=real,
                             padding=rows - real):
            if sig not in self._shapes_seen:
                self._shapes_seen.add(sig)
                self.metrics.inc("compiles")
            prof = self._profiler_session()
            try:
                res = self._spec.sd.output(ph, self._spec.output_names)
            finally:
                if prof is not None:
                    prof.__exit__(None, None, None)
        outs = [np.asarray(res[n].to_numpy())
                for n in self._spec.output_names]
        self.metrics.observe_batch(
            rows=real, padding=rows - real,
            exec_ms=(time.perf_counter() - t0) * 1000.0)
        return outs

    def _profiler_session(self):
        if not self.profile_dir:
            return None
        from deeplearning4j_tpu.profiler import ProfilerSession
        n = self.metrics.counters["batches_dispatched"]
        sess = ProfilerSession(
            log_dir=os.path.join(self.profile_dir, f"batch_{n:06d}"))
        try:
            return sess.__enter__()
        except Exception:
            return None             # profiling is best-effort

    # -- worker loops ---------------------------------------------------
    def _worker_loop(self):
        if self.mode is InferenceMode.BATCHED:
            loop_body = self._batched_step
        else:
            loop_body = self._sequential_step
        while True:
            try:
                progressed = loop_body()
            except Exception:
                # last-ditch guard: a worker thread must never die while
                # the queue accepts work (stranded futures hang clients).
                # Per-request failure paths live inside the step fns;
                # anything reaching here is unexpected — keep serving.
                time.sleep(0.01)
                progressed = True
            if not progressed and self._queue.finished:
                return

    def _batched_step(self) -> bool:
        # the span is discarded on an empty poll — an idle server must
        # not fill the trace ring with 50 ms waits
        with _tracer.span("serving.batch", cat="serving") as bsp:
            batch = self._batcher.next_batch(poll_timeout=0.05)
            if batch is None:
                bsp.discard()
                return False
            bsp.set(rows=batch.rows, bucket=batch.bucket,
                    requests=len(batch.requests))
        try:
            outs = self._execute([batch.features], real_rows=batch.rows)
        except Exception as e:
            self.metrics.record_failure(e, n=len(batch.requests))
            batch.fail(e)
            return True
        with _tracer.span("serving.reply", cat="serving",
                          requests=len(batch.requests)):
            batch.resolve(outs)
        done = time.monotonic()
        for req in batch.requests:
            self.metrics.observe_request(
                queue_wait_ms=(batch.created_t - req.enqueue_t) * 1000.0,
                e2e_ms=(done - req.enqueue_t) * 1000.0)
        return True

    def _sequential_step(self) -> bool:
        reqs = self._queue.take(max_rows=1, timeout=0.05)
        if not reqs:
            return False
        req = reqs[0]
        t_pop = time.monotonic()
        try:
            outs = self._execute(list(req.x))
        except Exception as e:
            self.metrics.record_failure(e)
            req.fail(e)
            return True
        with _tracer.span("serving.reply", cat="serving", requests=1):
            req.complete(outs)
        done = time.monotonic()
        self.metrics.observe_request(
            queue_wait_ms=(t_pop - req.enqueue_t) * 1000.0,
            e2e_ms=(done - req.enqueue_t) * 1000.0)
        return True

    # -- client API -----------------------------------------------------
    def submit(self, x, timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one request; returns a Future resolving to the model
        output rows for exactly this request. Raises
        :class:`ServerOverloadedError` (queue full) or
        :class:`ServerClosedError` (after shutdown) at the call site."""
        if self._closed:
            raise ServerClosedError("ParallelInference is shut down")
        features, squeeze = self._prepare(x)
        if self.mode is InferenceMode.BATCHED and \
                features[0].shape[0] > self.max_batch_size:
            raise ValueError(
                f"request of {features[0].shape[0]} rows exceeds "
                f"max_batch_size {self.max_batch_size}; split it or call "
                f"the model's output() directly")
        self.metrics.inc("requests_submitted")
        if self.mode is InferenceMode.INPLACE:
            if timeout_ms is not None:
                raise ValueError("INPLACE mode has no queue; timeout_ms "
                                 "is not applicable (use BATCHED or "
                                 "SEQUENTIAL for deadline-bounded "
                                 "requests)")
            return self._inplace(features, squeeze)
        timeout_ms = timeout_ms if timeout_ms is not None \
            else self.default_timeout_ms
        deadline = time.monotonic() + timeout_ms / 1000.0 \
            if timeout_ms is not None else None
        fut: Future = Future()
        req = InferenceRequest(x=features, future=fut,
                               rows=features[0].shape[0], deadline=deadline,
                               squeeze=squeeze, id=self._next_id())
        with _tracer.span("serving.enqueue", cat="serving", id=req.id,
                          rows=req.rows):
            try:
                self._queue.put(req)
            except ServerOverloadedError:
                self.metrics.inc("requests_rejected")
                raise
        return fut

    def _inplace(self, features: List[np.ndarray], squeeze: bool) -> Future:
        fut: Future = Future()
        t0 = time.monotonic()
        try:
            outs = self._execute(features)
        except Exception as e:
            self.metrics.record_failure(e)
            fut.set_exception(e)
            return fut
        fut.set_result(collapse_outputs(outs, squeeze))
        self.metrics.observe_request(
            queue_wait_ms=0.0, e2e_ms=(time.monotonic() - t0) * 1000.0)
        return fut

    def output(self, x, timeout_ms: Optional[float] = None):
        """Blocking convenience around :meth:`submit` (reference:
        ParallelInference.output)."""
        return self.submit(x, timeout_ms=timeout_ms).result()

    def update_model(self) -> None:
        """Re-pull trained parameters into the serving graph (reference:
        ParallelInference.updateModel) — call after further fit()."""
        with self._exec_lock:
            self._spec.sync()

    def _telemetry_health(self) -> dict:
        """Health-provider payload for the telemetry endpoint: serving
        queue depth vs capacity. Not-ready when closed or the queue is
        full (admission would raise ServerOverloadedError — the signal
        an SLO-aware load balancer sheds on)."""
        depth = self._queue.pending()
        return {"queue_depth": depth,
                "queue_capacity": self.max_queue_len,
                "ready": not self._closed and depth < self.max_queue_len,
                "healthy": not self._closed}

    # -- lifecycle ------------------------------------------------------
    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop intake; with ``drain`` (default) serve what is queued,
        otherwise fail pending futures with ServerClosedError. Further
        submits raise :class:`ServerClosedError`. Idempotent. The
        telemetry endpoint (``telemetry_port=``) stays up through the
        drain — /readyz reports not-ready immediately — and closes
        last."""
        if self._closed:
            return
        self._closed = True
        self._queue.close(drain=drain)
        for t in self._workers:
            t.join(timeout=timeout)
        if self.stats_storage is not None:
            self.metrics.publish(self.stats_storage)
        if self.telemetry is not None:
            self.telemetry.close()

    def __enter__(self) -> "ParallelInference":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(drain=exc_type is None)


__all__ = ["InferenceMode", "ParallelInference", "ServingSpec",
           "ServingError", "ServerOverloadedError", "ServerClosedError",
           "RequestTimeoutError"]
