"""Dynamic batching with power-of-two shape buckets.

Reference parity: ParallelInference InferenceMode.BATCHED +
observers/BatchedInferenceObservable.java — concurrent requests coalesce
into one model invocation. The reference pays nothing for odd batch
sizes (imperative per-op dispatch); under ``jax.jit`` every distinct
input shape is a fresh XLA compilation, so a naive batcher that
dispatches whatever row count it happened to coalesce would compile
O(distinct request shapes) programs and spend its life in the compiler.

The TPU-native answer is SHAPE BUCKETING: dispatched batches are padded
up to a small fixed set of power-of-two row counts, so the server
compiles O(len(buckets)) programs total — by default 4 — and every
subsequent batch hits the jit cache. Padding rows are zeros; they ride
along through the compiled forward and are sliced off before futures
resolve (row i of a dense/conv forward does not depend on row j, so real
rows are bit-identical to an unpadded run — asserted in
tests/test_serving.py).
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.serving.queue import InferenceRequest, RequestQueue


def pow2_buckets(max_batch_size: int, n_buckets: int = 4) -> Tuple[int, ...]:
    """Power-of-two row-count buckets ending at ``max_batch_size``.

    E.g. ``pow2_buckets(32) == (4, 8, 16, 32)``: halving down from the
    cap for ``n_buckets`` steps (stopping at 1). Once a dispatch fills
    the smallest bucket, padding waste is <50%; below it (a lone
    request under light load) waste can reach
    ``(smallest - 1) / smallest`` — include bucket 1 if that matters
    more than the extra compile. Total compilations are bounded by the
    bucket count regardless of request-size mix.
    """
    if max_batch_size <= 0:
        raise ValueError("max_batch_size must be positive")
    buckets = [int(max_batch_size)]
    while len(buckets) < n_buckets and buckets[0] > 1:
        buckets.insert(0, max(1, buckets[0] // 2))
    return tuple(dict.fromkeys(buckets))


class BucketSpec:
    """Sorted row-count buckets + lookup of the smallest fitting bucket."""

    def __init__(self, buckets: Sequence[int]):
        bs = sorted({int(b) for b in buckets})
        if not bs or bs[0] <= 0:
            raise ValueError(f"invalid buckets {buckets!r}")
        self.buckets = tuple(bs)

    @property
    def max_rows(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        if rows > self.max_rows:
            raise ValueError(f"{rows} rows exceed largest bucket "
                             f"{self.max_rows}")
        for b in self.buckets:
            if rows <= b:
                return b
        raise AssertionError  # unreachable

    def __repr__(self):
        return f"BucketSpec{self.buckets}"


def scatter_rows(requests: Sequence[InferenceRequest],
                 outputs: Sequence[np.ndarray]) -> List[InferenceRequest]:
    """Scatter per-output row slices back to each request's future —
    THE one implementation of the reply contract (used by Batch.resolve
    and the resilient bisecting dispatcher). Each request's deadline is
    re-checked by ``complete()``; the returned list holds the requests
    whose deadline passed during exec (their futures got
    ServingTimeoutError, not the stale result — the caller records the
    timeouts)."""
    off = 0
    expired: List[InferenceRequest] = []
    for req in requests:
        if not req.complete([np.asarray(o[off:off + req.rows])
                             for o in outputs]):
            expired.append(req)
        off += req.rows
    return expired


@dataclass
class Batch:
    """One coalesced dispatch: padded features + the requests inside it."""

    requests: List[InferenceRequest]
    features: np.ndarray            # (bucket, *feat) — zero-padded
    rows: int                       # real rows (== sum of request rows)
    bucket: int                     # padded row count actually dispatched
    created_t: float = field(default_factory=time.monotonic)

    @property
    def padding(self) -> int:
        return self.bucket - self.rows

    def resolve(self, outputs: List[np.ndarray]) -> List[InferenceRequest]:
        """Scatter row slices to futures (see :func:`scatter_rows`)."""
        return scatter_rows(self.requests, outputs)

    def fail(self, exc: BaseException) -> None:
        for req in self.requests:
            req.fail(exc)


def pad_to_bucket(arrays: Sequence[np.ndarray], bucket: int) -> np.ndarray:
    """Stack request arrays along rows and zero-pad to ``bucket`` rows."""
    stacked = np.concatenate(arrays, axis=0) if len(arrays) > 1 \
        else np.asarray(arrays[0])
    pad = bucket - stacked.shape[0]
    if pad < 0:
        raise ValueError(f"{stacked.shape[0]} rows exceed bucket {bucket}")
    if pad == 0:
        return stacked
    return np.concatenate(
        [stacked, np.zeros((pad,) + stacked.shape[1:], stacked.dtype)],
        axis=0)


class DynamicBatcher:
    """Pulls requests off a :class:`RequestQueue` into padded batches.

    Coalescing: block for the first request, then keep absorbing queued
    requests until the batch holds ``max_batch_size`` rows or
    ``max_delay_ms`` has elapsed since the first pop — the classic
    size-or-deadline trigger. The result is padded to the smallest
    bucket that fits (see :func:`pow2_buckets`).

    Thread-safe: several workers may call :meth:`next_batch`
    concurrently; the queue's lock makes each request land in exactly
    one batch.
    """

    def __init__(self, queue: RequestQueue, max_batch_size: int = 32,
                 max_delay_ms: float = 5.0,
                 buckets: Optional[Sequence[int]] = None):
        self.queue = queue
        self.max_batch_size = int(max_batch_size)
        self.max_delay_ms = float(max_delay_ms)
        self.spec = BucketSpec(buckets if buckets is not None
                               else pow2_buckets(self.max_batch_size))
        if self.spec.max_rows < self.max_batch_size:
            raise ValueError(
                f"largest bucket {self.spec.max_rows} < max_batch_size "
                f"{self.max_batch_size}: full batches could not dispatch")

    def next_batch(self, poll_timeout: float = 0.1) -> Optional[Batch]:
        """Build the next batch, or return None on timeout/shutdown."""
        reqs = self.queue.take(self.max_batch_size, timeout=poll_timeout,
                               strict=True)
        if not reqs:
            return None
        rows = sum(r.rows for r in reqs)
        deadline = time.monotonic() + self.max_delay_ms / 1000.0
        while rows < self.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            more = self.queue.take(self.max_batch_size - rows,
                                   timeout=remaining, strict=True)
            if not more:
                break
            reqs.extend(more)
            rows += sum(r.rows for r in more)
        try:
            bucket = self.spec.bucket_for(rows)
            # req.x is the per-input list built by submit(); batching is
            # single-input, so the first (only) entry is the feature array
            with _tracer.span("serving.pad", cat="serving", rows=rows,
                              bucket=bucket):
                features = pad_to_bucket(
                    [np.asarray(r.x[0] if isinstance(r.x, (list, tuple))
                                else r.x) for r in reqs], bucket)
        except Exception as e:
            # never strand popped requests: a malformed batch (e.g.
            # mismatched feature widths) fails ITS requests, not the
            # worker thread
            for r in reqs:
                r.fail(e)
            return None
        return Batch(requests=reqs, features=features, rows=rows,
                     bucket=bucket)
