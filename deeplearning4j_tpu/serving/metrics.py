"""Serving metrics: counters + latency histograms in the ui/stats format.

Reference parity: the reference exposes ParallelInference health only
through its own counters; the wider reference UI stack persists training
stats through StatsStorage (ui-model BaseStatsListener ->
api/storage/StatsStorage). This module gives serving the same treatment:
everything a load balancer or dashboard needs — queue wait, end-to-end
latency, batch occupancy, padding waste, compile count, rejection /
timeout totals — accumulated lock-cheaply in-process and exported as
``{"type": "serving", ...}`` JSON-lines records through the EXISTING
:class:`deeplearning4j_tpu.ui.stats.StatsStorage`, so the same tooling
that reads training stats reads serving stats.

Latency is histogram-based (fixed log-spaced bins, microsecond to
minute): recording is O(1) with no unbounded memory, percentiles are
read from the cumulative counts — the standard production shape for
serving metrics (vs storing every sample).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

# log-spaced bin edges (ms): 0.01 ms .. 60 s, ~12 bins per decade
_EDGES = np.geomspace(0.01, 60_000.0, 82)


def safe_ratio(num: float, den: float) -> float:
    """``num / den`` with 0.0 (not NaN/inf) on a zero denominator — the
    cold-start rule for every exported gauge ratio: a dashboard reading
    prefix-hit-rate or pool-occupancy before the first sample must see
    a number it can plot/alert on."""
    den = float(den)
    if den == 0.0 or not np.isfinite(den):
        return 0.0
    return float(num) / den


class LatencyHistogram:
    """Fixed-bin log-scale latency histogram with percentile readout."""

    def __init__(self, edges: Optional[np.ndarray] = None):
        self.edges = np.asarray(edges if edges is not None else _EDGES,
                                np.float64)
        # one underflow + one overflow bucket
        self.counts = np.zeros(len(self.edges) + 1, np.int64)
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0

    def record(self, ms: float) -> None:
        # NaN-free by construction: a non-finite sample (a clock glitch,
        # a 0-row dispatch timed as 0/0 upstream) records as 0.0 instead
        # of poisoning total_ms/max_ms and every later mean()
        ms = float(ms)
        if not np.isfinite(ms):
            ms = 0.0
        self.counts[int(np.searchsorted(self.edges, ms, side="left"))] += 1
        self.count += 1
        self.total_ms += ms
        self.max_ms = max(self.max_ms, ms)

    def percentile(self, p: float) -> float:
        """p in [0, 100]; returns the upper edge of the bucket holding
        the p-th sample (a conservative estimate), 0.0 when empty —
        never NaN (the guard dashboards divide/alert on)."""
        if self.count == 0:
            return 0.0
        target = max(1, int(np.ceil(p / 100.0 * self.count)))
        cum = np.cumsum(self.counts)
        idx = int(np.searchsorted(cum, target))
        if idx >= len(self.edges):
            return float(self.max_ms)
        # upper edge of the bucket holding the target sample, clamped to
        # the exact observed max (an edge can overshoot it)
        return float(min(self.edges[idx], self.max_ms))

    def mean(self) -> float:
        return self.total_ms / self.count if self.count else 0.0

    def summary(self) -> Dict[str, float]:
        """Stats dict; ``count`` rides along and ``low_sample`` flags a
        histogram whose tail percentiles are read from fewer than 32
        samples (a p99 of 3 requests is the max, not a p99 — consumers
        should render it with that caveat)."""
        return {"count": int(self.count),
                "low_sample": bool(self.count < 32),
                "mean": round(self.mean(), 4),
                "p50": round(self.percentile(50), 4),
                "p95": round(self.percentile(95), 4),
                "p99": round(self.percentile(99), 4),
                "max": round(self.max_ms, 4)}


_COUNTERS = ("requests_submitted", "requests_served", "requests_rejected",
             "requests_timed_out", "requests_failed", "batches_dispatched",
             "rows_served", "rows_padded", "compiles", "warmup_compiles",
             # resilience rail (serving/resilience.py): SLO sheds at
             # admission, breaker trips, crash-recovery requeues/worker
             # restarts, transient exec faults absorbed, bisection
             # splits + quarantined poisoned requests, hot reloads
             "requests_shed", "breaker_opens", "requests_requeued",
             "worker_restarts", "exec_faults", "bisect_splits",
             "poisoned_quarantined", "reloads", "reload_rollbacks")


class ServingMetrics:
    """Thread-safe accumulator for one ParallelInference instance."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {c: 0 for c in _COUNTERS}
        self.queue_wait_ms = LatencyHistogram()
        self.e2e_ms = LatencyHistogram()
        self.exec_ms = LatencyHistogram()
        self.batch_sizes: Dict[int, int] = {}   # real rows -> dispatches
        # per-cause breakdowns + the most recent failure, so serving
        # degradation (a creeping OOM, a model bug after update_model)
        # is attributable BEFORE it becomes an outage
        self.failure_causes: Dict[str, int] = {}
        self.timeout_causes: Dict[str, int] = {}
        self.last_error: Optional[dict] = None
        # resilience state snapshot (breaker state, last reload step,
        # ...) — merged by the serving rail, exported in to_record()
        self.resilience: Dict[str, object] = {}
        self._start_t = time.time()

    # -- recording ------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def record_failure(self, error: BaseException,
                       cause: Optional[str] = None, n: int = 1) -> None:
        """One failed dispatch affecting ``n`` requests; ``cause``
        defaults to the exception class name."""
        cause = cause or type(error).__name__
        with self._lock:
            self.counters["requests_failed"] += n
            self.failure_causes[cause] = \
                self.failure_causes.get(cause, 0) + n
            self.last_error = {"kind": "failure", "cause": cause,
                              "error": repr(error), "t": time.time()}

    def record_timeout(self, cause: str = "deadline",
                       error: Optional[BaseException] = None,
                       n: int = 1) -> None:
        with self._lock:
            self.counters["requests_timed_out"] += n
            self.timeout_causes[cause] = \
                self.timeout_causes.get(cause, 0) + n
            self.last_error = {"kind": "timeout", "cause": cause,
                              "error": repr(error) if error else None,
                              "t": time.time()}

    def set_resilience(self, **fields) -> None:
        """Merge resilience-state fields (``breaker_state``,
        ``last_reload_step``, ...) into the exported snapshot."""
        with self._lock:
            self.resilience.update(fields)

    def observe_batch(self, rows: int, padding: int, exec_ms: float) -> None:
        """Negative/zero rows and non-finite exec times record as
        zeros (``LatencyHistogram.record`` guards the time): an
        empty/degenerate dispatch must not put NaN into the padding-
        waste or mean-size divisions downstream."""
        rows, padding = max(0, int(rows)), max(0, int(padding))
        with self._lock:
            self.counters["batches_dispatched"] += 1
            self.counters["rows_served"] += rows
            self.counters["rows_padded"] += padding
            self.batch_sizes[rows] = self.batch_sizes.get(rows, 0) + 1
            self.exec_ms.record(exec_ms)

    def observe_request(self, queue_wait_ms: float, e2e_ms: float) -> None:
        with self._lock:
            self.counters["requests_served"] += 1
            self.queue_wait_ms.record(queue_wait_ms)
            self.e2e_ms.record(e2e_ms)

    # -- readout --------------------------------------------------------
    def padding_waste(self) -> float:
        """Fraction of dispatched rows that were padding."""
        with self._lock:
            total = self.counters["rows_served"] + self.counters["rows_padded"]
            return self.counters["rows_padded"] / total if total else 0.0

    def mean_batch_size(self) -> float:
        with self._lock:
            n = self.counters["batches_dispatched"]
            return self.counters["rows_served"] / n if n else 0.0

    def to_record(self) -> dict:
        """One ``{"type": "serving", ...}`` record in the ui/stats
        JSON-lines convention (see ui/stats.py module docstring)."""
        with self._lock:
            return {
                "type": "serving",
                "t": time.time(),
                "uptime_s": round(time.time() - self._start_t, 3),
                "counters": dict(self.counters),
                "failure_causes": dict(self.failure_causes),
                "timeout_causes": dict(self.timeout_causes),
                "last_error": dict(self.last_error)
                if self.last_error else None,
                "resilience": dict(self.resilience)
                if self.resilience else None,
                "latency_ms": {"queue_wait": self.queue_wait_ms.summary(),
                               "e2e": self.e2e_ms.summary(),
                               "exec": self.exec_ms.summary()},
                "batch": {
                    "mean_size": round(self.counters["rows_served"] /
                                       self.counters["batches_dispatched"], 3)
                    if self.counters["batches_dispatched"] else 0.0,
                    "padding_waste": round(
                        self.counters["rows_padded"] /
                        (self.counters["rows_served"] +
                         self.counters["rows_padded"]), 4)
                    if (self.counters["rows_served"] +
                        self.counters["rows_padded"]) else 0.0,
                    "size_hist": {str(k): v for k, v in
                                  sorted(self.batch_sizes.items())}},
            }

    def publish(self, storage) -> dict:
        """Append the current snapshot to a ui.stats.StatsStorage."""
        rec = self.to_record()
        storage.put(rec)
        return rec

    def stats(self) -> str:
        """Printable summary (the Evaluation.stats() convention)."""
        rec = self.to_record()
        c = rec["counters"]
        lines = [f"ServingMetrics: {c['requests_served']} served / "
                 f"{c['requests_submitted']} submitted "
                 f"({c['requests_rejected']} rejected, "
                 f"{c['requests_timed_out']} timed out, "
                 f"{c['requests_failed']} failed)",
                 f"  batches: {c['batches_dispatched']} dispatched, "
                 f"mean size {rec['batch']['mean_size']}, padding waste "
                 f"{rec['batch']['padding_waste']:.1%}, "
                 f"{c['compiles']} compiled shapes "
                 f"({c['warmup_compiles']} prewarmed)"]
        for name in ("queue_wait", "e2e", "exec"):
            s = rec["latency_ms"][name]
            lines.append(f"  {name:<10} p50 {s['p50']:.3f} ms  "
                         f"p95 {s['p95']:.3f} ms  p99 {s['p99']:.3f} ms  "
                         f"max {s['max']:.3f} ms  (n={s['count']})")
        causes = {**rec["failure_causes"],
                  **{f"timeout:{k}": v
                     for k, v in rec["timeout_causes"].items()}}
        if causes:
            lines.append("  causes: " + ", ".join(
                f"{k}={v}" for k, v in sorted(causes.items())))
        if rec["last_error"]:
            le = rec["last_error"]
            lines.append(f"  last_error: [{le['cause']}] {le['error']}")
        res = rec.get("resilience")
        resil_counts = {k: c[k] for k in
                        ("requests_shed", "breaker_opens",
                         "worker_restarts", "requests_requeued",
                         "poisoned_quarantined", "reloads",
                         "reload_rollbacks") if c.get(k)}
        if res or resil_counts:
            bits = [f"{k}={v}" for k, v in sorted(resil_counts.items())]
            if res and res.get("breaker_state"):
                bits.insert(0, f"breaker={res['breaker_state']}")
            lines.append("  resilience: " + ", ".join(bits))
        return "\n".join(lines)
