"""Seeded host-side token sampling for the generative serving tier.

Temperature / top-k / top-p sampling over one slot's logits row, with
the draw keyed by ``(seed, index)`` — the request's seed folded with
the ABSOLUTE token index (prompt length + tokens generated so far),
the same fold-in discipline as training's per-step data seeds. Because
the fold carries no server state, the sampled continuation for a given
request is reproducible regardless of co-batching, admission order, or
crash-requeue re-entry at prefill: the requeued request re-derives the
same ``index`` for its next token from ``prompt + generated`` alone.

The sampler runs on the host (numpy, float64) over the [vocab] logits
the compiled step already returns: one tiny O(vocab) pass per sampled
token, nothing re-jitted, and the greedy path (temperature 0) keeps
using the device argmax untouched — bit-identical to the greedy-only
server. See docs/serving.md "Decode speed".
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["sample_token"]


def sample_token(logits, temperature: float = 1.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 seed: int = 0, index: int = 0) -> int:
    """Draw one token id from a [vocab] logits row.

    - ``temperature <= 0`` is exact greedy (argmax, no rng consumed).
    - ``top_k`` keeps the k highest logits before the softmax.
    - ``top_p`` keeps the smallest descending-probability prefix whose
      mass reaches p (the boundary token included), renormalized.
    - ``(seed, index)`` seeds a fresh ``np.random.default_rng`` per
      draw — a pure function of its arguments, so the same request
      replays identically whatever else shares the batch.

    NaN-safe: non-finite logits can never be drawn; if every logit is
    non-finite the argmax fallback still returns a valid id.
    """
    z = np.asarray(logits, np.float64).reshape(-1)
    if z.size < 1:
        raise ValueError("sample_token needs a non-empty logits row")
    if temperature is None or float(temperature) <= 0.0:
        return int(np.argmax(z))
    z = np.where(np.isfinite(z), z, -np.inf)
    z = z / float(temperature)
    if top_k is not None and 0 < int(top_k) < z.size:
        kth = np.partition(z, -int(top_k))[-int(top_k)]
        z = np.where(z >= kth, z, -np.inf)
    m = z.max()
    if not np.isfinite(m):
        # every logit masked/non-finite: degenerate row, greedy fallback
        return int(np.argmax(np.asarray(logits, np.float64).reshape(-1)))
    p = np.exp(z - m)
    p /= p.sum()
    if top_p is not None and 0.0 < float(top_p) < 1.0:
        order = np.argsort(-p, kind="stable")
        csum = np.cumsum(p[order])
        cut = int(np.searchsorted(csum, float(top_p)) + 1)
        keep = np.zeros(p.size, bool)
        keep[order[:cut]] = True
        p = np.where(keep, p, 0.0)
        p /= p.sum()
    # SeedSequence rejects negative entries; fold to the nonneg range
    rng = np.random.default_rng((int(seed) & 0xFFFFFFFFFFFFFFFF,
                                 int(index) & 0xFFFFFFFFFFFFFFFF))
    r = rng.random()
    tok = int(np.searchsorted(np.cumsum(p), r, side="right"))
    return min(tok, p.size - 1)
