"""Closed- and open-loop load generation for the serving stack.

No reference analogue (the reference ships no load tool); this is the
standard serving-benchmark pair:

- **closed loop**: N client threads, each issuing its next request only
  after the previous one completes — measures latency under a fixed
  concurrency, throughput is an OUTPUT;
- **open loop**: requests submitted on a fixed-rate clock regardless of
  completion — the arrival process a real fleet produces; exposes
  queueing collapse (rejections/timeouts) that closed loops hide.

Used by tests/test_serving.py and examples/serving_mnist.py.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.serving.queue import (
    RequestTimeoutError, ServerClosedError, ServerOverloadedError)


@dataclass
class LoadResult:
    """Outcome of one load run."""

    n_ok: int = 0
    n_rejected: int = 0             # ServerOverloadedError at submit
    n_timed_out: int = 0            # RequestTimeoutError from the future
    n_failed: int = 0               # anything else
    duration_s: float = 0.0
    latencies_ms: List[float] = field(default_factory=list)

    @property
    def n_issued(self) -> int:
        return self.n_ok + self.n_rejected + self.n_timed_out + self.n_failed

    @property
    def throughput_rps(self) -> float:
        return self.n_ok / self.duration_s if self.duration_s > 0 else 0.0

    def percentile(self, p: float) -> float:
        if not self.latencies_ms:
            return 0.0
        return float(np.percentile(np.asarray(self.latencies_ms), p))

    def stats(self) -> str:
        return (f"LoadResult: {self.n_ok}/{self.n_issued} ok "
                f"({self.n_rejected} rejected, {self.n_timed_out} timed "
                f"out, {self.n_failed} failed) in {self.duration_s:.2f}s "
                f"-> {self.throughput_rps:.1f} req/s; latency p50 "
                f"{self.percentile(50):.2f} ms, p95 "
                f"{self.percentile(95):.2f} ms, p99 "
                f"{self.percentile(99):.2f} ms")


class LoadGenerator:
    """Drives a :class:`~deeplearning4j_tpu.serving.ParallelInference`.

    ``request_fn(rng, i)`` builds the i-th request payload (a
    (rows, *features) array); each worker thread gets an independent
    seeded Generator so runs are reproducible.
    """

    def __init__(self, server,
                 request_fn: Callable[[np.random.Generator, int], object],
                 seed: int = 0):
        self.server = server
        self.request_fn = request_fn
        self.seed = int(seed)

    # -- closed loop ----------------------------------------------------
    def run_closed(self, n_requests: int = 256, concurrency: int = 4,
                   timeout_ms: Optional[float] = None) -> LoadResult:
        result = LoadResult()
        lock = threading.Lock()
        counter = {"next": 0}

        def worker(wid: int):
            rng = np.random.default_rng(self.seed + wid)
            while True:
                with lock:
                    i = counter["next"]
                    if i >= n_requests:
                        return
                    counter["next"] = i + 1
                x = self.request_fn(rng, i)
                t0 = time.monotonic()
                try:
                    self.server.output(x, timeout_ms=timeout_ms)
                except ServerOverloadedError:
                    with lock:
                        result.n_rejected += 1
                    continue
                except RequestTimeoutError:
                    with lock:
                        result.n_timed_out += 1
                    continue
                except Exception:
                    with lock:
                        result.n_failed += 1
                    continue
                ms = (time.monotonic() - t0) * 1000.0
                with lock:
                    result.n_ok += 1
                    result.latencies_ms.append(ms)

        t_start = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(max(1, int(concurrency)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        result.duration_s = time.monotonic() - t_start
        return result

    # -- open loop ------------------------------------------------------
    def run_open(self, n_requests: int = 256, rate_rps: float = 200.0,
                 timeout_ms: Optional[float] = None) -> LoadResult:
        result = LoadResult()
        lock = threading.Lock()
        rng = np.random.default_rng(self.seed)
        interval = 1.0 / max(rate_rps, 1e-9)
        pending = []
        t_start = time.monotonic()
        for i in range(n_requests):
            target = t_start + i * interval
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            x = self.request_fn(rng, i)
            t0 = time.monotonic()
            try:
                fut = self.server.submit(x, timeout_ms=timeout_ms)
            except ServerOverloadedError:
                with lock:              # callbacks also mutate result
                    result.n_rejected += 1
                continue
            except ServerClosedError:
                with lock:
                    result.n_failed += 1
                continue

            def _done(f, t0=t0):
                with lock:
                    try:
                        f.result()
                    except RequestTimeoutError:
                        result.n_timed_out += 1
                    except Exception:
                        result.n_failed += 1
                    else:
                        result.n_ok += 1
                        result.latencies_ms.append(
                            (time.monotonic() - t0) * 1000.0)

            fut.add_done_callback(_done)
            pending.append(fut)
        for fut in pending:
            try:
                fut.exception()     # wait for completion; counted above
            except Exception:
                pass
        result.duration_s = time.monotonic() - t_start
        return result
