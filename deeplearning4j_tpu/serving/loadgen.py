"""Closed- and open-loop load generation for the serving stack.

No reference analogue (the reference ships no load tool); this is the
standard serving-benchmark pair:

- **closed loop**: N client threads, each issuing its next request only
  after the previous one completes — measures latency under a fixed
  concurrency, throughput is an OUTPUT;
- **open loop**: requests submitted on a fixed-rate clock regardless of
  completion — the arrival process a real fleet produces; exposes
  queueing collapse (rejections/timeouts) that closed loops hide.

:class:`GenerativeLoadGenerator` is the autoregressive twin over a
``serving.generative.GenerativeServer``: mixed prompt/output lengths
sampled from a **seeded per-request distribution** (request ``i`` is
identical across runs and concurrency settings, so continuous- and
static-batching servers can be compared on the SAME trace), optional
per-request deadlines, and TTFT + inter-token percentiles on
:class:`LoadResult` — one driver shared by the acceptance tests
(tests/test_generative.py) and ``bench.py generative``.

:class:`FleetLoadGenerator` is the multi-target replay: it drives a
**callable front door** (``serving.fleet.FleetRouter.generate``, or
any ``fn(prompt, max_new_tokens, timeout_ms)`` returning a
``FleetResult``-shaped object) instead of one server, tags every
``LoadResult`` row with the replica that served it and the retries it
took, and reports fleet-wide TTFT / inter-token percentiles. Request
``i`` stays a pure function of ``(seed, i)`` — identical traces
against one replica, a fleet of three, or affinity-vs-random routing.
An optional ``prefix_pool`` mixes shared prompt prefixes into the
trace (the repeated-prefix traffic that prefix-affinity routing and
prefix caching exist for).

Used by tests/test_serving.py, tests/test_fleet.py and
examples/serving_mnist.py / examples/fleet_serving.py.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

import numpy as np

from deeplearning4j_tpu.serving.queue import (
    RequestTimeoutError, ServerClosedError, ServerOverloadedError)
from deeplearning4j_tpu.serving.resilience import RetryableServingError


@dataclass
class LoadResult:
    """Outcome of one load run."""

    n_ok: int = 0
    n_rejected: int = 0             # ServerOverloadedError at submit
    n_timed_out: int = 0            # RequestTimeoutError from the future
    n_failed: int = 0               # anything else
    duration_s: float = 0.0
    latencies_ms: List[float] = field(default_factory=list)
    # generative traffic (GenerativeLoadGenerator): per-request time to
    # first streamed token, per-gap inter-token latencies, token total
    ttft_ms: List[float] = field(default_factory=list)
    intertoken_ms: List[float] = field(default_factory=list)
    tokens_total: int = 0
    # fleet traffic (FleetLoadGenerator): one row per request —
    # ``{"i", "outcome", "replica", "retries", "routed", "ttft_ms",
    # "e2e_ms", "resumes", "tokens_salvaged", "ttft_breakdown"}`` — so
    # a run can be sliced per replica, per retry count, per durability
    # resume, and (when the request's trace was sampled) per TTFT phase
    rows: List[dict] = field(default_factory=list)

    @property
    def resumes_total(self) -> int:
        """Mid-stream failovers resumed from the emitted prefix across
        the run (fleet rows; 0 without the durability rail)."""
        return sum(int(r.get("resumes") or 0) for r in self.rows)

    @property
    def tokens_salvaged_total(self) -> int:
        return sum(int(r.get("tokens_salvaged") or 0) for r in self.rows)

    @property
    def n_issued(self) -> int:
        return self.n_ok + self.n_rejected + self.n_timed_out + self.n_failed

    @property
    def retries_total(self) -> int:
        return sum(int(r.get("retries") or 0) for r in self.rows)

    def by_replica(self) -> dict:
        """``{replica: n_ok}`` over the tagged rows (fleet runs)."""
        out: dict = {}
        for r in self.rows:
            if r.get("outcome") == "ok" and r.get("replica"):
                out[r["replica"]] = out.get(r["replica"], 0) + 1
        return out

    @property
    def throughput_rps(self) -> float:
        return self.n_ok / self.duration_s if self.duration_s > 0 else 0.0

    @property
    def tokens_per_sec(self) -> float:
        return self.tokens_total / self.duration_s \
            if self.duration_s > 0 else 0.0

    @staticmethod
    def _pct(values: List[float], p: float) -> float:
        if not values:
            return 0.0
        return float(np.percentile(np.asarray(values), p))

    def percentile(self, p: float) -> float:
        return self._pct(self.latencies_ms, p)

    def ttft_percentile(self, p: float) -> float:
        return self._pct(self.ttft_ms, p)

    def intertoken_percentile(self, p: float) -> float:
        return self._pct(self.intertoken_ms, p)

    def slo_attainment(self, slo_ms: float, lane: str = "ttft_ms") -> float:
        """Fraction of issued requests that met ``slo_ms`` on ``lane``
        (``"ttft_ms"`` or ``"e2e_ms"``) — the SAME definition the
        router-side ``monitor.reqtrace.SLOTracker`` applies, so bench
        rows and the fleet record's ``slo`` sub-dict can't disagree:
        non-ok outcomes are misses, ok rows without a measurement are
        excluded."""
        from deeplearning4j_tpu.monitor.reqtrace import slo_attainment
        return slo_attainment(
            ((("ok" if r.get("outcome") == "ok"
               else (r.get("outcome") or "failed")), r.get(lane))
             for r in self.rows), slo_ms)

    def stats(self) -> str:
        s = (f"LoadResult: {self.n_ok}/{self.n_issued} ok "
             f"({self.n_rejected} rejected, {self.n_timed_out} timed "
             f"out, {self.n_failed} failed) in {self.duration_s:.2f}s "
             f"-> {self.throughput_rps:.1f} req/s; latency p50 "
             f"{self.percentile(50):.2f} ms, p95 "
             f"{self.percentile(95):.2f} ms, p99 "
             f"{self.percentile(99):.2f} ms")
        if self.tokens_total:
            s += (f"; {self.tokens_total} tokens -> "
                  f"{self.tokens_per_sec:.1f} tok/s; TTFT p50 "
                  f"{self.ttft_percentile(50):.2f} ms, p99 "
                  f"{self.ttft_percentile(99):.2f} ms; inter-token p50 "
                  f"{self.intertoken_percentile(50):.2f} ms")
        if self.rows:
            s += (f"; fleet: {self.retries_total} retries across "
                  f"{len(self.by_replica())} serving replicas")
        return s


class LoadGenerator:
    """Drives a :class:`~deeplearning4j_tpu.serving.ParallelInference`.

    ``request_fn(rng, i)`` builds the i-th request payload (a
    (rows, *features) array); each worker thread gets an independent
    seeded Generator so runs are reproducible.
    """

    def __init__(self, server,
                 request_fn: Callable[[np.random.Generator, int], object],
                 seed: int = 0):
        self.server = server
        self.request_fn = request_fn
        self.seed = int(seed)

    # -- closed loop ----------------------------------------------------
    def run_closed(self, n_requests: int = 256, concurrency: int = 4,
                   timeout_ms: Optional[float] = None) -> LoadResult:
        result = LoadResult()
        lock = threading.Lock()
        counter = {"next": 0}

        def worker(wid: int):
            rng = np.random.default_rng(self.seed + wid)
            while True:
                with lock:
                    i = counter["next"]
                    if i >= n_requests:
                        return
                    counter["next"] = i + 1
                x = self.request_fn(rng, i)
                t0 = time.monotonic()
                try:
                    self.server.output(x, timeout_ms=timeout_ms)
                except ServerOverloadedError:
                    with lock:
                        result.n_rejected += 1
                    continue
                except RequestTimeoutError:
                    with lock:
                        result.n_timed_out += 1
                    continue
                except Exception:
                    with lock:
                        result.n_failed += 1
                    continue
                ms = (time.monotonic() - t0) * 1000.0
                with lock:
                    result.n_ok += 1
                    result.latencies_ms.append(ms)

        t_start = time.monotonic()
        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(max(1, int(concurrency)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        result.duration_s = time.monotonic() - t_start
        return result

    # -- open loop ------------------------------------------------------
    def run_open(self, n_requests: int = 256, rate_rps: float = 200.0,
                 timeout_ms: Optional[float] = None) -> LoadResult:
        result = LoadResult()
        lock = threading.Lock()
        rng = np.random.default_rng(self.seed)
        interval = 1.0 / max(rate_rps, 1e-9)
        pending = []
        t_start = time.monotonic()
        for i in range(n_requests):
            target = t_start + i * interval
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            x = self.request_fn(rng, i)
            t0 = time.monotonic()
            try:
                fut = self.server.submit(x, timeout_ms=timeout_ms)
            except ServerOverloadedError:
                with lock:              # callbacks also mutate result
                    result.n_rejected += 1
                continue
            except ServerClosedError:
                with lock:
                    result.n_failed += 1
                continue

            def _done(f, t0=t0):
                with lock:
                    try:
                        f.result()
                    except RequestTimeoutError:
                        result.n_timed_out += 1
                    except Exception:
                        result.n_failed += 1
                    else:
                        result.n_ok += 1
                        result.latencies_ms.append(
                            (time.monotonic() - t0) * 1000.0)

            fut.add_done_callback(_done)
            pending.append(fut)
        for fut in pending:
            try:
                fut.exception()     # wait for completion; counted above
            except Exception:
                pass
        result.duration_s = time.monotonic() - t_start
        return result


class GenerativeLoadGenerator:
    """Drives a ``serving.generative.GenerativeServer`` with a seeded
    mixed-length autoregressive trace.

    Request ``i`` is a pure function of ``(seed, i)`` — prompt tokens,
    prompt length (uniform in ``prompt_len``), output budget (uniform
    in ``new_tokens``), optional deadline (uniform in ``deadline_ms``),
    and a per-request sampling ``(temperature, seed)`` pair (uniform in
    ``temperature`` when given as a range; 0.0 = greedy) — regardless
    of loop mode or concurrency, so two
    servers (e.g. continuous vs static admission) can be benchmarked on
    the SAME trace. Per-token timings land on the LoadResult as
    ``ttft_ms`` / ``intertoken_ms``; ``tokens_total``/``tokens_per_sec``
    are the generative throughput."""

    def __init__(self, server, seed: int = 0,
                 prompt_len=(1, 16), new_tokens=(4, 32),
                 deadline_ms=None, vocab_size: Optional[int] = None,
                 temperature=0.0):
        self.server = server
        self.seed = int(seed)
        # (lo, hi) = uniform inclusive; a callable(rng) -> int models
        # the long-tailed output lengths real LLM traffic has (the
        # distribution continuous batching exists for)
        self.prompt_len = prompt_len
        self.new_tokens = new_tokens
        self.deadline_ms = deadline_ms
        # scalar (every request) or (lo, hi) uniform range; 0.0 keeps
        # the trace greedy — the historical behaviour
        self.temperature = temperature
        self.vocab_size = int(vocab_size if vocab_size is not None
                              else server.spec.vocab_size)

    @staticmethod
    def _sample_len(spec, rng) -> int:
        if callable(spec):
            return max(1, int(spec(rng)))
        lo, hi = spec
        return int(rng.integers(int(lo), int(hi) + 1))

    @staticmethod
    def _sample_temperature(spec, rng) -> float:
        if isinstance(spec, (tuple, list)):
            lo, hi = spec
            return float(rng.uniform(float(lo), float(hi)))
        return float(spec)

    def request(self, i: int):
        """The i-th trace entry: ``(prompt, max_new_tokens,
        deadline_ms, temperature, sample_seed)`` — deterministic in
        ``(seed, i)``, so a sampled trace replays token-identically
        whatever the concurrency or admission order."""
        rng = np.random.default_rng((self.seed, int(i)))
        plen = self._sample_len(self.prompt_len, rng)
        prompt = rng.integers(0, self.vocab_size, plen).astype(np.int32)
        n_new = self._sample_len(self.new_tokens, rng)
        deadline = None
        if self.deadline_ms is not None:
            dlo, dhi = (self.deadline_ms
                        if isinstance(self.deadline_ms, (tuple, list))
                        else (self.deadline_ms, self.deadline_ms))
            deadline = float(rng.uniform(dlo, dhi))
        temp = self._sample_temperature(self.temperature, rng)
        sample_seed = int(rng.integers(0, 2 ** 63))
        return prompt, n_new, deadline, temp, sample_seed

    def _consume(self, handle, t0: float, result: LoadResult,
                 lock: threading.Lock) -> None:
        """Drain one generation's token stream, recording TTFT and
        inter-token gaps; classify the outcome like the fixed-shape
        loops do."""
        ttft = None
        gaps: List[float] = []
        n_tokens = 0
        last = t0
        try:
            for _tok in handle.tokens():
                now = time.monotonic()
                if ttft is None:
                    ttft = (now - t0) * 1000.0
                else:
                    gaps.append((now - last) * 1000.0)
                last = now
                n_tokens += 1
            handle.result(timeout=0)   # surfaces a non-stream failure
        except RequestTimeoutError:
            with lock:
                result.n_timed_out += 1
                result.tokens_total += n_tokens
                if ttft is not None:
                    result.ttft_ms.append(ttft)
                result.intertoken_ms.extend(gaps)
            return
        except Exception:
            with lock:
                result.n_failed += 1
                result.tokens_total += n_tokens
            return
        with lock:
            result.n_ok += 1
            result.tokens_total += n_tokens
            result.latencies_ms.append((last - t0) * 1000.0)
            if ttft is not None:
                result.ttft_ms.append(ttft)
            result.intertoken_ms.extend(gaps)

    # -- closed loop ----------------------------------------------------
    def run_closed(self, n_requests: int = 64,
                   concurrency: int = 4) -> LoadResult:
        result = LoadResult()
        lock = threading.Lock()
        counter = {"next": 0}

        def worker():
            while True:
                with lock:
                    i = counter["next"]
                    if i >= n_requests:
                        return
                    counter["next"] = i + 1
                prompt, n_new, deadline, temp, sseed = self.request(i)
                t0 = time.monotonic()
                try:
                    handle = self.server.submit(prompt, n_new,
                                                timeout_ms=deadline,
                                                temperature=temp,
                                                seed=sseed)
                except ServerOverloadedError:
                    with lock:
                        result.n_rejected += 1
                    continue
                except ServerClosedError:
                    with lock:
                        result.n_failed += 1
                    continue
                self._consume(handle, t0, result, lock)

        t_start = time.monotonic()
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, int(concurrency)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        result.duration_s = time.monotonic() - t_start
        return result

    # -- open loop ------------------------------------------------------
    def run_open(self, n_requests: int = 64,
                 rate_rps: float = 50.0) -> LoadResult:
        result = LoadResult()
        lock = threading.Lock()
        interval = 1.0 / max(rate_rps, 1e-9)
        consumers: List[threading.Thread] = []
        t_start = time.monotonic()
        for i in range(n_requests):
            target = t_start + i * interval
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            prompt, n_new, deadline, temp, sseed = self.request(i)
            t0 = time.monotonic()
            try:
                handle = self.server.submit(prompt, n_new,
                                            timeout_ms=deadline,
                                            temperature=temp,
                                            seed=sseed)
            except ServerOverloadedError:
                with lock:
                    result.n_rejected += 1
                continue
            except ServerClosedError:
                with lock:
                    result.n_failed += 1
                continue
            t = threading.Thread(target=self._consume,
                                 args=(handle, t0, result, lock),
                                 daemon=True)
            t.start()
            consumers.append(t)
        for t in consumers:
            t.join()
        result.duration_s = time.monotonic() - t_start
        return result


class FleetLoadGenerator:
    """Open-loop replay against a callable front door (the fleet
    router) — N servers behind one function.

    ``front_door(prompt, max_new_tokens=..., timeout_ms=...)`` must
    BLOCK until the generation completes and return an object with
    ``tokens`` / ``replica`` / ``retries`` / ``routed`` / ``ttft_ms`` /
    ``intertoken_ms`` (``serving.fleet.FleetResult``). Typed sheds the
    router gave up on (``RetryableServingError``) count as rejected;
    deadline misses as timed out; anything else as failed. Every
    request lands one tagged row on ``LoadResult.rows``.

    Request ``i`` is a pure function of ``(seed, i)`` — and of the
    fixed ``prefix_pool``, when given: with probability ``prefix_p``
    request ``i`` prepends pool entry ``rng.integers(len(pool))`` to
    its random tail, producing the repeated-prefix traffic that makes
    affinity routing measurable (same trace under any routing policy).
    """

    def __init__(self, front_door: Callable, *, vocab_size: int,
                 seed: int = 0, prompt_len=(1, 16), new_tokens=(4, 32),
                 deadline_ms=None, prefix_pool=None,
                 prefix_p: float = 0.75, temperature=0.0):
        self.front_door = front_door
        self.vocab_size = int(vocab_size)
        self.seed = int(seed)
        self.prompt_len = prompt_len
        self.new_tokens = new_tokens
        self.deadline_ms = deadline_ms
        self.prefix_pool = None if prefix_pool is None else [
            np.asarray(p, np.int32).reshape(-1) for p in prefix_pool]
        self.prefix_p = float(prefix_p)
        # scalar or (lo, hi); nonzero traces forward temperature+seed
        # to the front door (FleetRouter.generate passes them through
        # to replica submit) — 0.0 keeps the plain greedy contract
        self.temperature = temperature

    def request(self, i: int):
        """The i-th trace entry ``(prompt, max_new_tokens,
        deadline_ms, temperature, sample_seed)`` — deterministic in
        ``(seed, i)``."""
        rng = np.random.default_rng((self.seed, int(i)))
        plen = GenerativeLoadGenerator._sample_len(self.prompt_len, rng)
        tail = rng.integers(0, self.vocab_size, plen).astype(np.int32)
        prompt = tail
        if self.prefix_pool and rng.random() < self.prefix_p:
            prefix = self.prefix_pool[
                int(rng.integers(len(self.prefix_pool)))]
            prompt = np.concatenate([prefix, tail])
        n_new = GenerativeLoadGenerator._sample_len(self.new_tokens, rng)
        deadline = None
        if self.deadline_ms is not None:
            dlo, dhi = (self.deadline_ms
                        if isinstance(self.deadline_ms, (tuple, list))
                        else (self.deadline_ms, self.deadline_ms))
            deadline = float(rng.uniform(dlo, dhi))
        temp = GenerativeLoadGenerator._sample_temperature(
            self.temperature, rng)
        sample_seed = int(rng.integers(0, 2 ** 63))
        return prompt, n_new, deadline, temp, sample_seed

    def _issue(self, i: int, result: LoadResult,
               lock: threading.Lock) -> None:
        prompt, n_new, deadline, temp, sseed = self.request(i)
        t0 = time.monotonic()
        row = {"i": int(i), "outcome": None, "replica": None,
               "retries": 0, "routed": None, "ttft_ms": None,
               "e2e_ms": None, "resumes": 0, "tokens_salvaged": 0,
               "ttft_breakdown": None}
        # sampling kwargs only on sampled traces: plain front doors
        # keep the documented (prompt, max_new_tokens, timeout_ms)
        # signature working unchanged
        kw = {"temperature": temp, "seed": sseed} if temp > 0.0 else {}
        try:
            res = self.front_door(prompt, max_new_tokens=n_new,
                                  timeout_ms=deadline, **kw)
        except RetryableServingError:
            row["outcome"] = "rejected"     # typed give-up: budget spent
            with lock:
                result.n_rejected += 1
                result.rows.append(row)
            return
        except RequestTimeoutError:
            row["outcome"] = "timed_out"
            with lock:
                result.n_timed_out += 1
                result.rows.append(row)
            return
        except Exception as e:              # noqa: BLE001 — tally + tag
            row["outcome"] = f"failed:{type(e).__name__}"
            with lock:
                result.n_failed += 1
                result.rows.append(row)
            return
        ms = (time.monotonic() - t0) * 1000.0
        row.update(outcome="ok",
                   replica=getattr(res, "replica", None),
                   retries=int(getattr(res, "retries", 0) or 0),
                   routed=getattr(res, "routed", None),
                   ttft_ms=getattr(res, "ttft_ms", None),
                   e2e_ms=ms,
                   resumes=int(getattr(res, "resumes", 0) or 0),
                   tokens_salvaged=int(
                       getattr(res, "tokens_salvaged", 0) or 0),
                   # populated when the request's trace was sampled
                   # (FleetResult.ttft_breakdown from the assembled
                   # waterfall): queue_wait/prefill/first_decode ms
                   ttft_breakdown=getattr(res, "ttft_breakdown", None))
        with lock:
            result.n_ok += 1
            result.latencies_ms.append(ms)
            result.tokens_total += len(getattr(res, "tokens", ()) or ())
            if row["ttft_ms"] is not None:
                result.ttft_ms.append(float(row["ttft_ms"]))
            result.intertoken_ms.extend(
                getattr(res, "intertoken_ms", ()) or ())
            result.rows.append(row)

    def run_closed(self, n_requests: int = 64,
                   concurrency: int = 4) -> LoadResult:
        """Fixed-concurrency closed loop over the front door: each of
        ``concurrency`` workers issues its next request only after the
        previous one returned (same trace as :meth:`run_open` — request
        ``i`` is a pure function of ``(seed, i)``)."""
        result = LoadResult()
        lock = threading.Lock()
        counter = {"next": 0}

        def worker():
            while True:
                with lock:
                    i = counter["next"]
                    if i >= n_requests:
                        return
                    counter["next"] = i + 1
                self._issue(i, result, lock)

        t_start = time.monotonic()
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, int(concurrency)))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        result.duration_s = time.monotonic() - t_start
        return result

    def run_open(self, n_requests: int = 64,
                 rate_rps: float = 50.0) -> LoadResult:
        """Fixed-rate open-loop replay: request ``i`` is issued at
        ``i / rate_rps`` regardless of completions (each in its own
        thread — the front door blocks per request)."""
        result = LoadResult()
        lock = threading.Lock()
        interval = 1.0 / max(rate_rps, 1e-9)
        workers: List[threading.Thread] = []
        t_start = time.monotonic()
        for i in range(n_requests):
            target = t_start + i * interval
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            t = threading.Thread(target=self._issue,
                                 args=(i, result, lock), daemon=True)
            t.start()
            workers.append(t)
        for t in workers:
            t.join()
        result.duration_s = time.monotonic() - t_start
        result.rows.sort(key=lambda r: r["i"])
        return result
