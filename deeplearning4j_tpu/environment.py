"""Runtime environment: the framework's flag/property catalog.

Reference parity: org.nd4j.common.config.ND4JSystemProperties (the
documented catalog of system properties) and libnd4j
include/system/Environment.h:41 (the runtime toggle singleton —
verbose/debug mode, max memory, workspace behavior, blas threads).

TPU-native redesign: properties map to environment variables read once
at first access and overridable programmatically; device/platform rows
are live queries against JAX (there is no native env struct to mirror —
XLA owns execution), and memory caps surface the XLA client options
instead of workspace byte counts.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Dict, Optional

_TRUE = ("1", "true", "yes", "on")


def _as_bool(v: str) -> bool:
    return str(v).strip().lower() in _TRUE


@dataclasses.dataclass(frozen=True)
class PropertySpec:
    key: str                 # environment variable
    type: Callable
    default: Any
    description: str
    # read once by JAX/XLA at backend initialization; a set() after that
    # point cannot affect the running process
    startup_only: bool = False


# The documented property catalog (reference: ND4JSystemProperties.java —
# every toggle is listed with its doc string so `describe()` can print
# the same kind of reference table).
PROPERTIES: Dict[str, PropertySpec] = {
    "verbose": PropertySpec(
        "DL4J_TPU_VERBOSE", _as_bool, False,
        "Print per-fit compile/dispatch diagnostics (Environment.h "
        "verbose mode)."),
    "debug": PropertySpec(
        "DL4J_TPU_DEBUG", _as_bool, False,
        "Debug execution mode: every fit() checks fetched losses for "
        "NaN/Inf regardless of TrainingConfig.nan_panic, and compile "
        "logging turns on (Environment.h debug mode; per-op localization "
        "stays on sd.exec_debug())."),
    "nan_panic": PropertySpec(
        "DL4J_TPU_NAN_PANIC", _as_bool, False,
        "Default TrainingConfig.nan_panic: raise on non-finite loss "
        "(PerformanceListener/NaN panic rails)."),
    "default_dtype": PropertySpec(
        "DL4J_TPU_DTYPE", str, "float32",
        "Default floating dtype for new networks (ND4JSystemProperties "
        "dtype property)."),
    "log_compiles": PropertySpec(
        "DL4J_TPU_LOG_COMPILES", _as_bool, False,
        "Ask JAX to log every XLA compilation (jax_log_compiles)."),
    "mem_fraction": PropertySpec(
        "XLA_PYTHON_CLIENT_MEM_FRACTION", float, 0.75,
        "Fraction of device HBM the XLA client may preallocate (the "
        "workspace-size analogue; read by JAX at process start).",
        startup_only=True),
    "preallocate": PropertySpec(
        "XLA_PYTHON_CLIENT_PREALLOCATE", _as_bool, True,
        "Whether the XLA client preallocates the memory pool at startup.",
        startup_only=True),
    "compilation_cache_dir": PropertySpec(
        "JAX_COMPILATION_CACHE_DIR", str, "",
        "Persistent XLA compilation cache directory (first-compile "
        "latency amortization across process restarts). Applied LIVE "
        "through jax.config — set() works after import, '' disables "
        "(docs/cold_start.md)."),
    "compilation_cache_min_entry_size": PropertySpec(
        "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", int, 0,
        "Smallest executable (bytes) worth persisting to the "
        "compilation cache; -1 caches everything. Applied live."),
    "compilation_cache_min_compile_time": PropertySpec(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", float, 1.0,
        "Shortest compile (seconds) worth persisting to the "
        "compilation cache; 0 caches everything. Applied live."),
    "host_device_count": PropertySpec(
        "DL4J_TPU_HOST_DEVICES", int, 0,
        "Virtual CPU device count for mesh testing (0 = leave XLA_FLAGS "
        "alone); mirrors --xla_force_host_platform_device_count.",
        startup_only=True),
}


# properties whose set()/reset() must touch live jax.config state
_SIDE_EFFECT_PROPS = ("log_compiles", "compilation_cache_dir",
                      "compilation_cache_min_entry_size",
                      "compilation_cache_min_compile_time")

# cache properties additionally export their env var on set() so child
# processes (bench probes, multihost workers) inherit the cache
_CACHE_PROPS = ("compilation_cache_dir",
                "compilation_cache_min_entry_size",
                "compilation_cache_min_compile_time")


class Environment:
    """Singleton runtime toggles (reference: Environment.getInstance()).

    Values resolve in order: programmatic ``set()`` > environment
    variable > catalog default.
    """

    _instance: Optional["Environment"] = None

    def __init__(self):
        self._overrides: Dict[str, Any] = {}
        # original env-var values before startup_only set()s, so reset()
        # can restore the documented 'set > env > default' resolution
        self._env_saved: Dict[str, Optional[str]] = {}

    @classmethod
    def get_instance(cls) -> "Environment":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    # -- generic access ----------------------------------------------------
    def get(self, name: str):
        spec = PROPERTIES.get(name)
        if spec is None:
            raise KeyError(f"unknown property {name!r}; "
                           f"have {sorted(PROPERTIES)}")
        if name in self._overrides:
            return self._overrides[name]
        raw = os.environ.get(spec.key)
        if raw is None or raw == "":
            return spec.default
        try:
            return spec.type(raw)
        except (TypeError, ValueError):
            return spec.default

    def set(self, name: str, value, for_restart: bool = False
            ) -> "Environment":
        if name not in PROPERTIES:
            raise KeyError(f"unknown property {name!r}")
        spec = PROPERTIES[name]
        coerced = spec.type(value)     # validate before any write
        if spec.startup_only:
            # startup-only properties are read by JAX/XLA at backend
            # init: once the backend is up a set() CANNOT affect the
            # running process, so it raises instead of silently
            # accepting the write. ``for_restart=True`` opts into the
            # write-the-env-var behavior for child processes / the next
            # start.
            try:
                import jax._src.xla_bridge as _xb
                backend_up = bool(getattr(_xb, "_backends", None))
            except Exception:
                backend_up = True      # unknown -> assume live
            if backend_up and not for_restart:
                raise RuntimeError(
                    f"property {name!r} (${spec.key}) is read once at "
                    f"backend initialization and the backend is already "
                    f"up — setting it now cannot affect this process. "
                    f"Set the env var before importing jax, or pass "
                    f"for_restart=True to write it for child processes "
                    f"/ the next start.")
            if spec.key not in self._env_saved:
                self._env_saved[spec.key] = os.environ.get(spec.key)
            os.environ[spec.key] = str(coerced)
            return self
        self._overrides[name] = coerced
        # the compilation-cache properties also export their env var
        # (original saved for reset()) so child processes inherit the
        # cache — matching what the old startup_only declaration of
        # compilation_cache_dir provided. Ordinary toggles stay
        # process-local: set("debug", True) must not leak into every
        # subprocess spawned afterwards.
        if name in _CACHE_PROPS:
            if spec.key not in self._env_saved:
                self._env_saved[spec.key] = os.environ.get(spec.key)
            os.environ[spec.key] = str(coerced)
        self._apply_side_effects(name)
        return self

    def reset(self, name: Optional[str] = None) -> "Environment":
        def _restore_env(key):
            if key in self._env_saved:
                old = self._env_saved.pop(key)
                if old is None:
                    os.environ.pop(key, None)
                else:
                    os.environ[key] = old

        # only properties that were actually set() have live jax.config
        # side effects to undo — re-applying a never-touched one would
        # clobber state the user configured directly via jax.config
        # (e.g. a cache dir enabled the standard JAX way)
        if name is None:
            touched = [n for n in _SIDE_EFFECT_PROPS
                       if n in self._overrides]
            self._overrides.clear()
            for key in list(self._env_saved):
                _restore_env(key)
            for n in touched:
                self._apply_side_effects(n)
        else:
            was_set = name in self._overrides
            self._overrides.pop(name, None)
            if name in PROPERTIES:
                _restore_env(PROPERTIES[name].key)
            if name in _SIDE_EFFECT_PROPS and was_set:
                # re-apply from the now-resolved env/default value, so a
                # reset() actually undoes the live jax.config change
                self._apply_side_effects(name)
        return self

    def _source(self, name: str) -> str:
        if name in self._overrides:
            return "set"
        return "env" if os.environ.get(PROPERTIES[name].key) else "default"

    def _apply_side_effects(self, name: str) -> None:
        if name == "log_compiles":
            import jax
            jax.config.update("jax_log_compiles", bool(self.get(name)))
        elif name == "compilation_cache_dir":
            from deeplearning4j_tpu.compilecache import configure_cache
            configure_cache(str(self.get(name)) or None)
        elif name == "compilation_cache_min_entry_size":
            import jax
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              int(self.get(name)))
        elif name == "compilation_cache_min_compile_time":
            import jax
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              float(self.get(name)))

    def apply_compilation_cache(self) -> "Environment":
        """Push the resolved compilation-cache properties into the live
        JAX config. Properties still at their catalog default are left
        alone (a direct ``jax.config.update`` by the user wins), so this
        is safe to call from every startup path — ``SameDiff
        .precompile()``, serving warmup and the ``cold_start`` bench all
        do, making ``$JAX_COMPILATION_CACHE_DIR`` set after import (or a
        programmatic ``set()``) take effect at the next compile."""
        for n in ("compilation_cache_dir",
                  "compilation_cache_min_entry_size",
                  "compilation_cache_min_compile_time"):
            if self._source(n) != "default":
                self._apply_side_effects(n)
        return self

    def compilation_cache_dir(self) -> str:
        return str(self.get("compilation_cache_dir"))

    # -- named accessors (Environment.h style) -----------------------------
    def is_verbose(self) -> bool:
        return bool(self.get("verbose"))

    def set_verbose(self, v: bool):
        return self.set("verbose", v)

    def is_debug(self) -> bool:
        return bool(self.get("debug"))

    def set_debug(self, v: bool):
        return self.set("debug", v)

    def default_dtype(self) -> str:
        return str(self.get("default_dtype"))

    # -- live platform rows (reference: Environment.h backend queries) -----
    def platform(self) -> str:
        import jax
        try:
            return jax.default_backend()
        except Exception:
            return "uninitialized"

    def device_count(self) -> int:
        import jax
        try:
            return jax.device_count()
        except Exception:
            return 0

    def describe(self) -> str:
        """Render the property catalog with current values (the
        ND4JSystemProperties doc table, live)."""
        lines = [f"deeplearning4j_tpu runtime environment "
                 f"(platform={self.platform()}, "
                 f"devices={self.device_count()})"]
        for name, spec in sorted(PROPERTIES.items()):
            src = ("set" if name in self._overrides else
                   "env" if os.environ.get(spec.key) else "default")
            lines.append(f"  {name} = {self.get(name)!r} [{src}; "
                         f"${spec.key}]")
            lines.append(f"      {spec.description}")
        return "\n".join(lines)

    def to_dict(self) -> Dict[str, Any]:
        return {name: self.get(name) for name in PROPERTIES}


def environment() -> Environment:
    """Module-level accessor (reference: Nd4j.getEnvironment())."""
    return Environment.get_instance()
