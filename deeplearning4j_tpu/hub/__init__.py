"""Model hub: pretrained-weight loading + local artifact cache.

Reference parity: the reference zoo's ZooModel.initPretrained()
(deeplearning4j-zoo/.../ZooModel.java:1 — downloads checkpoint zips into
~/.deeplearning4j/models and loads them) and the omnihub module
(model artifact registry/cache). This environment has zero egress, so
the hub is download-free by design: artifacts land in the cache via
``ModelHub.add`` (CI pre-seeding, scp, bind mounts) and loads are pure
local reads — the same split the reference makes between fetch and
restore.
"""
from deeplearning4j_tpu.hub.cache import KNOWN_ARTIFACTS, ModelHub
from deeplearning4j_tpu.hub.pretrained import (
    init_pretrained, load_sequential_weights, read_h5_layer_weights)

__all__ = ["ModelHub", "KNOWN_ARTIFACTS", "init_pretrained",
           "load_sequential_weights", "read_h5_layer_weights"]
