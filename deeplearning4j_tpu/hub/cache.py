"""Local artifact cache (reference: ZooModel's ~/.deeplearning4j/models
cache dir + omnihub's named-artifact registry).
"""
from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Optional

# named artifacts the zoo knows how to consume (reference: each ZooModel
# subclass pins pretrainedUrl + checksum). Stock keras-applications
# weight files load into the zoo's VGG16/ResNet50 via
# hub.init_pretrained.
KNOWN_ARTIFACTS: Dict[str, Dict[str, str]] = {
    "vgg16_keras": {
        "filename": "vgg16_weights_tf_dim_ordering_tf_kernels.h5",
        "consumer": "zoo.VGG16",
        "note": "stock keras-applications VGG16 ImageNet weights"},
    "vgg16_keras_notop": {
        "filename": "vgg16_weights_tf_dim_ordering_tf_kernels_notop.h5",
        "consumer": "zoo.VGG16 (feature extractor)",
        "note": "keras-applications VGG16 without the dense head"},
    "resnet50_keras": {
        "filename": "resnet50_weights_tf_dim_ordering_tf_kernels.h5",
        "consumer": "zoo.ResNet50",
        "note": "stock keras-applications ResNet50 ImageNet weights"},
}


class ModelHub:
    """Filesystem artifact cache. Resolution order for ``path(name)``:
    exact file path -> cache entry -> KNOWN_ARTIFACTS filename in cache.
    Missing artifacts raise with the exact placement instructions
    (zero-egress environments can't download)."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or os.environ.get(
            "DL4J_TPU_HUB",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "deeplearning4j_tpu", "hub"))
        os.makedirs(self.cache_dir, exist_ok=True)

    def add(self, name: str, src_path: str) -> str:
        """Copy an artifact into the cache under ``name``.

        Atomic: the copy lands in a temp file inside the cache dir and
        is renamed into place (checkpoint/atomic.py), so a partially
        copied artifact is never visible to ``contains()``/``path()``
        — a crashed add() leaves the cache entry absent, not torn."""
        from deeplearning4j_tpu.checkpoint.atomic import atomic_copy
        dst = os.path.join(self.cache_dir, name)
        if os.path.abspath(src_path) != os.path.abspath(dst):
            atomic_copy(src_path, dst)
        return dst

    def contains(self, name: str) -> bool:
        try:
            self.path(name)
            return True
        except FileNotFoundError:
            return False

    def list(self) -> List[str]:
        return sorted(os.listdir(self.cache_dir))

    def sha256(self, name: str) -> str:
        h = hashlib.sha256()
        with open(self.path(name), "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()

    def path(self, name: str) -> str:
        if os.path.isfile(name):
            return name
        cand = os.path.join(self.cache_dir, name)
        if os.path.isfile(cand):
            return cand
        known = KNOWN_ARTIFACTS.get(name)
        if known:
            cand = os.path.join(self.cache_dir, known["filename"])
            if os.path.isfile(cand):
                return cand
            raise FileNotFoundError(
                f"hub artifact {name!r} ({known['note']}) not cached; "
                f"place {known['filename']!r} into {self.cache_dir} "
                f"(this environment has no network egress, so the hub "
                f"never downloads)")
        raise FileNotFoundError(
            f"no hub artifact {name!r} in {self.cache_dir}; "
            f"known names: {sorted(KNOWN_ARTIFACTS)}, or pass a file path")
