"""Pretrained-weight loading: Keras h5 weight files -> zoo networks.

Reference parity: ZooModel.initPretrained() restores a downloaded
checkpoint into the freshly-built architecture; KerasModelImport's
weight path does the same from h5. Here the loader is ORDER-based with
strict shape checks: keras-applications weight files enumerate layers
in model order (h5 attr ``layer_names``), the zoo nets build the same
architecture in the same order, and conv kernels are HWIO on both sides
(the NHWC runtime keeps Keras layout verbatim) — so position+shape is a
complete, name-independent pairing.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _decode(names) -> List[str]:
    return [n.decode() if isinstance(n, bytes) else str(n) for n in names]


def read_h5_layer_weights(path: str) -> List[Tuple[str, List[np.ndarray]]]:
    """[(layer_name, [arrays in weight_names order])] for BOTH Keras h5
    layouts: full-model files (root group ``model_weights``) and
    weights-only files (layers at the root, keras-applications style)."""
    import h5py
    out = []
    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        layer_names = _decode(root.attrs.get("layer_names", []))
        if not layer_names:      # fall back to group order
            layer_names = [k for k in root.keys()
                           if isinstance(root[k], h5py.Group)]
        for ln in layer_names:
            if ln not in root:
                continue
            g = root[ln]
            wnames = _decode(g.attrs.get("weight_names", []))
            arrs = []
            for wn in wnames:
                node = g[wn] if wn in g else (
                    root[wn] if wn in root else None)
                if node is None:   # nested one level (layer/layer/kernel)
                    parts = wn.split("/")
                    node = g
                    for p in parts:
                        if p in node:
                            node = node[p]
                    if not hasattr(node, "shape"):
                        continue
                arrs.append(np.asarray(node))
            if not wnames:         # no attr: collect datasets recursively
                def walk(grp, acc):
                    for k in grp:
                        item = grp[k]
                        if hasattr(item, "shape"):
                            acc.append(np.asarray(item))
                        else:
                            walk(item, acc)
                walk(g, arrs)
            if arrs:
                out.append((ln, arrs))
    return out


def load_sequential_weights(net, source: str, strict: bool = True,
                            skip_mismatched_head: bool = False) -> int:
    """Pour h5 layer weights into ``net`` (MultiLayerNetwork) by order
    with exact shape checks. Returns the number of arrays assigned.

    ``skip_mismatched_head=True`` skips trailing layers whose shapes
    differ (e.g. notop/1000-class weights into a custom-class head) —
    the transfer-learning import mode (reference:
    TransferLearningHelper + ZooModel.initPretrained(num_classes)).
    """
    from deeplearning4j_tpu.hub.cache import ModelHub
    path = ModelHub().path(source)
    h5_layers = [(ln, arrs) for ln, arrs in read_h5_layer_weights(path)]

    # net params grouped by layer stem, in build order; state vars (BN
    # running mean/var) merge into their layer's stem group so a Keras
    # BN layer's [gamma, beta, mean, var] pairs one-to-one
    sd = net.samediff
    params = {n: np.asarray(a) for n, a in
              {**sd.trainable_params(), **sd.state_vars_map()}.items()}
    stems: List[str] = []
    by_stem: Dict[str, List[Tuple[str, np.ndarray]]] = {}
    for name, arr in params.items():
        stem = name.rsplit("_", 1)[0]
        if stem not in by_stem:
            by_stem[stem] = []
            stems.append(stem)
        by_stem[stem].append((name, arr))

    n_assigned = 0
    hi = 0
    assigned: Dict[str, np.ndarray] = {}
    for stem in stems:
        entries = by_stem[stem]
        if hi >= len(h5_layers):
            if strict and not skip_mismatched_head:
                raise ValueError(
                    f"h5 file exhausted at net layer {stem!r} "
                    f"({len(h5_layers)} weighted h5 layers, net needs "
                    f"more)")
            break
        ln, arrs = h5_layers[hi]
        hi += 1
        if len(arrs) != len(entries):
            raise ValueError(
                f"layer pairing mismatch at net {stem!r} <- h5 {ln!r}: "
                f"{len(entries)} net arrays vs {len(arrs)} h5 arrays")
        for (pname, cur), new in zip(entries, arrs):
            if tuple(cur.shape) != tuple(new.shape):
                if skip_mismatched_head:
                    break
                raise ValueError(
                    f"shape mismatch at {pname} <- h5 {ln!r}: net "
                    f"{tuple(cur.shape)} vs h5 {tuple(new.shape)} — "
                    f"pass skip_mismatched_head=True to keep the "
                    f"random-init head (custom num_classes)")
            assigned[pname] = np.asarray(new, dtype=np.asarray(cur).dtype)
        else:
            continue
        break        # inner break (mismatched head) stops the walk

    for pname, arr in assigned.items():
        for sd in (net._sd_train, net._sd_infer):
            if sd is not None and sd.has_variable(pname):
                sd.set_arr_for_var(pname, arr)
        n_assigned += 1
    if strict and hi < len(h5_layers) and not skip_mismatched_head:
        raise ValueError(
            f"{len(h5_layers) - hi} unconsumed weighted h5 layers "
            f"(starting at {h5_layers[hi][0]!r}) — architecture mismatch")
    return n_assigned


def init_pretrained(zoo_model, source: str,
                    skip_mismatched_head: Optional[bool] = None):
    """Build a zoo model and load pretrained weights (the reference's
    ``ZooModel.initPretrained()`` shape)::

        net = init_pretrained(VGG16(), "vgg16_keras")

    ``skip_mismatched_head`` defaults to True when the model's
    num_classes differs from the artifact's 1000-way head.
    """
    net = zoo_model.build()
    if skip_mismatched_head is None:
        skip_mismatched_head = getattr(zoo_model, "num_classes", 1000) != 1000
    load_sequential_weights(net, source,
                            skip_mismatched_head=skip_mismatched_head)
    return net
