"""Regression evaluation.

Reference parity: org.nd4j.evaluation.regression.RegressionEvaluation —
per-column MSE/MAE/RMSE/RSE/PC (Pearson correlation)/R².
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class RegressionEvaluation:
    def __init__(self, n_columns: Optional[int] = None):
        self.n_columns = n_columns
        self._n = 0
        self._sum_err2 = None
        self._sum_abs = None
        self._sum_y = None
        self._sum_y2 = None
        self._sum_p = None
        self._sum_p2 = None
        self._sum_yp = None

    def eval(self, labels, predictions) -> None:
        y = np.asarray(getattr(labels, "to_numpy", lambda: labels)())
        p = np.asarray(getattr(predictions, "to_numpy", lambda: predictions)())
        y = y.reshape(len(y), -1).astype(np.float64)
        p = p.reshape(y.shape).astype(np.float64)
        if self._sum_err2 is None:
            c = y.shape[1]
            self.n_columns = c
            self._sum_err2 = np.zeros(c)
            self._sum_abs = np.zeros(c)
            self._sum_y = np.zeros(c)
            self._sum_y2 = np.zeros(c)
            self._sum_p = np.zeros(c)
            self._sum_p2 = np.zeros(c)
            self._sum_yp = np.zeros(c)
        e = p - y
        self._n += len(y)
        self._sum_err2 += (e ** 2).sum(0)
        self._sum_abs += np.abs(e).sum(0)
        self._sum_y += y.sum(0)
        self._sum_y2 += (y ** 2).sum(0)
        self._sum_p += p.sum(0)
        self._sum_p2 += (p ** 2).sum(0)
        self._sum_yp += (y * p).sum(0)

    def mean_squared_error(self, col: int = 0) -> float:
        return float(self._sum_err2[col] / self._n)

    def mean_absolute_error(self, col: int = 0) -> float:
        return float(self._sum_abs[col] / self._n)

    def root_mean_squared_error(self, col: int = 0) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int = 0) -> float:
        ss_tot = self._sum_y2[col] - self._sum_y[col] ** 2 / self._n
        ss_res = self._sum_err2[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot else 0.0

    def pearson_correlation(self, col: int = 0) -> float:
        n = self._n
        cov = self._sum_yp[col] - self._sum_y[col] * self._sum_p[col] / n
        vy = self._sum_y2[col] - self._sum_y[col] ** 2 / n
        vp = self._sum_p2[col] - self._sum_p[col] ** 2 / n
        d = np.sqrt(vy * vp)
        return float(cov / d) if d else 0.0

    def stats(self) -> str:
        cols = range(self.n_columns)
        lines = ["Column    MSE        MAE        RMSE       R^2        PC"]
        for c in cols:
            lines.append(
                f"{c:<8} {self.mean_squared_error(c):<10.5f} "
                f"{self.mean_absolute_error(c):<10.5f} "
                f"{self.root_mean_squared_error(c):<10.5f} "
                f"{self.r_squared(c):<10.5f} "
                f"{self.pearson_correlation(c):<10.5f}")
        return "\n".join(lines)
