"""Evaluation metrics (reference: org.nd4j.evaluation)."""
from deeplearning4j_tpu.evaluation.calibration import (
    EvaluationCalibration, Histogram, ReliabilityDiagram, channel_scales,
    histogram_quantile)
from deeplearning4j_tpu.evaluation.classification import (
    Evaluation, EvaluationBinary, ROC, ROCBinary, ROCMultiClass)
from deeplearning4j_tpu.evaluation.regression import RegressionEvaluation

__all__ = ["Evaluation", "EvaluationBinary", "EvaluationCalibration",
           "Histogram", "ReliabilityDiagram", "ROC", "ROCBinary",
           "ROCMultiClass", "RegressionEvaluation", "channel_scales",
           "histogram_quantile"]
