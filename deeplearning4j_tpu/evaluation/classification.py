"""Classification evaluation.

Reference parity: org.nd4j.evaluation.classification —
Evaluation (Evaluation.java:57: accuracy/precision/recall/F1/MCC, confusion
matrix, top-N), EvaluationBinary (per-output binary metrics), ROC
(ROC.java: thresholded TPR/FPR + AUC/AUPRC), ROCBinary, ROCMultiClass.
Metrics accumulate incrementally across eval(labels, predictions) calls
exactly like the reference's record-then-report design; math is host-side
numpy (metric finalization is not a device workload).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def _to_np(a):
    try:
        return np.asarray(a.to_numpy())
    except AttributeError:
        return np.asarray(a)


class Evaluation:
    """Multi-class evaluation (reference: classification/Evaluation.java:57)."""

    def __init__(self, num_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None, top_n: int = 1):
        self.num_classes = num_classes
        self.label_names = labels
        self.top_n = top_n
        self._conf: Optional[np.ndarray] = None   # [actual, predicted]
        self._top_n_correct = 0
        self._count = 0

    # ------------------------------------------------------------------
    def eval(self, labels, predictions) -> None:
        """Accumulate a batch. labels: one-hot or class indices;
        predictions: probabilities/scores (N, C)."""
        y = _to_np(labels)
        p = _to_np(predictions)
        if p.ndim != 2:
            raise ValueError(f"predictions must be (N, C), got {p.shape}")
        n_classes = p.shape[1]
        if self.num_classes is None:
            self.num_classes = n_classes
        if self._conf is None:
            self._conf = np.zeros((self.num_classes, self.num_classes),
                                  np.int64)
        y_idx = y.argmax(-1) if y.ndim == 2 else y.astype(int)
        p_idx = p.argmax(-1)
        np.add.at(self._conf, (y_idx, p_idx), 1)
        self._count += len(y_idx)
        if self.top_n > 1:
            top = np.argsort(-p, axis=-1)[:, :self.top_n]
            self._top_n_correct += int((top == y_idx[:, None]).any(-1).sum())
        else:
            self._top_n_correct += int((p_idx == y_idx).sum())

    # ------------------------------------------------------------------
    def _require(self):
        if self._conf is None:
            raise ValueError("no data evaluated yet")

    def confusion_matrix(self) -> np.ndarray:
        self._require()
        return self._conf.copy()

    def accuracy(self) -> float:
        self._require()
        return float(np.trace(self._conf)) / max(self._count, 1)

    def top_n_accuracy(self) -> float:
        self._require()
        return self._top_n_correct / max(self._count, 1)

    def true_positives(self, c: int) -> int:
        return int(self._conf[c, c])

    def false_positives(self, c: int) -> int:
        return int(self._conf[:, c].sum() - self._conf[c, c])

    def false_negatives(self, c: int) -> int:
        return int(self._conf[c, :].sum() - self._conf[c, c])

    def precision(self, c: Optional[int] = None) -> float:
        """Per-class, or macro-average over classes seen (reference
        default: macro, excluding classes with 0 predictions+labels)."""
        self._require()
        if c is not None:
            denom = self._conf[:, c].sum()
            return float(self._conf[c, c] / denom) if denom else 0.0
        vals = [self.precision(i) for i in range(self.num_classes)
                if self._conf[:, i].sum() + self._conf[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, c: Optional[int] = None) -> float:
        self._require()
        if c is not None:
            denom = self._conf[c, :].sum()
            return float(self._conf[c, c] / denom) if denom else 0.0
        vals = [self.recall(i) for i in range(self.num_classes)
                if self._conf[:, i].sum() + self._conf[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, c: Optional[int] = None) -> float:
        if c is not None:
            p, r = self.precision(c), self.recall(c)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        vals = [self.f1(i) for i in range(self.num_classes)
                if self._conf[:, i].sum() + self._conf[i, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def matthews_correlation(self) -> float:
        """Multi-class MCC (reference: Evaluation.matthewsCorrelation)."""
        self._require()
        c = self._conf.astype(np.float64)
        t = c.sum(1)          # actual counts
        p = c.sum(0)          # predicted counts
        n = c.sum()
        cov_tp = np.trace(c) * n - t @ p
        denom = np.sqrt(n * n - p @ p) * np.sqrt(n * n - t @ t)
        return float(cov_tp / denom) if denom else 0.0

    def stats(self) -> str:
        self._require()
        names = self.label_names or [str(i) for i in range(self.num_classes)]
        lines = [
            "========================Evaluation Metrics========================",
            f" # of classes:    {self.num_classes}",
            f" Accuracy:        {self.accuracy():.4f}",
            f" Precision:       {self.precision():.4f}",
            f" Recall:          {self.recall():.4f}",
            f" F1 Score:        {self.f1():.4f}",
        ]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: "
                         f"{self.top_n_accuracy():.4f}")
        lines.append("\n=========================Confusion Matrix=========================")
        header = "     " + " ".join(f"{n:>5}" for n in names)
        lines.append(header)
        for i, row in enumerate(self._conf):
            lines.append(f"{names[i]:>4} " + " ".join(f"{v:>5}" for v in row))
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output binary metrics at threshold 0.5 (reference:
    classification/EvaluationBinary.java)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self._tp = self._fp = self._tn = self._fn = None

    def eval(self, labels, predictions) -> None:
        y = _to_np(labels)
        p = (_to_np(predictions) >= self.threshold)
        y = y.reshape(y.shape[0], -1).astype(bool)
        p = p.reshape(p.shape[0], -1)
        if self._tp is None:
            n_out = y.shape[1]
            self._tp = np.zeros(n_out, np.int64)
            self._fp = np.zeros(n_out, np.int64)
            self._tn = np.zeros(n_out, np.int64)
            self._fn = np.zeros(n_out, np.int64)
        self._tp += (p & y).sum(0)
        self._fp += (p & ~y).sum(0)
        self._tn += (~p & ~y).sum(0)
        self._fn += (~p & y).sum(0)

    def accuracy(self, i: int = 0) -> float:
        tot = self._tp[i] + self._fp[i] + self._tn[i] + self._fn[i]
        return float((self._tp[i] + self._tn[i]) / tot) if tot else 0.0

    def precision(self, i: int = 0) -> float:
        d = self._tp[i] + self._fp[i]
        return float(self._tp[i] / d) if d else 0.0

    def recall(self, i: int = 0) -> float:
        d = self._tp[i] + self._fn[i]
        return float(self._tp[i] / d) if d else 0.0

    def f1(self, i: int = 0) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0


class ROC:
    """Binary ROC/AUC with exact thresholding (reference:
    classification/ROC.java; thresholdSteps=0 → exact mode)."""

    def __init__(self):
        self._scores: List[np.ndarray] = []
        self._labels: List[np.ndarray] = []

    def eval(self, labels, predictions) -> None:
        y = _to_np(labels)
        p = _to_np(predictions)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
            y = y[:, 1] if y.ndim == 2 else y
        self._scores.append(p.reshape(-1))
        self._labels.append(y.reshape(-1))

    def _collect(self):
        if not self._scores:
            raise ValueError("no data evaluated yet")
        return np.concatenate(self._scores), np.concatenate(self._labels)

    def roc_curve(self):
        """(fpr, tpr, thresholds) sorted by descending threshold."""
        s, y = self._collect()
        order = np.argsort(-s)
        y = y[order].astype(bool)
        tps = np.cumsum(y)
        fps = np.cumsum(~y)
        tpr = tps / max(y.sum(), 1)
        fpr = fps / max((~y).sum(), 1)
        return (np.concatenate([[0.0], fpr]), np.concatenate([[0.0], tpr]),
                np.concatenate([[np.inf], s[order]]))

    def auc(self) -> float:
        fpr, tpr, _ = self.roc_curve()
        return float(np.trapezoid(tpr, fpr))

    def auprc(self) -> float:
        s, y = self._collect()
        order = np.argsort(-s)
        y = y[order].astype(bool)
        tps = np.cumsum(y)
        precision = tps / np.arange(1, len(y) + 1)
        recall = tps / max(y.sum(), 1)
        return float(np.trapezoid(precision, recall))


class ROCBinary:
    """Per-output ROC (reference: ROCBinary.java)."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions) -> None:
        y = _to_np(labels).reshape(len(_to_np(labels)), -1)
        p = _to_np(predictions).reshape(y.shape)
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(y.shape[1])]
        for i, roc in enumerate(self._rocs):
            roc.eval(y[:, i], p[:, i])

    def auc(self, i: int = 0) -> float:
        return self._rocs[i].auc()


class ROCMultiClass:
    """One-vs-all ROC per class (reference: ROCMultiClass.java)."""

    def __init__(self):
        self._rocs: Optional[List[ROC]] = None

    def eval(self, labels, predictions) -> None:
        y = _to_np(labels)
        p = _to_np(predictions)
        if y.ndim != 2:
            y = np.eye(p.shape[1])[y.astype(int)]
        if self._rocs is None:
            self._rocs = [ROC() for _ in range(p.shape[1])]
        for c, roc in enumerate(self._rocs):
            roc.eval(y[:, c], p[:, c])

    def auc(self, c: int = 0) -> float:
        return self._rocs[c].auc()

    def average_auc(self) -> float:
        return float(np.mean([r.auc() for r in self._rocs]))
