"""Calibration analysis for classifiers.

Reference parity: org.nd4j.evaluation.classification.EvaluationCalibration
(nd4j-api/.../evaluation/classification/EvaluationCalibration.java:53) —
reliability diagrams, per-class label/prediction counts, residual plots,
and probability histograms. This implementation accumulates all counts
with vectorized numpy binning (one `bincount` per batch instead of the
reference's per-bin masked reductions) and adds expected calibration
error (ECE), the modern scalar summary of the reliability diagram.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

DEFAULT_RELIABILITY_BINS = 10
DEFAULT_HISTOGRAM_BINS = 50


def _to_np(a) -> np.ndarray:
    return np.asarray(a, dtype=np.float64)


def _as_one_hot(labels: np.ndarray, num_classes: int,
                n_rows: int) -> np.ndarray:
    """labels as [rows, C] one-hot: accepts class indices of any shape
    with n_rows entries ([N], [N,1], [N,T]...) or one-hot/probabilities
    with a trailing class dim."""
    if labels.size == n_rows and (labels.ndim == 1 or
                                  labels.shape[-1] != num_classes
                                  or num_classes == 1):
        idx = labels.reshape(-1).astype(np.int64)
        return np.eye(num_classes, dtype=np.float64)[idx]
    return labels.reshape(-1, num_classes)


class Histogram:
    """A fixed-range histogram (reference: curves/Histogram.java)."""

    def __init__(self, title: str, lower: float, upper: float,
                 counts: np.ndarray):
        self.title = title
        self.lower = float(lower)
        self.upper = float(upper)
        self.bin_counts = np.asarray(counts, dtype=np.int64)

    @property
    def num_bins(self) -> int:
        return int(self.bin_counts.shape[0])

    def bin_edges(self) -> np.ndarray:
        return np.linspace(self.lower, self.upper, self.num_bins + 1)

    def __repr__(self):
        return (f"Histogram({self.title!r}, [{self.lower}, {self.upper}], "
                f"n={int(self.bin_counts.sum())})")


def _quantile_from_counts(counts: np.ndarray, lowers: np.ndarray,
                          uppers: np.ndarray, q: float) -> np.ndarray:
    """Value at quantile ``q`` for each row of binned ``counts`` —
    right-edge convention: the smallest bin upper edge below which at
    least ``q`` of the mass lies. Shared by :func:`histogram_quantile`
    (one histogram) and :func:`channel_scales` (one row per channel)."""
    counts = np.asarray(counts, np.float64)
    nb = counts.shape[1]
    total = counts.sum(axis=1)
    cum = np.cumsum(counts, axis=1)
    target = max(float(q), 0.0) * total[:, None]
    b = np.argmax(cum >= target, axis=1)        # first bin reaching q
    lowers = np.asarray(lowers, np.float64)
    uppers = np.asarray(uppers, np.float64)
    return lowers + (b + 1) / nb * (uppers - lowers)


def histogram_quantile(hist: Histogram, q: float) -> float:
    """Value at quantile ``q`` of a :class:`Histogram`'s binned mass
    (right-edge convention). The binned analogue of ``np.quantile`` for
    data only available as fixed-range counts."""
    if not 0.0 < q <= 1.0:
        raise ValueError(f"quantile must be in (0, 1], got {q}")
    return float(_quantile_from_counts(
        hist.bin_counts[None], [hist.lower], [hist.upper], q)[0])


def channel_scales(samples, method: str = "absmax", quantile: float = 0.999,
                   num_bins: int = 512, qmax: float = 127.0) -> np.ndarray:
    """NaN-safe per-channel symmetric-int quantization scales.

    ``samples``: an array whose LAST axis is the channel axis (leading
    axes are flattened into observations). Returns ``scales`` of shape
    ``[channels]`` (float32) such that ``round(x / scale)`` clipped to
    ``[-qmax, qmax]`` is the int payload and ``payload * scale`` the
    dequantized value.

    - ``method="absmax"``: scale = max |x| / qmax — exact range cover,
      the right default for weights (every value representable).
    - ``method="quantile"``: per-channel |x| is binned into the same
      fixed-range histogram layout as :class:`Histogram` /
      :class:`EvaluationCalibration` and the scale is the value at
      ``quantile`` (right-edge convention, via the shared
      :func:`_quantile_from_counts`) — clips activation/KV outliers so
      the int grid spends its codes on the mass, not one spike.

    NaN/Inf observations are ignored; a channel with no positive finite
    mass (all-zero, all-NaN) gets scale 1.0 — its payload quantizes to
    0 and dequantizes to 0, never NaN/Inf (tests/test_evaluation.py).
    """
    if method not in ("absmax", "quantile"):
        raise ValueError(f"method must be 'absmax' or 'quantile', "
                         f"got {method!r}")
    if not 0.0 < quantile <= 1.0:
        raise ValueError(f"quantile must be in (0, 1], got {quantile}")
    if int(num_bins) <= 0:
        raise ValueError("num_bins must be positive")
    x = np.asarray(samples, np.float64)
    if x.ndim == 0:
        raise ValueError("samples must have a channel axis")
    c = x.shape[-1]
    a = np.abs(x.reshape(-1, c))
    finite = np.isfinite(a)
    a = np.where(finite, a, 0.0)
    amax = a.max(axis=0) if a.shape[0] else np.zeros(c)
    if method == "absmax":
        peak = amax
    else:
        nb = int(num_bins)
        # the EvaluationCalibration binning pattern: normalize to the
        # per-channel range, clip into nb bins, one bincount total
        safe = np.where(amax > 0, amax, 1.0)
        bins = np.clip((a / safe * nb).astype(np.int64), 0, nb - 1)
        flat = (np.broadcast_to(np.arange(c), a.shape) * nb + bins)
        counts = np.bincount(flat.reshape(-1),
                             weights=finite.reshape(-1).astype(np.float64),
                             minlength=c * nb).reshape(c, nb)
        peak = _quantile_from_counts(counts, np.zeros(c), amax, quantile)
    peak = np.where(np.isfinite(peak) & (peak > 0), peak, float(qmax))
    return (peak / float(qmax)).astype(np.float32)


class ReliabilityDiagram:
    """Mean predicted probability vs observed frequency per confidence bin
    (reference: curves/ReliabilityDiagram.java)."""

    def __init__(self, title: str, mean_predicted: np.ndarray,
                 frac_positives: np.ndarray, counts: np.ndarray):
        self.title = title
        self.mean_predicted_value = mean_predicted
        self.frac_positives = frac_positives
        self.bin_counts = counts

    def __repr__(self):
        return f"ReliabilityDiagram({self.title!r}, bins={len(self.bin_counts)})"


class EvaluationCalibration:
    """Accumulating calibration evaluation.

    Reference parity: EvaluationCalibration.java:106-467. `eval()` may be
    called repeatedly with batches; reports are computed on demand.
    """

    def __init__(self, reliability_bins: int = DEFAULT_RELIABILITY_BINS,
                 histogram_bins: int = DEFAULT_HISTOGRAM_BINS,
                 exclude_empty_bins: bool = True):
        if reliability_bins <= 0 or histogram_bins <= 0:
            raise ValueError("bin counts must be positive")
        self.reliability_bins = reliability_bins
        self.histogram_bins = histogram_bins
        self.exclude_empty_bins = exclude_empty_bins
        self._num_classes: Optional[int] = None
        self.reset()

    # -- accumulation ------------------------------------------------------

    def reset(self) -> None:
        self._num_classes = None
        self._rdiag_pos = None          # [C, RB] positives per bin
        self._rdiag_total = None        # [C, RB] examples per bin
        self._rdiag_sum_pred = None     # [C, RB] sum of predicted prob
        self._label_counts = None       # [C]
        self._pred_counts = None        # [C]
        self._residual_all = None       # [HB] |label - p| over all entries
        self._residual_by_label = None  # [C, HB] for rows whose label == c
        self._prob_all = None           # [HB] predicted prob, all entries
        self._prob_by_label = None      # [C, HB]

    def _init_state(self, num_classes: int) -> None:
        self._num_classes = num_classes
        rb, hb, c = self.reliability_bins, self.histogram_bins, num_classes
        self._rdiag_pos = np.zeros((c, rb), dtype=np.int64)
        self._rdiag_total = np.zeros((c, rb), dtype=np.int64)
        self._rdiag_sum_pred = np.zeros((c, rb), dtype=np.float64)
        self._label_counts = np.zeros(c, dtype=np.int64)
        self._pred_counts = np.zeros(c, dtype=np.int64)
        self._residual_all = np.zeros(hb, dtype=np.int64)
        self._residual_by_label = np.zeros((c, hb), dtype=np.int64)
        self._prob_all = np.zeros(hb, dtype=np.int64)
        self._prob_by_label = np.zeros((c, hb), dtype=np.int64)

    def eval(self, labels, predictions, mask=None) -> None:
        """Accumulate a batch. labels: one-hot [N,C] or indices [N];
        predictions: probabilities [N,C]. Rows with mask==0 are dropped."""
        p = _to_np(predictions)
        if p.ndim != 2:
            p = p.reshape(-1, p.shape[-1])
        n, c = p.shape
        y = _as_one_hot(_to_np(labels), c, n)
        if mask is not None:
            keep = _to_np(mask).reshape(-1) != 0
            p, y = p[keep], y[keep]
            n = p.shape[0]
        if self._num_classes is None:
            self._init_state(c)
        elif c != self._num_classes:
            raise ValueError(
                f"num_classes changed: {self._num_classes} -> {c}")
        if n == 0:
            return

        rb, hb = self.reliability_bins, self.histogram_bins
        # Reliability diagram: bin each (example, class) prob into rb bins.
        bins = np.clip((p * rb).astype(np.int64), 0, rb - 1)  # [N, C]
        cls = np.broadcast_to(np.arange(c), (n, c))
        flat = (cls * rb + bins).reshape(-1)
        self._rdiag_total += np.bincount(
            flat, minlength=c * rb).reshape(c, rb)
        self._rdiag_pos += np.bincount(
            flat, weights=y.reshape(-1),
            minlength=c * rb).reshape(c, rb).astype(np.int64)
        self._rdiag_sum_pred += np.bincount(
            flat, weights=p.reshape(-1), minlength=c * rb).reshape(c, rb)

        # Label / argmax-prediction counts.
        lab_idx = y.argmax(axis=1)
        self._label_counts += np.bincount(lab_idx, minlength=c)
        self._pred_counts += np.bincount(p.argmax(axis=1), minlength=c)

        # Residual plot: |label - p| over every (example, class) entry,
        # range [0, 1] (EvaluationCalibration.java:268-305).
        resid = np.abs(y - p)
        rbins = np.clip((resid * hb).astype(np.int64), 0, hb - 1)
        self._residual_all += np.bincount(
            rbins.reshape(-1), minlength=hb)
        pbins = np.clip((p * hb).astype(np.int64), 0, hb - 1)
        self._prob_all += np.bincount(pbins.reshape(-1), minlength=hb)
        # Per-label-class versions: for rows labeled class c, bin ONLY
        # column c — the positive-label entry (i, c) — matching the
        # reference residualPlotByLabelClass / probHistogramByLabelClass
        # (l.mul(currBinBitMask).sum(0): the label one-hot masks out the
        # other classes' columns). One entry per row, not C.
        rbin_lab = rbins[np.arange(n), lab_idx]
        self._residual_by_label += np.bincount(
            lab_idx * hb + rbin_lab, minlength=c * hb).reshape(c, hb)
        pbin_lab = pbins[np.arange(n), lab_idx]
        self._prob_by_label += np.bincount(
            lab_idx * hb + pbin_lab, minlength=c * hb).reshape(c, hb)

    def merge(self, other: "EvaluationCalibration") -> None:
        if other._num_classes is None:
            return
        if self._num_classes is None:
            self._init_state(other._num_classes)
        for name in ("_rdiag_pos", "_rdiag_total", "_rdiag_sum_pred",
                     "_label_counts", "_pred_counts", "_residual_all",
                     "_residual_by_label", "_prob_all", "_prob_by_label"):
            setattr(self, name, getattr(self, name) + getattr(other, name))

    # -- reports -----------------------------------------------------------

    def _require(self):
        if self._num_classes is None:
            raise RuntimeError("eval() has not been called")

    def num_classes(self) -> int:
        self._require()
        return self._num_classes

    def reliability_diagram(self, class_idx: int) -> ReliabilityDiagram:
        """(reference: getReliabilityDiagram, EvaluationCalibration.java:365)"""
        self._require()
        total = self._rdiag_total[class_idx]
        pos = self._rdiag_pos[class_idx]
        sum_pred = self._rdiag_sum_pred[class_idx]
        if self.exclude_empty_bins:
            keep = total > 0
            total, pos, sum_pred = total[keep], pos[keep], sum_pred[keep]
        with np.errstate(invalid="ignore", divide="ignore"):
            mean_pred = np.where(total > 0, sum_pred / total, 0.0)
            frac_pos = np.where(total > 0, pos / np.maximum(total, 1), 0.0)
        return ReliabilityDiagram(
            f"Reliability diagram: class {class_idx}",
            mean_pred, frac_pos, total.copy())

    def expected_calibration_error(self, class_idx: Optional[int] = None
                                   ) -> float:
        """ECE = sum_b (n_b / N) * |acc_b - conf_b| (not in the reference;
        the standard scalar summary of its reliability diagram)."""
        self._require()
        if class_idx is None:
            total = self._rdiag_total.sum(axis=0)
            pos = self._rdiag_pos.sum(axis=0)
            sum_pred = self._rdiag_sum_pred.sum(axis=0)
        else:
            total = self._rdiag_total[class_idx]
            pos = self._rdiag_pos[class_idx]
            sum_pred = self._rdiag_sum_pred[class_idx]
        n = total.sum()
        if n == 0:
            return 0.0
        keep = total > 0
        conf = sum_pred[keep] / total[keep]
        acc = pos[keep] / total[keep]
        return float(np.sum(total[keep] / n * np.abs(acc - conf)))

    def label_counts_each_class(self) -> np.ndarray:
        self._require()
        return self._label_counts.copy()

    def prediction_counts_each_class(self) -> np.ndarray:
        self._require()
        return self._pred_counts.copy()

    def residual_plot_all_classes(self) -> Histogram:
        self._require()
        return Histogram("Residual plot - all predictions and labels",
                         0.0, 1.0, self._residual_all)

    def residual_plot(self, label_class_idx: int) -> Histogram:
        self._require()
        return Histogram(
            f"Residual plot - predictions for label class {label_class_idx}",
            0.0, 1.0, self._residual_by_label[label_class_idx])

    def probability_histogram_all_classes(self) -> Histogram:
        self._require()
        return Histogram("Network probabilities", 0.0, 1.0, self._prob_all)

    def probability_histogram(self, label_class_idx: int) -> Histogram:
        self._require()
        return Histogram(
            f"Network probabilities: label class {label_class_idx}",
            0.0, 1.0, self._prob_by_label[label_class_idx])

    def stats(self) -> str:
        self._require()
        c = self._num_classes
        lines = [f"EvaluationCalibration: {c} classes, "
                 f"{int(self._label_counts.sum())} examples",
                 f"  ECE (all classes): "
                 f"{self.expected_calibration_error():.4f}"]
        for i in range(c):
            lines.append(
                f"  class {i}: labels={int(self._label_counts[i])} "
                f"predicted={int(self._pred_counts[i])} "
                f"ECE={self.expected_calibration_error(i):.4f}")
        return "\n".join(lines)
