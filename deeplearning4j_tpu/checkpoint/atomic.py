"""Crash-safe filesystem primitives shared by every persistence path.

The invariant all writers in this codebase rely on: a reader NEVER
observes a partially written file at its final path. The recipe is the
standard one (write a temp file in the destination directory, flush +
fsync the data, ``os.replace`` into place, fsync the directory so the
rename itself is durable). ``os.replace`` is atomic on POSIX when source
and target live on the same filesystem — which is why the temp file MUST
be created next to the target, never in /tmp.

Reference parity: the reference's ModelSerializer writes straight to the
final path (ModelSerializer.java — a killed JVM leaves a torn zip); this
module is the Orbax-style correction every serde path here routes
through (model_serde.save_net_zip, autodiff/serde.save, hub.cache.add,
earlystopping LocalFileModelSaver, and the checkpoint/ manager's commit
protocol).
"""
from __future__ import annotations

import contextlib
import os
import shutil
import tempfile
from typing import Callable, Iterator


def _umask_mode(base: int = 0o666) -> int:
    """The mode a plain open() would have produced under the current
    umask — mkstemp creates 0600, which must not silently narrow
    permissions on published artifacts (shared checkpoint dirs)."""
    cur = os.umask(0)
    os.umask(cur)
    return base & ~cur


def fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed/created entry survives a
    crash (no-op on platforms that cannot open directories)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:          # pragma: no cover - windows / exotic fs
        return
    try:
        os.fsync(fd)
    except OSError:          # pragma: no cover
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_output_file(path, suffix: str = ".tmp") -> Iterator[str]:
    """Context manager yielding a temp path in ``path``'s directory; on
    clean exit the temp file is fsynced and atomically renamed to
    ``path``. On error the temp file is removed and nothing is visible
    at ``path``::

        with atomic_output_file(dst) as tmp:
            write_everything_to(tmp)
        # dst now exists, complete, or was never touched
    """
    path = os.fspath(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".",
                               suffix=suffix)
    os.close(fd)
    try:
        yield tmp
        # the writer may have replaced (not appended to) the temp file;
        # open it ourselves to fsync whatever is there now
        with open(tmp, "rb") as fh:
            os.fsync(fh.fileno())
        os.chmod(tmp, _umask_mode())     # mkstemp's 0600 -> umask mode
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def atomic_write_bytes(path, data: bytes) -> None:
    """Atomically publish ``data`` at ``path``."""
    with atomic_output_file(path) as tmp:
        with open(tmp, "wb") as fh:
            fh.write(data)


def atomic_write_via(path, write_fn: Callable[[str], None]) -> None:
    """Run ``write_fn(temp_path)`` and atomically publish the result at
    ``path``. The serializer must write to EXACTLY the path it is given
    (``model.save``, ``zipfile.ZipFile`` do); serializers that append
    their own extension (``np.savez`` adds ``.npz``) would leave the
    temp file untouched and publish an empty artifact — pass a wrapper
    that renames, or use ``atomic_write_bytes``."""
    with atomic_output_file(path) as tmp:
        write_fn(tmp)


def atomic_copy(src_path, dst_path) -> str:
    """Copy ``src_path`` to ``dst_path`` so the destination appears
    atomically (temp copy in the destination directory + rename)."""
    with atomic_output_file(dst_path) as tmp:
        shutil.copy2(src_path, tmp)
    return os.fspath(dst_path)
