"""checkpoint/ — asynchronous, atomic, sharded training checkpoints.

The persistence layer the elastic-training roadmap builds on:

- ``atomic``      — crash-safe write primitives (temp file + fsync +
  ``os.replace``) shared by every serde path in the codebase;
- ``manifest``    — per-file sha256 manifest + COMMIT marker: the
  commit protocol that makes a checkpoint directory verifiable;
- ``state``       — ``TrainingState`` capture/restore: params, updater
  state, iteration/epoch counters, RNG base seed, normalizer stats —
  everything needed for BIT-EXACT resume;
- ``manager``     — ``CheckpointManager``: async background writer,
  atomic commits, retention (keep-last-N / keep-every-N-epochs /
  pin-best), multihost per-process shards with a pre-commit barrier;
- ``reshard``     — elastic resharded restore: reassemble global
  arrays from ANY committed shard set and re-slice them for the
  CURRENT mesh (save on N hosts, restore on M;
  docs/elastic_training.md);
- ``listener``    — DL4J-parity ``CheckpointListener`` (every N
  iterations / epochs / seconds) for any ``fit(listeners=...)`` path;
- ``savers``      — early-stopping model saver routed through the
  manager;
- ``preemption``  — SIGTERM → final synchronous checkpoint → exit;
- ``scrub``       — :class:`Scrubber`: rate-limited background
  re-hashing of committed step dirs against their manifests during
  idle time, quarantining rotten steps aside (``step_N.rotten`` +
  typed record) so ``restore_latest`` never lands on bit-rot
  mid-recovery; ``python -m deeplearning4j_tpu.checkpoint scrub`` is
  the offline CLI (integrity rail, docs/fault_tolerance.md).

Reference parity: util/ModelSerializer + optimize/listeners/
CheckpointListener, redesigned Orbax-style (off-critical-path
serialization, atomic publish, integrity-verified restore).
"""
from deeplearning4j_tpu.checkpoint.atomic import (
    atomic_copy, atomic_output_file, atomic_write_bytes, atomic_write_via,
    fsync_dir)
from deeplearning4j_tpu.checkpoint.listener import CheckpointListener
from deeplearning4j_tpu.checkpoint.manager import (CheckpointError,
                                                   CheckpointManager,
                                                   ShardCountMismatchError,
                                                   TopologyChangedError)
from deeplearning4j_tpu.checkpoint.manifest import (is_committed, sha256_file,
                                                    verify_dir)
from deeplearning4j_tpu.checkpoint.preemption import Preempted, PreemptionHook
from deeplearning4j_tpu.checkpoint.reshard import restore_resharded
from deeplearning4j_tpu.checkpoint.savers import CheckpointModelSaver
from deeplearning4j_tpu.checkpoint.scrub import Scrubber
from deeplearning4j_tpu.checkpoint.state import (TrainingState,
                                                 capture_topology,
                                                 capture_training_state,
                                                 restore_training_state)

__all__ = [
    "CheckpointError", "CheckpointListener", "CheckpointManager",
    "CheckpointModelSaver", "Preempted", "PreemptionHook",
    "Scrubber", "ShardCountMismatchError", "TopologyChangedError",
    "TrainingState",
    "atomic_copy", "atomic_output_file", "atomic_write_bytes",
    "atomic_write_via", "capture_topology", "capture_training_state",
    "fsync_dir", "is_committed", "restore_resharded",
    "restore_training_state", "sha256_file", "verify_dir",
]
