"""Early-stopping model saver backed by the CheckpointManager.

Implements the saver protocol ``autodiff.earlystopping`` expects
(``save_best`` / ``save_latest`` / ``restore_best``) on top of the
atomic commit path, so "best model so far" can never be torn by a crash
during an improvement save — the previous best stays committed until
the new one is.

Reference parity: earlystopping/saver/LocalFileModelSaver, with the
manager's protocol replacing the direct bestModel.bin write.
"""
from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
from deeplearning4j_tpu.checkpoint.state import (capture_training_state,
                                                 restore_training_state)


class CheckpointModelSaver:
    """Saves best/latest models as committed checkpoint steps.

    Steps are the epoch number; the best step is pinned (retention never
    deletes it) and tagged with the score, so ``manager.best_step()``
    agrees with ``restore_best``.
    """

    def __init__(self, manager_or_dir, blocking: bool = True):
        if isinstance(manager_or_dir, CheckpointManager):
            self.manager = manager_or_dir
        else:
            self.manager = CheckpointManager(
                manager_or_dir, keep_last_n=2, pin_best_metric="score")
        self.blocking = blocking
        self.best_step: Optional[int] = None
        self.best_epoch = -1
        self.best_score = float("inf")
        self.latest_epoch = -1

    def save_best(self, model, epoch: int, score: float) -> None:
        state = capture_training_state(model, epoch=epoch)
        prev_best = self.best_step
        self.manager.save(int(epoch), state, metrics={"score": float(score)},
                          blocking=self.blocking, pin=True)
        # only the CURRENT best stays pinned; the dethroned one ages out
        # through keep_last_n like any other step
        if prev_best is not None and prev_best != int(epoch):
            self.manager.unpin(prev_best)
        self.best_step = int(epoch)
        self.best_epoch = int(epoch)
        self.best_score = float(score)

    def save_latest(self, model, epoch: int, score: float) -> None:
        state = capture_training_state(model, epoch=epoch)
        self.manager.save(int(epoch), state, metrics={"score": float(score)},
                          blocking=self.blocking)
        self.latest_epoch = int(epoch)

    def restore_best(self, model):
        self.manager.wait_until_finished()
        if self.best_step is None:
            return model
        state = self.manager.restore(self.best_step)
        restore_training_state(model, state)
        return model
