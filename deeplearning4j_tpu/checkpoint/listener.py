"""DL4J-parity CheckpointListener backed by the CheckpointManager.

Reference parity: optimize/listeners/CheckpointListener.java — the
builder cadences (every N epochs / every N iterations / every N
seconds) and keep policies, re-based onto the atomic async manager so a
listener-driven checkpoint can neither tear a file nor stall the train
loop for serialization.

Plugs into every fit path that accepts ``listeners=``:
``MultiLayerNetwork.fit``, ``ComputationGraph.fit``, ``SameDiff.fit``,
and ``parallel.ParallelTrainer.fit``. Declares ``needs_params`` so the
fit loop syncs current params/updater state/iteration into the graph at
each listener flush — mid-epoch snapshots see the real training state,
not the state from the last epoch boundary.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

from deeplearning4j_tpu.autodiff.training import Listener
from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
from deeplearning4j_tpu.checkpoint.state import capture_training_state


class CheckpointListener(Listener):
    """Periodic checkpoints on an iteration / epoch / wall-clock cadence.

    ``manager_or_dir``: a CheckpointManager, or a directory path (a
    manager with ``keep_last_n=keep_last`` is created over it).
    At least one cadence must be set. Checkpoint steps are the global
    count of iterations COMPLETED at snapshot time (== the restored
    ``state.iteration``), identical across cadences and stable across
    restarts.
    """

    #: fit() syncs params + updater state + iteration into the graph at
    #: every listener flush when this is set
    needs_params = True

    def __init__(self, manager_or_dir,
                 every_n_iterations: Optional[int] = None,
                 every_n_epochs: Optional[int] = None,
                 every_n_seconds: Optional[float] = None,
                 keep_last: int = 3, normalizer=None,
                 save_on_training_end: bool = False):
        if isinstance(manager_or_dir, CheckpointManager):
            self.manager = manager_or_dir
        else:
            self.manager = CheckpointManager(manager_or_dir,
                                             keep_last_n=keep_last)
        if not any((every_n_iterations, every_n_epochs, every_n_seconds)):
            raise ValueError("set at least one cadence: every_n_iterations, "
                             "every_n_epochs, every_n_seconds")
        if every_n_iterations is not None and every_n_iterations <= 0:
            raise ValueError("every_n_iterations must be positive")
        if every_n_epochs is not None and every_n_epochs <= 0:
            raise ValueError("every_n_epochs must be positive")
        if every_n_seconds is not None and self.manager.process_count > 1:
            # each host's wall clock would fire divergently and the
            # processes would hang on mismatched commit barriers —
            # multihost cadence must be deterministic (iterations/epochs)
            raise ValueError(
                "every_n_seconds is not supported multihost: processes "
                "would decide to save at different steps and deadlock on "
                "the commit barrier; use every_n_iterations/every_n_epochs")
        self.every_n_iterations = every_n_iterations
        self.every_n_epochs = every_n_epochs
        self.every_n_seconds = every_n_seconds
        self.normalizer = normalizer
        self.save_on_training_end = save_on_training_end
        # scalar-delivery cadence: iteration checkpoints need flushes on
        # their own cadence; time-based ones need frequent flushes to
        # bound save latency (per-iteration delivery — the documented
        # cost of wall-clock cadence under a compiled step); epoch-only
        # listeners never need mid-epoch flushes, and because
        # needs_params makes every flush copy params + the optimizer
        # tree, their frequency is set huge so fit only flushes at
        # epoch boundaries
        if every_n_iterations is not None:
            self.frequency = every_n_iterations
        elif every_n_seconds is not None:
            self.frequency = 1
        else:
            self.frequency = 1_000_000_000
        self._epoch = 0
        self._last_time_save = None
        self._last_step: Optional[int] = None

    # -- builder (reference: CheckpointListener.builder(dir)...) --------
    class Builder:
        def __init__(self, directory):
            self._dir = directory
            self._kw = {}

        def keep_last(self, n: int):
            self._kw["keep_last"] = int(n); return self

        def save_every_n_epochs(self, n: int):
            self._kw["every_n_epochs"] = int(n); return self

        def save_every_n_iterations(self, n: int):
            self._kw["every_n_iterations"] = int(n); return self

        def save_every(self, seconds: float):
            self._kw["every_n_seconds"] = float(seconds); return self

        def build(self) -> "CheckpointListener":
            return CheckpointListener(self._dir, **self._kw)

    @staticmethod
    def builder(directory) -> "CheckpointListener.Builder":
        return CheckpointListener.Builder(directory)

    # -- cadence --------------------------------------------------------
    @staticmethod
    def _global_epoch(sd, fallback: int) -> int:
        """Epochs COMPLETED globally (tc.epoch_count), not the fit's
        local loop index. restore_training_state writes state.epoch back
        into tc.epoch_count, so a snapshot must record the global
        counter — a fit-local index from a resumed/retried fit would
        roll the epoch budget backwards on restore (the
        faults.FaultTolerantFit remaining-epochs accounting relies on
        this)."""
        tc = getattr(sd, "training_config", None)
        if tc is None:
            return int(fallback)
        return int(getattr(tc, "epoch_count", fallback))

    def _save(self, sd, step: int, blocking: bool = False) -> None:
        state = capture_training_state(sd, epoch=self._epoch,
                                       normalizer=self.normalizer)
        # capture_training_state reads tc.iteration_count, which the fit
        # flush has just synced; step is passed explicitly for cadence
        self.manager.save(step, state, blocking=blocking)
        self._last_step = step

    def on_training_start(self, sd):
        if self._last_time_save is None:
            self._last_time_save = time.perf_counter()

    def on_epoch_start(self, sd, epoch: int):
        self._epoch = self._global_epoch(sd, epoch)

    def iterations_done(self, sd, epoch: int, iterations: Sequence[int],
                        losses: Sequence[float]):
        self._epoch = self._global_epoch(sd, epoch)
        it = iterations[-1]
        fire = False
        # scalars arrive in bursts; the snapshot granularity is the
        # burst, so fire if ANY iteration in it hit the cadence (bursts
        # are at most ``frequency`` long, so at most one hit per burst)
        if self.every_n_iterations is not None and any(
                (i + 1) % self.every_n_iterations == 0 for i in iterations):
            fire = True
        if self.every_n_seconds is not None:
            now = time.perf_counter()
            if now - (self._last_time_save or 0) >= self.every_n_seconds:
                self._last_time_save = now
                fire = True
        # step = iterations COMPLETED (same numbering as the epoch
        # cadence's tc.iteration_count, so a step checkpointed by both
        # cadences dedupes instead of committing twice)
        step = it + 1
        if fire and step != self._last_step:
            self._save(sd, step)

    def on_epoch_end(self, sd, epoch: int, mean_loss: float):
        # tc.epoch_count is incremented before on_epoch_end fires, so
        # this is the completed count INCLUDING this epoch — restoring
        # an epoch-end snapshot resumes at the next epoch. The cadence
        # runs on the global count too, so it stays stable across
        # resumed/retried fits (for a fresh model it equals epoch + 1).
        self._epoch = self._global_epoch(sd, epoch + 1)
        if self.every_n_epochs is not None and \
                self._epoch % self.every_n_epochs == 0:
            tc = sd.training_config
            step = int(getattr(tc, "iteration_count", 0)) if tc else epoch
            if step != self._last_step:       # iteration cadence may have
                self._save(sd, step)          # just committed this state

    def on_training_end(self, sd):
        if self.save_on_training_end:
            tc = sd.training_config
            step = int(getattr(tc, "iteration_count", 0)) if tc else 0
            if step != self._last_step:
                self._save(sd, step, blocking=True)
        # surface any async write error before fit() returns
        self.manager.wait_until_finished()

    # -- introspection --------------------------------------------------
    def last_checkpoint(self) -> Optional[int]:
        """Newest committed step (after wait_until_finished)."""
        return self.manager.latest_step()
