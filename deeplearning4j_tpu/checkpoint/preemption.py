"""Preemption hook: a final synchronous checkpoint on SIGTERM.

TPU pods are preemptible; the scheduler sends SIGTERM with a grace
window before killing the job. This hook turns that window into a
committed checkpoint: the handler captures the live training state,
commits it through the manager's atomic protocol with ``blocking=True``
(the async queue is also drained first, so earlier in-flight saves are
not lost), and then raises ``Preempted`` (a ``SystemExit`` subclass) so
the process unwinds and exits with the conventional 128+SIGTERM code.

The reference has no analogue (a killed JVM loses everything since its
CheckpointListener writes non-atomically on the training thread).

Usage::

    with PreemptionHook(manager, net, epoch_provider=lambda: listener._epoch):
        net.fit(data, epochs=100, listeners=[listener])
    # SIGTERM during fit -> checkpoint committed, Preempted raised

Signal handlers only run on the main thread; install from the thread
that drives training.
"""
from __future__ import annotations

import os
import signal
from typing import Callable, Optional, Sequence

from deeplearning4j_tpu.checkpoint.manager import CheckpointManager
from deeplearning4j_tpu.checkpoint.state import capture_training_state


class Preempted(SystemExit):
    """Raised (after the final checkpoint commits) when a preemption
    signal arrives. Subclasses SystemExit so an unhandled preemption
    exits the process instead of printing a traceback."""

    def __init__(self, signum: int, step: Optional[int]):
        super().__init__(128 + signum)
        self.signum = signum
        self.step = step

    def __str__(self):
        return (f"preempted by signal {self.signum}; final checkpoint "
                f"step={self.step}")


class PreemptionHook:
    """Installs signal handlers that checkpoint-then-exit.

    ``model``: the network/SameDiff to snapshot at signal time.
    ``epoch_provider``: optional callable giving the current epoch for
    the snapshot metadata. ``reraise=False`` suppresses ``Preempted``
    (the handler only checkpoints and sets ``.preempted``; the caller
    polls and exits on its own schedule).

    Stacks with outer supervisors: a non-default handler that was
    installed for the same signal BEFORE this hook is invoked after the
    final checkpoint commits, so its cleanup still runs; if it raises
    (its own exit path), that wins over ``Preempted``.
    """

    def __init__(self, manager: CheckpointManager, model,
                 signals: Sequence[int] = (signal.SIGTERM,),
                 epoch_provider: Optional[Callable[[], int]] = None,
                 normalizer=None, reraise: bool = True,
                 drain_timeout: float = 60.0):
        self.manager = manager
        self.model = model
        self.signals = tuple(signals)
        self.epoch_provider = epoch_provider
        self.normalizer = normalizer
        self.reraise = reraise
        self.drain_timeout = drain_timeout
        self.preempted = False
        self.final_step: Optional[int] = None
        self._previous = {}
        self._installed = False

    # ------------------------------------------------------------------
    def install(self) -> "PreemptionHook":
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionHook":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ------------------------------------------------------------------
    def _handle(self, signum, frame) -> None:
        self.preempted = True
        # earlier async saves first — the final snapshot must be the
        # NEWEST committed step, and a half-written queue entry must not
        # race the rename. Bounded wait: the grace window must not be
        # spent stuck behind a wedged writer (or an interrupted save()
        # frame on this very thread whose enqueue never happened)
        try:
            self.manager.wait_until_finished(timeout=self.drain_timeout)
        except Exception:
            pass  # a failed earlier write must not block the final save
        try:
            # a sticky writer error from the drain above must not turn
            # the final save into a raise out of the signal handler
            self.manager.check_error()
        except Exception:
            pass
        epoch = self.epoch_provider() if self.epoch_provider else 0
        state = capture_training_state(self.model, epoch=epoch,
                                       normalizer=self.normalizer)
        step = int(state.iteration)
        try:
            # bounded: a writer thread wedged mid-commit must not eat
            # the whole grace window — better to exit checkpoint-less
            # than to be SIGKILLed mid-commit
            self.manager.save(step, state, blocking=True,
                              lock_timeout=self.drain_timeout)
            self.final_step = step
        except Exception:
            if not self.reraise:
                self._chain_previous(signum, frame)
                return
        # an outer supervisor's handler installed BEFORE this hook still
        # runs (after our commit): stacking hooks must not silently drop
        # the outer cleanup. Its exception (often its own SystemExit)
        # wins over our Preempted.
        self._chain_previous(signum, frame)
        if self.reraise:
            raise Preempted(signum, self.final_step)

    def _chain_previous(self, signum, frame) -> None:
        """Invoke the handler that was installed for ``signum`` before
        this hook, when it is a real (non-default) handler."""
        prev = self._previous.get(signum)
        if callable(prev) and prev not in (signal.SIG_DFL, signal.SIG_IGN):
            prev(signum, frame)

    # ------------------------------------------------------------------
    @staticmethod
    def simulate(pid: Optional[int] = None,
                 signum: int = signal.SIGTERM) -> None:
        """Deliver the preemption signal to this process (tests/drills)."""
        os.kill(pid if pid is not None else os.getpid(), signum)
