"""CLI: offline fleet-side verification of checkpoint trees.

::

    python -m deeplearning4j_tpu.checkpoint scrub ckpts/           # report
    python -m deeplearning4j_tpu.checkpoint scrub ckpts/ --quarantine
    python -m deeplearning4j_tpu.checkpoint scrub ckpts/ --json

Exit codes (the analyze-CLI convention): 0 every committed step dir is
intact, 1 rot found (listed; with ``--quarantine`` also moved aside to
``step_N.rotten`` with a typed ROTTEN.json record), 2 usage/load
failure. Pure file IO — safe to run from a cron job against a live
training job's checkpoint tree (quarantine renames are atomic).
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.checkpoint",
        description="offline checkpoint-tree integrity verification "
                    "(docs/fault_tolerance.md \"Non-raising failures\")")
    sub = ap.add_subparsers(dest="cmd")
    scrub = sub.add_parser(
        "scrub", help="re-hash every committed step dir against its "
                      "sha256 manifest")
    scrub.add_argument("directory", help="checkpoint tree "
                                         "(CheckpointManager directory)")
    scrub.add_argument("--quarantine", action="store_true",
                       help="move rotten steps aside to step_N.rotten "
                            "with a typed ROTTEN.json record")
    scrub.add_argument("--json", action="store_true",
                       help="emit the {'type': 'integrity'} scrub "
                            "record as JSON")
    scrub.add_argument("--max-mb-per-s", type=float, default=None,
                       help="bound the re-hash read rate (default: "
                            "unthrottled — this is the offline path)")
    args = ap.parse_args(argv)
    if args.cmd != "scrub":
        ap.print_usage(sys.stderr)
        print("error: a subcommand is required (scrub)", file=sys.stderr)
        return 2

    from deeplearning4j_tpu.checkpoint.scrub import Scrubber
    scrubber = Scrubber(args.directory, quarantine=args.quarantine,
                        max_mb_per_s=args.max_mb_per_s)
    try:
        report = scrubber.scrub_once()
    except FileNotFoundError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(report))
    else:
        print(f"scrubbed {report['scanned']} step dir(s), "
              f"{report['bytes'] / 2**20:.1f} MiB re-hashed in "
              f"{report['seconds']:.2f}s: "
              f"{report['rotten']} rotten")
        for ev in scrubber.events:
            if ev.get("event") in ("checkpoint_rotten",
                                   "checkpoint_quarantined"):
                dest = ev.get("quarantined_to")
                print(f"  step {ev['step']}: {'; '.join(ev['problems'])}"
                      + (f" -> {dest}" if dest else ""))
    return 1 if report["rotten"] else 0


if __name__ == "__main__":
    sys.exit(main())
