"""CheckpointManager — asynchronous, atomic, sharded training checkpoints.

Orbax-style manager over a local/NFS directory::

    mgr = CheckpointManager(dir, keep_last_n=3)
    state = capture_training_state(net, epoch=e)     # device→host copy
    mgr.save(step, state, metrics={"loss": l})       # returns immediately
    ...
    mgr.wait_until_finished()                        # surfaces writer errors
    restored = mgr.restore_latest(model=net)         # skips torn dirs

Commit protocol (per step N):

1. stage everything under ``step_N.tmp/`` (payload files fsynced);
2. [multihost] barrier — every process's shard is durable;
3. process 0 writes ``MANIFEST.json`` (per-file size + sha256), then the
   ``COMMIT`` marker, fsyncs both;
4. ``os.replace(step_N.tmp, step_N)`` + directory fsync — the atomic
   publish. A crash at ANY earlier point leaves only a ``.tmp``
   directory (or a final dir failing verification), which restore skips
   and ``gc_uncommitted()`` removes.

The async writer serializes/hashes/fsyncs on a background thread, so
``fit()`` stalls only for ``capture_training_state``'s device→host copy.
Writer errors are sticky: they re-raise on the next ``save()`` or
``wait_until_finished()`` — a checkpointing job must not silently stop
checkpointing.

Reference parity: optimize/listeners/CheckpointListener kept last-N zips
written in-line on the training thread with no atomicity; this manager
is the production replacement ROADMAP's elastic-training line builds on.
"""
from __future__ import annotations

import json
import os
import queue
import re
import shutil
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.checkpoint import manifest as _manifest
from deeplearning4j_tpu.checkpoint.atomic import fsync_dir
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer
from deeplearning4j_tpu.checkpoint.state import (
    TrainingState, capture_training_state, read_state_files,
    restore_training_state, write_state_files)

_STEP_RE = re.compile(r"^step_(\d+)$")
# .tmp = staging dir from a killed writer; .old = a committed dir swapped
# aside during a re-save whose cleanup was interrupted
_TMP_RE = re.compile(r"^step_(\d+)\.(tmp|old)$")


class CheckpointError(RuntimeError):
    """An asynchronous checkpoint write failed (raised on the training
    thread at the next save()/wait_until_finished())."""


class TopologyChangedError(CheckpointError):
    """The topology at restore differs from the checkpoint's manifest:
    the job lost or gained hosts/devices since the snapshot committed
    (preemption, elastic rescale). Structured and RETRYABLE —
    ``faults.FaultTolerantFit`` routes it through the resharded restore
    path (``checkpoint.reshard.restore_resharded``), which reassembles
    global arrays from any committed shard set and re-slices them for
    the current mesh."""

    def __init__(self, message: str, *, step: Optional[int] = None,
                 manifest: Optional[Dict[str, Any]] = None,
                 runtime: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.step = step
        self.manifest = dict(manifest or {})
        self.runtime = dict(runtime or {})


class ShardCountMismatchError(TopologyChangedError):
    """A committed checkpoint was written by ``manifest_count``
    processes but this runtime has ``runtime_count`` — the exact
    condition a preempted host leaves behind. Raised INSTEAD of the
    bare missing-shard-file failure a naive reader would hit, so the
    recovery rail can key on it."""

    def __init__(self, step: int, manifest_count: int, runtime_count: int,
                 detail: str = ""):
        self.manifest_count = int(manifest_count)
        self.runtime_count = int(runtime_count)
        super().__init__(
            f"checkpoint step {step} was committed by "
            f"{manifest_count} process(es) but this runtime has "
            f"{runtime_count}{': ' + detail if detail else ''} — the "
            f"topology changed since the save; restore through "
            f"checkpoint.reshard.restore_resharded() (or "
            f"faults.FaultTolerantFit, which does so automatically)",
            step=int(step),
            manifest={"process_count": int(manifest_count)},
            runtime={"process_count": int(runtime_count)})


class CheckpointManager:
    """Atomic, retained, optionally-async checkpoint directory manager.

    Retention (applied after every commit, pinned steps always kept):
    - ``keep_last_n``          — newest N checkpoints survive;
    - ``keep_every_n_epochs``  — checkpoints whose epoch is a multiple
      of N are kept permanently (the sparse long-horizon trail);
    - ``pin_best_metric``      — the checkpoint with the best
      ``metrics[name]`` (``pin_best_mode`` 'min'/'max') is kept.

    Multihost: pass ``process_index``/``process_count`` (default: the
    jax runtime's) and each process writes a disjoint array shard into
    the shared staging dir; ``barrier`` (default:
    parallel.multihost.sync_global_devices) runs before process 0
    commits the manifest, so a checkpoint can never commit with a
    missing shard.
    """

    def __init__(self, directory, keep_last_n: Optional[int] = 3,
                 keep_every_n_epochs: Optional[int] = None,
                 pin_best_metric: Optional[str] = None,
                 pin_best_mode: str = "min",
                 async_write: bool = True,
                 stats_storage=None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 barrier: Optional[Callable[[str], None]] = None,
                 verify_memo_ttl_s: float = 300.0):
        self.directory = os.fspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.keep_last_n = keep_last_n
        self.keep_every_n_epochs = keep_every_n_epochs
        self.pin_best_metric = pin_best_metric
        if pin_best_mode not in ("min", "max"):
            raise ValueError(f"pin_best_mode must be 'min'/'max', "
                             f"got {pin_best_mode!r}")
        self.pin_best_mode = pin_best_mode
        self.async_write = async_write
        self.stats_storage = stats_storage
        if process_index is None or process_count is None:
            try:
                import jax
                process_index = jax.process_index() if process_index is None \
                    else process_index
                process_count = jax.process_count() if process_count is None \
                    else process_count
            except Exception:       # pragma: no cover - jax not initialized
                process_index, process_count = 0, 1
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        if barrier is None and self.process_count > 1:
            from deeplearning4j_tpu.parallel.multihost import \
                sync_global_devices
            barrier = sync_global_devices
        self._barrier = barrier
        self._pinned: set = set()
        # verified-(path → (dir_token, verified_at)) memo: restore/
        # rollback paths full-rehash every candidate dir; repeated
        # rollbacks in one recovery loop must not re-hash unchanged
        # committed files on the critical path (the datapipe/reader.py
        # pattern). The token (per-file mtime_ns + size) invalidates on
        # any filesystem change; because MEDIA rot bypasses the
        # filesystem entirely (no mtime update), entries also expire
        # after ``verify_memo_ttl_s`` — the recovery loop's
        # seconds-apart rollbacks stay memoized while the blind window
        # against in-place decay stays bounded. The background Scrubber
        # re-hashes regardless and refreshes the memo.
        self._verify_memo_ttl_s = float(verify_memo_ttl_s)
        self._verified_memo: Dict[str, tuple] = {}
        if self.process_index == 0:
            self._recover_aside()     # crash-interrupted re-save repair
        self._q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._inflight = 0
        # REENTRANT locks: a SIGTERM preemption handler runs on the main
        # thread between bytecodes and may re-enter save()/_commit while
        # that same thread is inside a blocking commit — a plain Lock
        # would deadlock exactly when the final checkpoint matters most
        self._cv = threading.Condition(threading.RLock())
        self._commit_lock = threading.RLock()  # blocking vs async commits
        self._closed = False

    # ------------------------------------------------------------------
    # paths / listing
    def step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{int(step):08d}")

    def _tmp_dir(self, step: int) -> str:
        return self.step_dir(step) + ".tmp"

    def _verify_full(self, d: str) -> List[str]:
        """Memoized full verification of one step dir: an unchanged
        ``dir_token`` (every file's mtime_ns + size) since the last
        clean full verify within ``verify_memo_ttl_s`` skips the
        re-hash; any change, any problem, or an expired entry drops
        the memo and re-hashes."""
        token = _manifest.dir_token(d)
        ent = self._verified_memo.get(d)
        if token is not None and ent is not None and ent[0] == token \
                and time.monotonic() - ent[1] <= self._verify_memo_ttl_s:
            return []
        problems = _manifest.verify_dir(d, full=True)
        if problems or token is None:
            self._verified_memo.pop(d, None)
        else:
            self._verified_memo[d] = (token, time.monotonic())
        return problems

    def note_verified(self, d: str) -> None:
        """Record an externally-performed clean full verification
        (the background ``checkpoint.Scrubber`` re-hashes on its own
        cadence and feeds the restore-path memo through this)."""
        token = _manifest.dir_token(d)
        if token is not None:
            self._verified_memo[d] = (token, time.monotonic())

    def all_steps(self, verify: bool = False) -> List[int]:
        """Committed step numbers, ascending. ``verify=True`` re-hashes
        every file (memoized per unchanged dir); default checks
        marker/manifest/sizes only."""
        steps = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if not m:
                continue
            d = os.path.join(self.directory, name)
            ok = not self._verify_full(d) if verify \
                else _manifest.is_committed(d, full=False)
            if ok:
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def _recover_aside(self) -> None:
        """Repair a crash between the two re-save renames: ``step_N`` is
        gone but ``step_N.old`` (the previously committed checkpoint) or
        a fully staged ``step_N.tmp`` still verifies — rename it back
        instead of letting gc treat committed data as garbage."""
        for name in sorted(os.listdir(self.directory)):
            m = _TMP_RE.match(name)
            if not m:
                continue
            final = self.step_dir(int(m.group(1)))
            if os.path.isdir(final):
                continue               # step exists; leftover is garbage
            d = os.path.join(self.directory, name)
            if not self._verify_full(d):
                os.replace(d, final)
                self._verified_memo.pop(d, None)   # moved; token stale
                fsync_dir(self.directory)

    def uncommitted_dirs(self) -> List[str]:
        """Torn/stale directories: ``.tmp`` staging leftovers and final
        dirs that fail full verification (recoverable aside dirs from an
        interrupted re-save are first renamed back into place)."""
        if self.process_index == 0:
            self._recover_aside()
        bad = []
        for name in sorted(os.listdir(self.directory)):
            d = os.path.join(self.directory, name)
            if _TMP_RE.match(name):
                bad.append(d)
            elif _STEP_RE.match(name) and self._verify_full(d):
                bad.append(d)
        return bad

    def gc_uncommitted(self) -> List[str]:
        """Delete torn/uncommitted directories (crash leftovers)."""
        removed = []
        for d in self.uncommitted_dirs():
            shutil.rmtree(d, ignore_errors=True)
            removed.append(d)
        return removed

    # ------------------------------------------------------------------
    # save
    def save(self, step: int, state: Optional[TrainingState] = None,
             model=None, epoch: int = 0,
             metrics: Optional[Dict[str, float]] = None,
             normalizer=None, blocking: bool = False,
             pin: bool = False,
             lock_timeout: Optional[float] = None) -> None:
        """Checkpoint ``step``. Either pass a pre-captured ``state`` or a
        ``model``/SameDiff to capture from (the device→host copy happens
        here, on the caller's thread — the rest is async unless
        ``blocking``/``async_write=False``). Raises any pending writer
        error before starting new work. ``lock_timeout`` bounds how long
        a blocking save waits for an in-flight commit (preemption path)."""
        self.check_error()
        if self._closed:
            raise CheckpointError("CheckpointManager is closed")
        if state is None:
            if model is None:
                raise ValueError("save() needs state= or model=")
            # the only part of an async save the training thread stalls
            # for: the device→host copy of the full training state —
            # a blocking device boundary, so the stall watchdog
            # (integrity/watchdog.py) guards it
            from deeplearning4j_tpu.integrity.watchdog import \
                guard as _wd_guard
            with _tracer.span("checkpoint.capture", cat="checkpoint",
                              step=int(step)), \
                    _wd_guard("checkpoint_capture"):
                state = capture_training_state(model, epoch=epoch,
                                               normalizer=normalizer)
        if metrics:
            state.metadata.setdefault("metrics", {}).update(
                {k: float(v) for k, v in metrics.items()})
        if pin:
            self._pinned.add(int(step))
        enq_t = time.perf_counter()
        if blocking or not self.async_write:
            self._commit(int(step), state, enq_t, was_async=False,
                         lock_timeout=lock_timeout)
            return
        with self._cv:
            self._inflight += 1
        self._ensure_worker()
        self._q.put((int(step), state, enq_t))

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._drain, daemon=True,
                                            name="checkpoint-writer")
            self._worker.start()

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            step, state, enq_t = item
            try:
                self._commit(step, state, enq_t, was_async=True)
            except BaseException as e:   # sticky: surfaces on next save()
                self._error = e
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _commit(self, step: int, state: TrainingState, enq_t: float,
                was_async: bool,
                lock_timeout: Optional[float] = None) -> None:
        # bounded acquire so a preemption-handler's final save cannot
        # hang past the grace window behind a wedged writer thread
        if not self._commit_lock.acquire(
                timeout=-1 if lock_timeout is None else lock_timeout):
            raise CheckpointError(
                f"commit lock not acquired within {lock_timeout}s — "
                f"another commit is stuck")
        try:
            t0 = time.perf_counter()
            commit_span = _tracer.span(
                "checkpoint.commit", cat="checkpoint", step=int(step),
                asynchronous=bool(was_async),
                queue_s=round(max(0.0, t0 - enq_t), 6))
            commit_span.__enter__()
            tmp = self._tmp_dir(step)
            final = self.step_dir(step)
            if self.process_index == 0:
                if os.path.isdir(tmp):
                    shutil.rmtree(tmp)         # crash leftover
                os.makedirs(tmp)
            if self._barrier is not None:
                # staging dir prepared by process 0 before anyone writes
                # a shard into it (otherwise the cleanup could race a
                # fast peer's shard write)
                self._barrier(f"checkpoint_step_{step}_staged")
            os.makedirs(tmp, exist_ok=True)
            with _tracer.span("checkpoint.serialize", cat="checkpoint",
                              step=int(step)):
                write_state_files(tmp, state,
                                  shard_index=self.process_index,
                                  shard_count=self.process_count)
            t_serialize = time.perf_counter() - t0
            if self._barrier is not None:
                # every process's shard is durable before the commit
                self._barrier(f"checkpoint_step_{step}")
            if self.process_index == 0:
                _manifest.write_manifest(tmp)
                _manifest.write_commit_marker(tmp)
                fsync_dir(tmp)
                # re-save of an existing step: the committed dir stays
                # intact until the replacement is FULLY staged — it is
                # swapped aside only across the two renames (microsecond
                # window) rather than deleted before serialization
                aside = None
                if os.path.isdir(final):
                    aside = final + ".old"
                    if os.path.isdir(aside):
                        shutil.rmtree(aside)
                    os.replace(final, aside)
                os.replace(tmp, final)
                fsync_dir(self.directory)
                if aside is not None:
                    shutil.rmtree(aside, ignore_errors=True)
                self._apply_retention()
            if self._barrier is not None:
                # no process proceeds until the commit is visible to all
                self._barrier(f"checkpoint_step_{step}_committed")
            t_total = time.perf_counter() - t0
            if self.stats_storage is not None and self.process_index == 0:
                self.stats_storage.put({
                    "type": "checkpoint", "step": int(step),
                    "epoch": int(state.epoch),
                    "iteration": int(state.iteration),
                    "bytes": int(state.nbytes()),
                    "serialize_seconds": t_serialize,
                    "commit_seconds": t_total,
                    "queue_seconds": max(0.0, t0 - enq_t),
                    "async": bool(was_async), "t": time.time()})
        finally:
            commit_span.__exit__(*sys.exc_info())
            self._commit_lock.release()

    # ------------------------------------------------------------------
    # completion / errors
    def wait_until_finished(self, timeout: Optional[float] = None) -> None:
        """Block until every queued save has committed; re-raise the
        first writer error if one occurred."""
        with self._cv:
            if not self._cv.wait_for(lambda: self._inflight == 0,
                                     timeout=timeout):
                raise CheckpointError(
                    f"{self._inflight} checkpoint write(s) still pending "
                    f"after {timeout}s")
        self.check_error()

    def check_error(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise CheckpointError(
                f"asynchronous checkpoint write failed: {err}") from err

    def close(self) -> None:
        """Drain pending writes and stop the writer thread."""
        if self._closed:
            return
        try:
            self.wait_until_finished()
        finally:
            self._closed = True
            if self._worker is not None and self._worker.is_alive():
                self._q.put(None)
                self._worker.join(timeout=10)

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    # restore
    def _check_shard_topology(self, step: int) -> None:
        """Raise :class:`ShardCountMismatchError` when the committed
        step's shard layout does not match this runtime's process count
        (the recorded ``shard_count`` is authoritative — it is what the
        save-time manager actually wrote)."""
        meta = self._step_meta(step)
        manifest_count = int(meta.get("shard_count", 1))
        if manifest_count != self.process_count:
            raise ShardCountMismatchError(step, manifest_count,
                                          self.process_count)

    @staticmethod
    def _verify_stamp(state: TrainingState, step: int):
        """Re-verify the fingerprint stamp of a read state
        (integrity/fingerprint.py): unstamped states pass (pre-
        integrity checkpoints), a mismatching stamp raises a typed
        ``SilentCorruptionError`` — the payload changed since capture
        in a way the sha256 manifest did not witness (e.g. manifest and
        payload both rewritten)."""
        from deeplearning4j_tpu.integrity.fingerprint import \
            verify_state_stamp
        verify_state_stamp(state, where=f"restore step {step}")

    def restore(self, step: int, model=None, strict: bool = True,
                allow_reshard: bool = False) -> TrainingState:
        """Load (and verify) step ``step``; optionally restore into
        ``model``. Raises CheckpointError if the step is missing or
        fails integrity verification, SilentCorruptionError if its
        fingerprint stamp no longer matches the payload, and
        ShardCountMismatchError when the step was committed by a
        different process count than this runtime has
        (``allow_reshard=True`` bypasses the check and merges every
        shard regardless — the reshard path). Full re-hashing is
        memoized per unchanged directory (``_verify_full``), so
        repeated rollbacks in one recovery loop pay it once."""
        d = self.step_dir(step)
        problems = self._verify_full(d)
        if problems:
            raise CheckpointError(
                f"checkpoint step {step} at {d} is not committed/intact: "
                f"{problems}")
        if not allow_reshard:
            self._check_shard_topology(step)
        try:
            state = read_state_files(d)
        except FileNotFoundError as e:
            # counts already matched (or the caller bypassed the check)
            # — a file gone AFTER verification is loss/corruption (e.g.
            # retention racing this read), not a topology change
            raise CheckpointError(
                f"checkpoint step {step} lost files after verification "
                f"({e})") from e
        self._verify_stamp(state, step)
        if model is not None:
            restore_training_state(model, state, strict=strict)
        return state

    def latest_verified_step(self) -> Optional[int]:
        """The newest committed step whose fingerprint stamp
        re-verifies (None when no stamped-and-verified step exists) —
        the rollback target ``FaultTolerantFit`` prefers after a
        :class:`SilentCorruptionError`."""
        from deeplearning4j_tpu.integrity.fingerprint import \
            verify_state_stamp
        for step in sorted(self.all_steps(), reverse=True):
            d = self.step_dir(step)
            if self._verify_full(d):
                continue
            try:
                state = read_state_files(d)
                if verify_state_stamp(state, where="scan"):
                    return step
            except Exception:       # noqa: BLE001 — scan, not restore
                continue
        return None

    def restore_latest(self, model=None, strict: bool = True,
                       allow_reshard: bool = False,
                       verified_only: bool = False
                       ) -> Optional[Tuple[int, TrainingState]]:
        """Restore the newest COMMITTED checkpoint, skipping torn,
        uncommitted, or corrupted directories (missing COMMIT, bad
        manifest, truncated/bit-flipped payloads). Returns
        ``(step, state)`` or None when nothing restorable exists.
        Full re-hashing is memoized per unchanged directory, so a
        recovery loop's repeated rollbacks re-hash only what changed.

        A committed checkpoint whose shard count differs from this
        runtime's process count raises a structured
        :class:`ShardCountMismatchError` (manifest vs runtime counts)
        instead of crashing on a missing shard file — the signal
        ``faults.FaultTolerantFit`` keys elastic recovery on.
        ``allow_reshard=True`` merges all shards regardless of writer
        count (``checkpoint.reshard.restore_resharded`` is the blessed
        cross-topology restore built on the same contract).

        A fingerprint-stamped state whose stamp no longer matches its
        payload raises ``SilentCorruptionError``; with
        ``verified_only=True`` it is SKIPPED instead — along with
        unstamped states while any older verified one exists — so the
        walk lands on the newest checkpoint that provably holds the
        bytes the device computed (rollback-to-verified,
        docs/fault_tolerance.md). Falls back to the newest intact
        unstamped state when nothing verifies."""
        if self.process_index == 0:
            self._recover_aside()
        candidates = []
        for name in os.listdir(self.directory):
            m = _STEP_RE.match(name)
            if m:
                candidates.append(int(m.group(1)))
        fallback: Optional[Tuple[int, TrainingState]] = None
        for step in sorted(candidates, reverse=True):
            d = self.step_dir(step)
            if self._verify_full(d):
                continue                       # torn/corrupt: skip
            if not allow_reshard:
                self._check_shard_topology(step)
            try:
                state = read_state_files(d)
            except FileNotFoundError as e:
                # counts matched or check was bypassed: loss/corruption
                # after verification, not a topology change
                raise CheckpointError(
                    f"checkpoint step {step} lost files after "
                    f"verification ({e})") from e
            if verified_only:
                from deeplearning4j_tpu.integrity.fingerprint import \
                    verify_state_stamp
                try:
                    ok = verify_state_stamp(state,
                                            where=f"restore step {step}")
                except Exception:   # mismatching stamp: keep walking
                    continue
                if ok is None:      # unstamped: fallback candidate
                    if fallback is None:
                        fallback = (step, state)
                    continue
            else:
                self._verify_stamp(state, step)
            if model is not None:
                restore_training_state(model, state, strict=strict)
            return step, state
        if fallback is not None:
            step, state = fallback
            if model is not None:
                restore_training_state(model, state, strict=strict)
            return step, state
        return None

    # ------------------------------------------------------------------
    # retention
    def pin(self, step: int) -> None:
        """Exempt ``step`` from retention permanently."""
        self._pinned.add(int(step))

    def unpin(self, step: int) -> None:
        """Remove a pin; the step ages out through normal retention."""
        self._pinned.discard(int(step))

    def _step_meta(self, step: int) -> Dict[str, Any]:
        try:
            with open(os.path.join(self.step_dir(step), "state.json"),
                      encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return {}

    def _apply_retention(self) -> None:
        steps = self.all_steps()
        if not steps:
            return
        keep = set(self._pinned)
        metas = {s: self._step_meta(s) for s in steps}
        if self.keep_every_n_epochs:
            n = int(self.keep_every_n_epochs)
            keep.update(s for s, m in metas.items()
                        if int(m.get("epoch", 0)) % n == 0)
        if self.pin_best_metric:
            scored = [(s, m.get("metadata", {}).get("metrics", {})
                       .get(self.pin_best_metric))
                      for s, m in metas.items()]
            scored = [(s, v) for s, v in scored if v is not None]
            if scored:
                pick = min if self.pin_best_mode == "min" else max
                keep.add(pick(scored, key=lambda sv: sv[1])[0])
        if self.keep_last_n is not None:
            rest = [s for s in steps if s not in keep]
            keep.update(rest[-int(self.keep_last_n):])
        else:
            keep.update(steps)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.step_dir(s), ignore_errors=True)

    def best_step(self) -> Optional[int]:
        """The committed step with the best pinned metric (or None)."""
        if not self.pin_best_metric:
            return None
        scored = [(s, self._step_meta(s).get("metadata", {})
                   .get("metrics", {}).get(self.pin_best_metric))
                  for s in self.all_steps()]
        scored = [(s, v) for s, v in scored if v is not None]
        if not scored:
            return None
        pick = min if self.pin_best_mode == "min" else max
        return pick(scored, key=lambda sv: sv[1])[0]
