"""Proactive checkpoint scrubbing: find bit-rot BEFORE the rollback.

``restore_latest`` already skips a checkpoint that fails verification —
but discovering rot at restore time means discovering it at the worst
possible moment: mid-recovery, with the run down and the rollback
clock ticking. The :class:`Scrubber` moves that discovery to idle
time: a rate-limited background thread re-hashes every committed step
directory against its sha256 manifest on a cadence and QUARANTINES
rotten steps aside:

- ``step_N`` → ``step_N.rotten`` (an ``os.replace`` rename — atomic,
  and the name no longer matches the step pattern, so
  ``restore_latest``/retention/gc never touch it again; the bytes stay
  on disk for forensics);
- a typed ``ROTTEN.json`` record (step, problems, epoch, discovery
  time) is written inside the quarantined dir;
- ``{"type": "integrity", "event": "checkpoint_quarantined"}`` (and a
  per-cycle ``"scrub"`` summary) is published to the stats storage —
  ``MetricsRegistry.fold_integrity`` turns them into
  ``dl4j_integrity_*`` series.

Retention-aware: quarantine happens through the rename above, never a
delete — a pinned or keep-every-N step that rots is preserved aside
with its record, and the retention window naturally slides to the
surviving steps (``all_steps`` no longer sees the rotten name).

Clean verifications feed the manager's restore-path memo
(``CheckpointManager.note_verified``), so a later rollback skips the
re-hash the scrubber already paid.

Offline fleet-side variant: ``python -m deeplearning4j_tpu.checkpoint
scrub <dir>`` (exit 0 clean / 1 rot / 2 usage — the analyze-CLI
convention). See docs/fault_tolerance.md "Non-raising failures".
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.checkpoint.atomic import fsync_dir
from deeplearning4j_tpu.checkpoint.manifest import dir_token, verify_dir

_STEP_RE = re.compile(r"^step_(\d+)$")
ROTTEN_RECORD = "ROTTEN.json"
ROTTEN_SUFFIX = ".rotten"


def _dir_bytes(d: str) -> int:
    total = 0
    try:
        for name in os.listdir(d):
            p = os.path.join(d, name)
            if os.path.isfile(p):
                total += os.path.getsize(p)
    except OSError:
        pass
    return total


def scan_tree(directory: str) -> List[dict]:
    """One verification pass over every committed-looking step dir
    under ``directory``: ``[{step, path, bytes, problems}, ...]``
    (``problems`` empty = intact). Shared by the Scrubber and the
    offline CLI."""
    out = []
    try:
        names = sorted(os.listdir(directory))
    except OSError as e:
        raise FileNotFoundError(
            f"checkpoint tree {directory!r} unreadable: {e}") from e
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        d = os.path.join(directory, name)
        if not os.path.isdir(d):
            continue
        out.append({"step": int(m.group(1)), "path": d,
                    "bytes": _dir_bytes(d),
                    "problems": verify_dir(d, full=True)})
    return out


class Scrubber:
    """Rate-limited background checkpoint scrubber (module docstring).

    ::

        scrub = Scrubber(manager, interval_s=300, max_mb_per_s=64,
                         storage=storage)
        with scrub:                   # start() / stop()
            ftf.fit(it, epochs=50)
        scrub.last_report             # the final cycle's summary

    Accepts a :class:`~deeplearning4j_tpu.checkpoint.manager.
    CheckpointManager` (shares its directory and feeds its restore-path
    verification memo) or a bare directory path. ``max_mb_per_s``
    bounds the re-hash read rate so scrubbing never competes with the
    training job's own IO; ``quarantine=False`` reports rot without
    moving it (the CLI's default).
    """

    def __init__(self, manager_or_dir, interval_s: float = 300.0,
                 max_mb_per_s: Optional[float] = 64.0,
                 storage=None, quarantine: bool = True,
                 sleep: Callable[[float], None] = time.sleep):
        if hasattr(manager_or_dir, "directory"):
            self.manager = manager_or_dir
            self.directory = manager_or_dir.directory
        else:
            self.manager = None
            self.directory = os.fspath(manager_or_dir)
        self.interval_s = float(interval_s)
        self.max_mb_per_s = max_mb_per_s
        self.storage = storage
        self.quarantine = bool(quarantine)
        self._sleep = sleep
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.cycles = 0
        self.quarantined: List[int] = []
        self.last_report: Optional[dict] = None
        self.events: List[dict] = []

    # -- one pass -------------------------------------------------------
    def scrub_once(self) -> dict:
        """Verify every committed step dir once; quarantine rot. The
        memoized-clean fast path is deliberately NOT used — re-hashing
        unchanged bytes is the scrubber's entire job (rot does not
        update mtimes); clean results feed the memo instead."""
        t0 = time.perf_counter()
        scanned = rotten = 0
        hashed_bytes = 0
        quarantined: List[int] = []
        for ent in scan_tree(self.directory):
            if self._stop.is_set():
                break
            scanned += 1
            hashed_bytes += ent["bytes"]
            if ent["problems"]:
                rotten += 1
                q = self._quarantine(ent) if self.quarantine else None
                if q is not None:
                    quarantined.append(ent["step"])
                self._publish({
                    "type": "integrity",
                    "event": "checkpoint_quarantined" if q is not None
                    else "checkpoint_rotten",
                    "t": time.time(), "step": ent["step"],
                    "problems": ent["problems"][:8],
                    "quarantined_to": q})
            elif self.manager is not None:
                # a clean full re-hash is exactly what the restore
                # memo wants: feed it so the next rollback skips this
                self.manager.note_verified(ent["path"])
            self._throttle(t0, hashed_bytes)
        report = {"type": "integrity", "event": "scrub",
                  "t": time.time(), "scanned": scanned,
                  "rotten": rotten, "quarantined": quarantined,
                  "bytes": hashed_bytes,
                  "seconds": round(time.perf_counter() - t0, 6)}
        self.cycles += 1
        self.quarantined.extend(quarantined)
        self.last_report = report
        self._publish(report)
        return report

    def _throttle(self, t0: float, total: int) -> None:
        """Keep the cumulative hash rate under ``max_mb_per_s`` by
        sleeping off any surplus after each directory."""
        if not self.max_mb_per_s:
            return
        budget_s = total / (self.max_mb_per_s * 1e6)
        surplus = budget_s - (time.perf_counter() - t0)
        if surplus > 0:
            self._sleep(surplus)

    def _quarantine(self, ent: dict) -> Optional[str]:
        """``step_N`` → ``step_N.rotten`` + typed ROTTEN.json record.
        Atomic rename: a concurrent restore either still sees the
        committed name (and its own verification rejects it) or no
        step at all — never a half-moved dir. A step that rots AGAIN
        after a re-save quarantines to ``step_N.rotten.2`` (.3, ...):
        the first incident's forensics stay on disk untouched."""
        src = ent["path"]
        dst = src + ROTTEN_SUFFIX
        k = 2
        while os.path.isdir(dst):       # rot found twice: keep first
            dst = f"{src}{ROTTEN_SUFFIX}.{k}"
            k += 1
        try:
            os.replace(src, dst)
            fsync_dir(self.directory)
        except OSError:
            # racing a re-save/retention of the same step: the next
            # cycle re-examines whatever won
            return None
        # the rename IS the quarantine; the record write is best-effort
        # (a full disk must not misreport an already-moved dir)
        if self.manager is not None:
            self.manager._verified_memo.pop(src, None)
        try:
            with open(os.path.join(dst, ROTTEN_RECORD), "w",
                      encoding="utf-8") as fh:
                json.dump({"step": ent["step"],
                           "problems": ent["problems"],
                           "bytes": ent["bytes"],
                           "quarantined_t": time.time()}, fh, indent=1)
        except OSError:
            pass
        return dst

    # -- background lifecycle -------------------------------------------
    def start(self) -> "Scrubber":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="checkpoint-scrubber",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.scrub_once()
            except FileNotFoundError:
                pass                   # tree vanished; retry next cycle
            self._stop.wait(self.interval_s)

    def __enter__(self) -> "Scrubber":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _publish(self, rec: dict) -> None:
        self.events.append(rec)
        if self.storage is not None:
            self.storage.put(rec)


__all__ = ["ROTTEN_RECORD", "ROTTEN_SUFFIX", "Scrubber", "scan_tree"]
