"""Checkpoint-directory integrity: per-file sha256 manifest + COMMIT marker.

A checkpoint step directory is COMMITTED iff:

1. it is named ``step_<N>`` (no ``.tmp`` suffix — the writer builds the
   whole directory under ``step_<N>.tmp`` and ``os.replace``-renames it);
2. it contains ``MANIFEST.json`` listing every payload file with its
   size and sha256;
3. it contains the ``COMMIT`` marker (written after the manifest, fsynced
   before the rename);
4. every manifest entry verifies: the file exists, has the recorded
   size, and (in full verification) hashes to the recorded digest.

Anything else — a ``.tmp`` directory from a killed writer, a truncated
payload, a corrupted/absent manifest, a missing COMMIT — is an
UNCOMMITTED checkpoint: ``restore_latest()`` skips it and the manager
garbage-collects it. This is the Orbax commit protocol mapped onto a
local/NFS filesystem.
"""
from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional

MANIFEST_NAME = "MANIFEST.json"
COMMIT_NAME = "COMMIT"


def sha256_file(path, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(chunk), b""):
            h.update(block)
    return h.hexdigest()


def build_manifest(directory: str) -> Dict[str, dict]:
    """Hash every payload file in ``directory`` (manifest/marker
    excluded; one level — checkpoints are flat)."""
    entries: Dict[str, dict] = {}
    for name in sorted(os.listdir(directory)):
        if name in (MANIFEST_NAME, COMMIT_NAME):
            continue
        p = os.path.join(directory, name)
        if not os.path.isfile(p):
            continue
        entries[name] = {"size": os.path.getsize(p), "sha256": sha256_file(p)}
    return entries


def write_manifest(directory: str, entries: Optional[Dict[str, dict]] = None
                   ) -> Dict[str, dict]:
    if entries is None:
        entries = build_manifest(directory)
    data = json.dumps({"files": entries}, indent=1, sort_keys=True)
    path = os.path.join(directory, MANIFEST_NAME)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    return entries


def write_commit_marker(directory: str) -> None:
    path = os.path.join(directory, COMMIT_NAME)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("committed\n")
        fh.flush()
        os.fsync(fh.fileno())


def verify_dir(directory: str, full: bool = True) -> List[str]:
    """Return the list of integrity problems (empty = committed & intact).

    ``full=False`` checks structure + sizes only (cheap scan);
    ``full=True`` additionally re-hashes every payload file.
    """
    problems: List[str] = []
    if not os.path.isdir(directory):
        return [f"{directory}: not a directory"]
    if not os.path.isfile(os.path.join(directory, COMMIT_NAME)):
        problems.append("missing COMMIT marker")
    mpath = os.path.join(directory, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        problems.append("missing MANIFEST.json")
        return problems
    try:
        with open(mpath, "r", encoding="utf-8") as fh:
            manifest = json.load(fh)
        files = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        problems.append(f"unreadable manifest: {e}")
        return problems
    for name, ent in files.items():
        p = os.path.join(directory, name)
        if not os.path.isfile(p):
            problems.append(f"{name}: missing")
            continue
        if os.path.getsize(p) != ent["size"]:
            problems.append(f"{name}: size {os.path.getsize(p)} != "
                            f"{ent['size']}")
            continue
        if full and sha256_file(p) != ent["sha256"]:
            problems.append(f"{name}: sha256 mismatch")
    return problems


def is_committed(directory: str, full: bool = True) -> bool:
    return not verify_dir(directory, full=full)


def dir_token(directory: str):
    """Cheap change token for a whole step directory: the sorted
    ``(name, mtime_ns, size)`` tuple of every file in it (one level —
    checkpoints are flat). Two equal tokens mean the files have not
    changed since the last full verification, so a repeat restore can
    skip the re-hash (the ``datapipe/reader.py`` verified-memo pattern
    lifted to directories). Returns None when the directory is
    unreadable — never memoize that."""
    try:
        entries = []
        for name in sorted(os.listdir(directory)):
            p = os.path.join(directory, name)
            try:
                st = os.stat(p)
            except OSError:
                return None
            if os.path.isfile(p):
                entries.append((name, st.st_mtime_ns, st.st_size))
        return tuple(entries)
    except OSError:
        return None
