"""Complete training-state snapshot for bit-exact resume.

What "complete" means here (everything the compiled train step threads
between iterations, plus the host-side counters that derive its inputs):

- ``arrays``         — trainable params AND non-trainable state vars
  (BN running stats) as host numpy copies;
- ``updater_leaves`` — the optimizer state pytree, flattened (the
  treedef is rebuilt from a fresh ``updater.init`` template at restore,
  the same idiom autodiff/serde uses);
- ``iteration`` / ``epoch`` — the counters;
- ``rng_seed``       — the base-key seed of the *current* training run.
  ``SameDiff.fit`` derives every step's dropout/noise key as
  ``fold_in(key(seed), absolute_iteration)``, so restoring this seed and
  the iteration counter makes the resumed run consume exactly the key
  sequence the uninterrupted run would have — randomness is bit-exact,
  not just statistics;
- ``normalizer``     — fitted data-normalizer statistics, so the resumed
  process preprocesses identically without refitting.

``capture_training_state`` is the ONLY synchronous cost the async
checkpoint path puts on ``fit()``: a device→host copy of the arrays.
Serialization, hashing and fsync happen on the manager's writer thread.
"""
from __future__ import annotations

import dataclasses
import io
import json
import os
from typing import Any, Dict, List, Optional

import numpy as np

STATE_JSON = "state.json"
ARRAYS_NPZ = "arrays.npz"          # single-process / shard template below
ARRAYS_SHARD = "arrays.shard{i:05d}-of-{n:05d}.npz"
UPDATER_NPZ = "updater.npz"
NORMALIZER_NPZ = "normalizer.npz"
FORMAT_VERSION = 1


def _as_sd(model_or_sd):
    return getattr(model_or_sd, "samediff", model_or_sd)


@dataclasses.dataclass
class TrainingState:
    """Host-memory snapshot of everything needed to resume bit-exactly."""
    arrays: Dict[str, np.ndarray]
    updater_leaves: Optional[List[np.ndarray]] = None
    iteration: int = 0
    epoch: int = 0
    rng_seed: Optional[int] = None
    normalizer_state: Optional[Dict[str, np.ndarray]] = None
    metadata: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def nbytes(self) -> int:
        total = sum(a.nbytes for a in self.arrays.values())
        total += sum(l.nbytes for l in (self.updater_leaves or []))
        total += sum(np.asarray(v).nbytes
                     for v in (self.normalizer_state or {}).values())
        return total

    def make_normalizer(self):
        """Rebuild the fitted Normalizer object (or None)."""
        if not self.normalizer_state:
            return None
        from deeplearning4j_tpu.dataset import normalizers as nz
        cls_name = str(np.asarray(self.normalizer_state["__class__"]))
        cls = {c.__name__: c for c in
               [nz.NormalizerStandardize, nz.NormalizerMinMaxScaler,
                nz.ImagePreProcessingScaler]}[cls_name]
        obj = cls.__new__(cls)
        obj._load_state(self.normalizer_state)
        return obj


def capture_topology(model_or_sd) -> Dict[str, Any]:
    """The mesh topology a snapshot was captured under — recorded into
    ``TrainingState.metadata["topology"]`` so a restore can tell whether
    the world changed shape since the save (checkpoint/reshard.py):

    - ``process_count`` / ``device_count``: the runtime's extent;
    - ``mesh_axes``: ``{axis: size}`` of the NamedSharding mesh the
      live arrays are committed to (None when single-device);
    - ``partition_specs``: per-array PartitionSpec entries for every
      mesh-resident array (how each GLOBAL array was sliced);
    - ``global_shapes``: per-array global shapes (what a resharded
      restore must reassemble to, whatever the new mesh looks like).
    """
    import jax
    from jax.sharding import NamedSharding
    sd = _as_sd(model_or_sd)
    mesh_axes = None
    specs: Dict[str, list] = {}
    shapes: Dict[str, list] = {}
    for n, a in {**sd.trainable_params(), **sd.state_vars_map()}.items():
        shapes[n] = [int(s) for s in np.shape(a)]
        sh = getattr(a, "sharding", None)
        if isinstance(sh, NamedSharding):
            if mesh_axes is None:
                mesh_axes = {str(k): int(v) for k, v in sh.mesh.shape.items()}
            specs[n] = [list(e) if isinstance(e, tuple) else e
                        for e in sh.spec]
    try:
        pc, dc = int(jax.process_count()), int(jax.device_count())
    except Exception:          # pragma: no cover - jax not initialized
        pc, dc = 1, 1
    return {"process_count": pc, "device_count": dc,
            "mesh_axes": mesh_axes, "partition_specs": specs,
            "global_shapes": shapes}


def capture_training_state(model_or_sd, epoch: int = 0, normalizer=None,
                           metadata: Optional[Dict[str, Any]] = None
                           ) -> TrainingState:
    """Snapshot a SameDiff (or network wrapping one) to host memory.

    This is the device→host copy — the only blocking step of an async
    save. Arrays are materialized with ``np.asarray`` so later training
    steps (which DONATE device buffers) cannot alias the snapshot.
    Sharded arrays gather to their GLOBAL value here, and the mesh
    topology they were sliced under is recorded in
    ``metadata["topology"]`` — the manifest half of the elastic-resume
    contract (save on N hosts, restore on M; docs/elastic_training.md).
    """
    import jax
    sd = _as_sd(model_or_sd)
    arrays = {n: np.asarray(a) for n, a in
              {**sd.trainable_params(), **sd.state_vars_map()}.items()}
    updater_leaves = None
    if sd._updater_state is not None:
        updater_leaves = [np.asarray(l) for l in
                          jax.tree_util.tree_leaves(sd._updater_state)]
    # tagged D2H accounting: the capture's device→host copy bytes land
    # in the AllocationsTracker (thread-safe — capture may run on the
    # training thread while the writer drains) and surface in
    # {"type": "memory"} records (docs/observability.md)
    from deeplearning4j_tpu.memory import AllocationsTracker
    d2h = sum(a.nbytes for a in arrays.values()) \
        + sum(l.nbytes for l in (updater_leaves or []))
    AllocationsTracker.get_instance().allocate("checkpoint_d2h", d2h)
    tc = sd.training_config
    iteration = int(getattr(tc, "iteration_count", 0)) if tc else 0
    # the base seed of the run in flight (recorded by fit); falling back
    # to the next-fit seed keeps pre-fit checkpoints restorable
    rng_seed = getattr(sd, "_fit_base_seed", None)
    if rng_seed is None:
        rng_seed = int(getattr(sd, "_seed", 0))
    norm_state = None
    if normalizer is not None:
        norm_state = {"__class__": np.asarray(type(normalizer).__name__),
                      **{k: np.asarray(v)
                         for k, v in normalizer._state().items()}}
    meta = dict(metadata or {})
    meta.setdefault("topology", capture_topology(sd))
    # bitwise fingerprint stamp (integrity/fingerprint.py): with
    # TrainingConfig.fingerprints armed, digest the captured HOST bytes
    # and — when the fit left a device digest for this exact boundary —
    # compare the two. A mismatch means the state corrupted between the
    # device computing it and this capture reading it (a bad D2H copy,
    # host memory rot): raise typed BEFORE the damage is committed.
    # The stamp rides the snapshot so restore re-verifies it.
    if tc is not None and getattr(tc, "fingerprints", False) \
            and "integrity" not in meta:
        from deeplearning4j_tpu.integrity.fingerprint import (ALGO,
                                                              np_fingerprint)
        host_fp = np_fingerprint(
            list(arrays.values()) + list(updater_leaves or []))
        dev = getattr(sd, "_device_fingerprint", None)
        dev_fp = None
        verified = None
        if dev is not None and int(dev.get("iteration", -1)) == iteration:
            dev_fp = int(dev["fp"])
            verified = dev_fp == host_fp
            if not verified:
                from deeplearning4j_tpu.faults.errors import \
                    SilentCorruptionError
                raise SilentCorruptionError(
                    f"checkpoint capture at iteration {iteration}: host "
                    f"bytes hash to {host_fp:#010x} but the device "
                    f"computed {dev_fp:#010x} at the same boundary — "
                    f"the state corrupted between the dispatch and this "
                    f"capture (device→host copy or host memory); "
                    f"refusing to commit a poisoned checkpoint",
                    check="capture", expected=dev_fp, actual=host_fp,
                    step=int(iteration), epoch=int(epoch))
        meta["integrity"] = {"algo": ALGO, "fingerprint": int(host_fp),
                             "device_fingerprint": dev_fp,
                             "verified": verified}
    # seekable streaming-pipeline position (datapipe/): fit() registers
    # the active pipeline on the graph; its PipelineState at THIS
    # iteration (shard cursor, shuffle pass, quarantine sets) rides the
    # snapshot so a restore can seek mid-epoch instead of replaying the
    # pass (docs/data_pipeline.md)
    dp = getattr(sd, "_active_datapipe", None)
    if dp is not None and "datapipe" not in meta:
        meta["datapipe"] = dp.export_state(iteration)
    return TrainingState(arrays=arrays, updater_leaves=updater_leaves,
                         iteration=iteration, epoch=int(epoch),
                         rng_seed=int(rng_seed),
                         normalizer_state=norm_state,
                         metadata=meta)


def restore_training_state(model_or_sd, state: TrainingState,
                           strict: bool = True):
    """Pour a snapshot back into a live (initialized) model/SameDiff.

    strict: raise if the snapshot does not cover every live parameter —
    a renamed/added layer must not silently resume from fresh init.
    Returns the rebuilt Normalizer (or None).
    """
    import jax
    import jax.numpy as jnp
    sd = _as_sd(model_or_sd)
    live = set(sd.trainable_params()) | set(sd._state_var_names)
    missing = sorted(live - set(state.arrays))
    if strict and missing:
        raise ValueError(
            f"checkpoint does not cover live parameters "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''} — the graph "
            f"changed since the snapshot; pass strict=False to restore "
            f"the matching subset")
    for n, arr in state.arrays.items():
        if n not in sd._arrays:
            continue
        if tuple(sd._arrays[n].shape) != tuple(arr.shape):
            if strict:
                raise ValueError(
                    f"checkpoint array {n!r} has shape {tuple(arr.shape)} "
                    f"but the live graph expects "
                    f"{tuple(sd._arrays[n].shape)}")
            continue       # non-strict: same-name different-layer, skip
        sd._arrays[n] = jnp.asarray(arr)
    if state.updater_leaves is not None and sd.training_config is not None:
        template = sd.training_config.updater.init(sd.trainable_params())
        t_leaves, treedef = jax.tree_util.tree_flatten(template)
        compatible = (len(t_leaves) == len(state.updater_leaves) and all(
            tuple(np.shape(t)) == tuple(np.shape(s))
            for t, s in zip(t_leaves, state.updater_leaves)))
        if compatible:
            sd._updater_state = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(l) for l in state.updater_leaves])
        elif strict:
            raise ValueError(
                "checkpoint updater state does not match the live "
                "graph's optimizer structure")
    tc = sd.training_config
    if tc is not None:
        tc.iteration_count = int(state.iteration)
        tc.epoch_count = int(state.epoch)
    if state.rng_seed is not None:
        # next fit() reuses this base key; per-step keys fold in the
        # absolute iteration, so the continuation replays the exact key
        # sequence of an uninterrupted run
        sd._seed = int(state.rng_seed)
        sd._fit_base_seed = int(state.rng_seed)
    # a restored state invalidates any device digest a previous fit
    # left behind (integrity/fingerprint.py): the next fit re-arms it
    sd._device_fingerprint = None
    if hasattr(model_or_sd, "_sync_infer"):
        model_or_sd._sync_infer()
    return state.make_normalizer()


# ---------------------------------------------------------------------------
# directory (de)serialization — called on the manager's writer thread

def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def shard_names(state: TrainingState, shard_index: int, shard_count: int
                ) -> List[str]:
    """Deterministic partition of array names across processes: sorted
    names round-robined over shards, so every process writes a disjoint
    subset and the union is total."""
    names = sorted(state.arrays)
    return [n for i, n in enumerate(names) if i % shard_count == shard_index]


def write_state_files(directory: str, state: TrainingState,
                      shard_index: int = 0, shard_count: int = 1) -> None:
    """Write this process's portion of the snapshot into ``directory``
    (the step's ``.tmp`` staging dir). Every process writes its array
    shard; process 0 also writes counters/updater/normalizer. Files are
    fsynced here; manifest/COMMIT/rename are the caller's commit step."""
    shard = {n: state.arrays[n]
             for n in shard_names(state, shard_index, shard_count)}
    fname = ARRAYS_NPZ if shard_count == 1 else \
        ARRAYS_SHARD.format(i=shard_index, n=shard_count)
    _write_durable(os.path.join(directory, fname), _npz_bytes(shard))
    if shard_index != 0:
        return
    if state.updater_leaves is not None:
        _write_durable(
            os.path.join(directory, UPDATER_NPZ),
            _npz_bytes({f"leaf_{i}": l
                        for i, l in enumerate(state.updater_leaves)}))
    if state.normalizer_state:
        _write_durable(os.path.join(directory, NORMALIZER_NPZ),
                       _npz_bytes(state.normalizer_state))
    meta = {"format_version": FORMAT_VERSION,
            "iteration": int(state.iteration),
            "epoch": int(state.epoch),
            "rng_seed": state.rng_seed,
            "shard_count": int(shard_count),
            "has_updater": state.updater_leaves is not None,
            "has_normalizer": bool(state.normalizer_state),
            "metadata": state.metadata}
    _write_durable(os.path.join(directory, STATE_JSON),
                   json.dumps(meta, indent=1, sort_keys=True).encode())


def _write_durable(path: str, data: bytes) -> None:
    with open(path, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())


def read_state_files(directory: str) -> TrainingState:
    """Load a committed step directory back into a TrainingState (merges
    all array shards)."""
    with open(os.path.join(directory, STATE_JSON), encoding="utf-8") as fh:
        meta = json.load(fh)
    shard_count = int(meta.get("shard_count", 1))
    arrays: Dict[str, np.ndarray] = {}
    if shard_count == 1:
        paths = [os.path.join(directory, ARRAYS_NPZ)]
    else:
        paths = [os.path.join(directory,
                              ARRAYS_SHARD.format(i=i, n=shard_count))
                 for i in range(shard_count)]
    for p in paths:
        with np.load(p) as npz:
            for k in npz.files:
                arrays[k] = npz[k]
    updater_leaves = None
    if meta.get("has_updater"):
        with np.load(os.path.join(directory, UPDATER_NPZ)) as npz:
            updater_leaves = [npz[f"leaf_{i}"] for i in range(len(npz.files))]
    norm_state = None
    if meta.get("has_normalizer"):
        with np.load(os.path.join(directory, NORMALIZER_NPZ)) as npz:
            norm_state = {k: npz[k] for k in npz.files}
    return TrainingState(arrays=arrays, updater_leaves=updater_leaves,
                         iteration=int(meta.get("iteration", 0)),
                         epoch=int(meta.get("epoch", 0)),
                         rng_seed=meta.get("rng_seed"),
                         normalizer_state=norm_state,
                         metadata=dict(meta.get("metadata", {})))
