"""Elastic resharded restore: resume a checkpoint on a DIFFERENT topology.

The commit protocol (manager.py) guarantees a committed step holds a
complete, integrity-verified shard set — written by however many
processes the job had THEN. This module is the restore path for a job
that comes back with a different shape (a host preempted away, a slice
grown back, a sharding strategy changed):

1. **reassemble** — merge every committed shard into the full global
   array set (shard layout is name-partitioned, ``state.shard_names``,
   so the union is total regardless of how many processes wrote it);
2. **restore** — pour the global state into the live model (params,
   updater leaves, iteration/epoch, RNG base seed — the same bit-exact
   contract as a same-topology restore);
3. **re-slice** — commit the arrays to the CURRENT mesh via the target
   ``ShardingStrategy`` (the trainer's, or one built from the model's
   declarative ``TrainingConfig.sharding`` spec), so the next step runs
   sharded on the surviving topology.

What is and is not bit-exact across a topology change is documented in
docs/elastic_training.md: the restored GLOBAL state is bit-exact; the
continued trajectory matches an uninterrupted run up to collective
reduction order on the new mesh (bit-exact when the topology is in fact
unchanged).

Every reshard is observable: a ``checkpoint.reshard`` span, a
``{"type": "reshard"}`` stats record (arrays resliced, bytes gathered,
wall time, from/to topology) folded to ``dl4j_reshard_*`` metrics by
``monitor.MetricsRegistry.fold_reshard`` and rendered by ``ui/report``.

Reference parity: none — the reference's elastic story was "restart the
whole job from a zip on the same cluster shape" (SURVEY §5). This is
the scaling-book model: topology change is a recoverable event.
"""
from __future__ import annotations

import sys
import time
from typing import Optional, Tuple

from deeplearning4j_tpu.checkpoint import manifest as _manifest
from deeplearning4j_tpu.checkpoint.manager import (CheckpointError,
                                                   CheckpointManager,
                                                   ShardCountMismatchError,
                                                   TopologyChangedError)
from deeplearning4j_tpu.checkpoint.state import (TrainingState,
                                                 read_state_files,
                                                 restore_training_state)
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer


def _as_sd(model_or_sd):
    return getattr(model_or_sd, "samediff", model_or_sd)


def _split_trainer(model):
    """(restore_target, trainer_or_None) — accepts a ParallelTrainer-
    like wrapper (has ``.model`` + ``.shard_params``) or a bare
    model/SameDiff."""
    if hasattr(model, "shard_params") and hasattr(model, "model"):
        return model.model, model
    return model, None


def _resolve_target_strategy(model, strategy):
    """The sharding the restored state should be re-sliced into:
    an explicit ``strategy=``, else a ParallelTrainer's, else one built
    from the model's declarative ``TrainingConfig.sharding`` spec, else
    None (host-resident restore — still a valid shrink-to-one)."""
    if strategy is not None:
        return strategy
    if model is None:
        return None
    trainer_strategy = getattr(model, "strategy", None)
    if trainer_strategy is not None:
        return trainer_strategy
    sd = _as_sd(model)
    spec = getattr(getattr(sd, "training_config", None), "sharding", None)
    if spec is not None:
        from deeplearning4j_tpu.parallel.trainer import resolve_strategy
        return resolve_strategy(sd, spec)
    return None


def restore_resharded(manager: CheckpointManager, model=None,
                      strategy=None, step: Optional[int] = None,
                      strict: bool = True, stats_storage=None
                      ) -> Optional[Tuple[int, TrainingState]]:
    """Restore a committed checkpoint across a topology change.

    Reads the newest committed step (or ``step=``) REGARDLESS of how
    many processes wrote it, reassembles the global arrays, restores
    them into ``model``, and re-slices everything for the current mesh
    (see module docstring). Returns ``(step, state)`` or None when no
    committed checkpoint exists; the reshard summary is left in
    ``state.metadata["reshard_info"]`` and published as a
    ``{"type": "reshard"}`` record to ``stats_storage``.
    """
    if step is None:
        # like restore_latest: salvage any fully-staged .tmp left by a
        # crash between re-save renames, then walk committed steps
        # newest-first skipping torn/corrupted dirs — a bit-flipped
        # newest step must not kill a recovery that an older intact
        # checkpoint could serve
        if manager.process_index == 0:
            manager._recover_aside()
        for cand in reversed(manager.all_steps()):
            if not _manifest.verify_dir(manager.step_dir(cand), full=True):
                step = cand
                break
        if step is None:
            return None
        d = manager.step_dir(step)
    else:
        d = manager.step_dir(step)
        problems = _manifest.verify_dir(d, full=True)
        if problems:
            raise CheckpointError(
                f"checkpoint step {step} at {d} is not committed/intact: "
                f"{problems}")
    t0 = time.perf_counter()
    span = _tracer.span("checkpoint.reshard", cat="checkpoint",
                        step=int(step))
    span.__enter__()
    try:
        try:
            state = read_state_files(d)  # merges ALL shards, any count
        except FileNotFoundError as e:
            # retention racing this read: loss after verification, not
            # a topology change — keep it on the retryable
            # CheckpointError rail (same hardening as manager.restore)
            raise CheckpointError(
                f"checkpoint step {step} lost files after verification "
                f"({e})") from e
        from_topo = (state.metadata or {}).get("topology") or {}
        target = _resolve_target_strategy(model, strategy)
        if model is not None:
            target_model, trainer = _split_trainer(model)
            restore_training_state(target_model, state, strict=strict)
            if target is not None:
                from deeplearning4j_tpu.parallel.trainer import shard_model
                if trainer is not None:
                    trainer.strategy = target    # trainer adopts the mesh
                shard_model(_as_sd(target_model), target)
        to_mesh = ({str(k): int(v)
                    for k, v in target.mesh.mesh.shape.items()}
                   if target is not None else None)
        info = {
            "step": int(step),
            "arrays": len(state.arrays),
            "bytes": int(state.nbytes()),
            "seconds": round(time.perf_counter() - t0, 6),
            "from_shards": None,      # filled from state.json below
            "from_mesh": from_topo.get("mesh_axes"),
            "to_mesh": to_mesh,
            "from_processes": from_topo.get("process_count"),
            "to_processes": int(manager.process_count)}
        span.set(arrays=info["arrays"], bytes=info["bytes"])
    finally:
        span.__exit__(*sys.exc_info())
    # the shard count the step was actually written with
    meta = manager._step_meta(step)
    info["from_shards"] = (int(meta["shard_count"])
                           if "shard_count" in meta else None)
    state.metadata["reshard_info"] = info
    if stats_storage is not None:
        stats_storage.put({"type": "reshard", "t": time.time(), **info})
    return step, state


__all__ = ["ShardCountMismatchError", "TopologyChangedError",
           "restore_resharded"]
