"""BERT as a frozen TF GraphDef + its import path (BASELINE config 4).

Reference parity: the reference's BERT benchmark imports a frozen
google-research/bert .pb through samediff-import-tensorflow
(ImportGraph.kt:218). TensorFlow does not exist in this environment, so the
.pb artifact itself is generated here: ``build_bert_graphdef`` emits the
SAME node/op patterns a frozen BERT inference graph contains —
GatherV2 embeddings, StridedSlice position-embedding slice, Mean/
SquaredDifference/Rsqrt layer-norm pattern, erf-based gelu, per-head
Reshape/Transpose with BatchMatMulV2 attention, `(1-mask)*-10000` additive
attention bias — serialized through the real protobuf wire encoder
(tf_builder). The import path is therefore identical to importing a
TF-produced file: bytes → GraphDef decode → op-by-op mapping → SameDiff.

``bert_base()`` gives the imported, fine-tunable SameDiff graph.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from deeplearning4j_tpu.modelimport.tf_builder import GraphDefBuilder


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads


BERT_BASE = BertConfig()
BERT_TINY = BertConfig(vocab_size=128, hidden_size=32, num_layers=2,
                       num_heads=2, intermediate_size=64,
                       max_position_embeddings=64, type_vocab_size=2)


class _BertGraphBuilder:
    """Emits frozen-BERT GraphDef nodes (names follow the stock
    google-research/bert checkpoint scope layout)."""

    def __init__(self, cfg: BertConfig, batch: int, seq_len: int, seed: int):
        self.cfg = cfg
        self.b = GraphDefBuilder()
        self.batch = batch
        self.seq = seq_len
        self.rng = np.random.RandomState(seed)
        self._uid = 0

    # -- helpers -----------------------------------------------------------
    def _w(self, name: str, shape, stddev=None) -> str:
        std = self.cfg.initializer_range if stddev is None else stddev
        return self.b.const(
            name, (self.rng.randn(*shape) * std).astype(np.float32))

    def _zeros(self, name: str, shape) -> str:
        return self.b.const(name, np.zeros(shape, np.float32))

    def _ones(self, name: str, shape) -> str:
        return self.b.const(name, np.ones(shape, np.float32))

    def _c(self, value, dtype=np.int32) -> str:
        self._uid += 1
        return self.b.const(f"const_{self._uid}", np.asarray(value, dtype))

    def dense(self, scope: str, x2d: str, n_in: int, n_out: int) -> str:
        w = self._w(f"{scope}/kernel", (n_in, n_out))
        bias = self._zeros(f"{scope}/bias", (n_out,))
        mm = self.b.node("MatMul", f"{scope}/MatMul", x2d, w,
                         transpose_a=False, transpose_b=False)
        return self.b.node("BiasAdd", f"{scope}/BiasAdd", mm, bias)

    def layer_norm(self, scope: str, x: str, width: int) -> str:
        """The frozen-graph LN pattern: Mean / SquaredDifference / Rsqrt."""
        gamma = self._ones(f"{scope}/gamma", (width,))
        beta = self._zeros(f"{scope}/beta", (width,))
        axes = self._c([-1])
        mean = self.b.node("Mean", f"{scope}/moments/mean", x, axes,
                           keep_dims=True)
        sqd = self.b.node("SquaredDifference", f"{scope}/moments/sqdiff",
                          x, mean)
        var = self.b.node("Mean", f"{scope}/moments/variance", sqd, axes,
                          keep_dims=True)
        eps = self._c(self.cfg.layer_norm_eps, np.float32)
        veps = self.b.node("AddV2", f"{scope}/add_eps", var, eps)
        rstd = self.b.node("Rsqrt", f"{scope}/Rsqrt", veps)
        norm = self.b.node("Mul", f"{scope}/mul_norm",
                           self.b.node("Sub", f"{scope}/sub", x, mean), rstd)
        scaled = self.b.node("Mul", f"{scope}/mul_gamma", norm, gamma)
        return self.b.node("AddV2", f"{scope}/out", scaled, beta)

    def gelu(self, scope: str, x: str) -> str:
        """Erf-based gelu exactly as the BERT graph emits it."""
        sqrt2 = self._c(np.sqrt(2.0), np.float32)
        xd = self.b.node("RealDiv", f"{scope}/truediv", x, sqrt2)
        e = self.b.node("Erf", f"{scope}/Erf", xd)
        one = self._c(1.0, np.float32)
        e1 = self.b.node("AddV2", f"{scope}/add", e, one)
        half = self._c(0.5, np.float32)
        xh = self.b.node("Mul", f"{scope}/mul", x, half)
        return self.b.node("Mul", f"{scope}/mul_1", xh, e1)

    # -- model -------------------------------------------------------------
    def build(self) -> bytes:
        cfg, b = self.cfg, self.b
        B, S, H = self.batch, self.seq, cfg.hidden_size
        b.placeholder("input_ids", shape=[B, S], dtype=np.int32)
        b.placeholder("input_mask", shape=[B, S], dtype=np.int32)
        b.placeholder("token_type_ids", shape=[B, S], dtype=np.int32)

        # --- embeddings ---------------------------------------------------
        word_emb = self._w("bert/embeddings/word_embeddings",
                           (cfg.vocab_size, H))
        axis0 = self._c(0)
        emb = b.node("GatherV2", "bert/embeddings/gather",
                     word_emb, "input_ids", axis0)
        # token-type: OneHot @ table (the stock graph's pattern)
        tt_table = self._w("bert/embeddings/token_type_embeddings",
                           (cfg.type_vocab_size, H))
        depth = self._c(cfg.type_vocab_size)
        on = self._c(1.0, np.float32)
        off = self._c(0.0, np.float32)
        flat_tt = b.node("Reshape", "bert/embeddings/tt_flat",
                         "token_type_ids", self._c([B * S]))
        oh = b.node("OneHot", "bert/embeddings/one_hot",
                    flat_tt, depth, on, off)
        tt2 = b.node("MatMul", "bert/embeddings/tt_matmul", oh, tt_table,
                     transpose_a=False, transpose_b=False)
        tt = b.node("Reshape", "bert/embeddings/tt_emb", tt2,
                    self._c([B, S, H]))
        emb = b.node("AddV2", "bert/embeddings/add_tt", emb, tt)
        # positions: StridedSlice of the full table
        pos_table = self._w("bert/embeddings/position_embeddings",
                            (cfg.max_position_embeddings, H))
        pos = b.raw_node(
            "bert/embeddings/pos_slice", "StridedSlice",
            [pos_table, self._c([0, 0]), self._c([S, H]), self._c([1, 1])])
        emb = b.node("AddV2", "bert/embeddings/add_pos", emb, pos)
        x = self.layer_norm("bert/embeddings/LayerNorm", emb, H)

        # --- attention mask: (1 - mask) * -10000, [B,1,1,S] ---------------
        mask_f = b.node("Cast", "bert/encoder/mask_cast", "input_mask",
                        DstT=("dtype", 1))     # AttrValue.type, as TF writes it
        mask_r = b.node("Reshape", "bert/encoder/mask_reshape", mask_f,
                        self._c([B, 1, 1, S]))
        one = self._c(1.0, np.float32)
        inv = b.node("Sub", "bert/encoder/mask_inv", one, mask_r)
        neg = self._c(-10000.0, np.float32)
        adder = b.node("Mul", "bert/encoder/mask_adder", inv, neg)

        # --- encoder layers ----------------------------------------------
        A, D = cfg.num_heads, cfg.head_size
        x2 = b.node("Reshape", "bert/encoder/flatten_in", x,
                    self._c([B * S, H]))
        for i in range(cfg.num_layers):
            sc = f"bert/encoder/layer_{i}"
            q = self.dense(f"{sc}/attention/self/query", x2, H, H)
            k = self.dense(f"{sc}/attention/self/key", x2, H, H)
            v = self.dense(f"{sc}/attention/self/value", x2, H, H)

            def heads(name, t):
                r = b.node("Reshape", f"{name}/reshape", t,
                           self._c([B, S, A, D]))
                return b.node("Transpose", f"{name}/transpose", r,
                              self._c([0, 2, 1, 3]))

            qh = heads(f"{sc}/attention/self/q", q)
            kh = heads(f"{sc}/attention/self/k", k)
            vh = heads(f"{sc}/attention/self/v", v)
            scores = b.node("BatchMatMulV2", f"{sc}/attention/self/qk",
                            qh, kh, adj_x=False, adj_y=True)
            scale = self._c(1.0 / np.sqrt(D), np.float32)
            scores = b.node("Mul", f"{sc}/attention/self/scale",
                            scores, scale)
            scores = b.node("AddV2", f"{sc}/attention/self/mask",
                            scores, adder)
            probs = b.node("Softmax", f"{sc}/attention/self/Softmax", scores)
            ctx = b.node("BatchMatMulV2", f"{sc}/attention/self/ctx",
                         probs, vh, adj_x=False, adj_y=False)
            ctx = b.node("Transpose", f"{sc}/attention/self/ctx_t", ctx,
                         self._c([0, 2, 1, 3]))
            ctx2 = b.node("Reshape", f"{sc}/attention/self/ctx_flat", ctx,
                          self._c([B * S, H]))
            attn_out = self.dense(f"{sc}/attention/output/dense", ctx2, H, H)
            attn_out = b.node("AddV2", f"{sc}/attention/output/add",
                              attn_out, x2)
            attn_out = self.layer_norm(f"{sc}/attention/output/LayerNorm",
                                       attn_out, H)
            inter = self.dense(f"{sc}/intermediate/dense", attn_out, H,
                               cfg.intermediate_size)
            inter = self.gelu(f"{sc}/intermediate/gelu", inter)
            lay_out = self.dense(f"{sc}/output/dense", inter,
                                 cfg.intermediate_size, H)
            lay_out = b.node("AddV2", f"{sc}/output/add", lay_out, attn_out)
            x2 = self.layer_norm(f"{sc}/output/LayerNorm", lay_out, H)

        seq_out = b.node("Reshape", "bert/encoder/sequence_output", x2,
                         self._c([B, S, H]))
        # --- pooler: first token -> dense tanh ----------------------------
        first = b.raw_node(
            "bert/pooler/first_token", "StridedSlice",
            [seq_out, self._c([0, 0, 0]), self._c([0, 1, 0]),
             self._c([1, 1, 1])],
            {"begin_mask": 5, "end_mask": 5, "shrink_axis_mask": 2})
        pooled = self.dense("bert/pooler/dense", first, H, H)
        b.node("Tanh", "bert/pooler/output", pooled)
        return b.build()


def build_bert_graphdef(cfg: BertConfig = BERT_BASE, batch: int = 8,
                        seq_len: int = 128, seed: int = 0) -> bytes:
    """Serialized frozen-BERT GraphDef bytes (the '.pb file')."""
    return _BertGraphBuilder(cfg, batch, seq_len, seed).build()


def bert_base(cfg: BertConfig = BERT_BASE, batch: int = 8, seq_len: int = 128,
              num_labels: Optional[int] = None, seed: int = 0):
    """Import a frozen BERT GraphDef into a fine-tunable SameDiff graph.

    With ``num_labels`` a classifier head + softmax-CE loss over the pooled
    output is appended (the BASELINE config 4 fine-tune step); label
    placeholder name: "labels" (one-hot [batch, num_labels]).
    Returns the SameDiff; outputs: "bert/encoder/sequence_output",
    "bert/pooler/output" (+ "loss" with a head).
    """
    from deeplearning4j_tpu.modelimport.tf_import import import_tf_graph
    pb = build_bert_graphdef(cfg, batch, seq_len, seed)
    sd = import_tf_graph(pb, trainable="auto")
    if num_labels is not None:
        rng = np.random.RandomState(seed + 1)
        pooled = sd.get_variable("bert/pooler/output")
        w = sd.var("classifier/kernel",
                   value=(rng.randn(cfg.hidden_size, num_labels)
                          * cfg.initializer_range).astype(np.float32))
        bias = sd.var("classifier/bias",
                      value=np.zeros(num_labels, np.float32))
        logits = sd.invoke("matmul", [pooled, w], name="classifier/logits")
        logits = sd.invoke("bias_add", [logits, bias],
                           name="classifier/logits_b")
        labels = sd.placeholder("labels", shape=(batch, num_labels))
        loss = sd.invoke("softmax_cross_entropy", [logits, labels],
                         name="loss")
        sd.set_loss_variables([loss])
    return sd
