"""Zoo model definitions.

Reference parity (architectures, not weights):
- LeNet        → zoo/model/LeNet.java:85-133
- SimpleCNN    → zoo/model/SimpleCNN.java
- AlexNet      → zoo/model/AlexNet.java
- VGG16        → zoo/model/VGG16.java
- ResNet50     → zoo/model/ResNet50.java:80-250 (identity/conv bottleneck
                 blocks, stages 2-5 = [3, 4, 6, 3])
- TextGenLSTM  → zoo/model/TextGenerationLSTM.java
- TransformerEncoder → NEW capability (BERT-class encoder; the reference
  reaches BERT only through TF import)

TPU-first deviations: batch norm everywhere the reference uses LRN-era
tricks is kept as the reference wrote it; convs run as fused XLA
convolutions in NCHW/HWIO; global average pooling replaces fixed-size
avg-pool+flatten heads so models accept any spatial input size.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.learning.updaters import Adam, IUpdater, Nesterovs
from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, ComputationGraph, ConvolutionLayer,
    DenseLayer, DropoutLayer, ElementWiseVertex, GlobalPoolingLayer,
    InputType, LSTMLayer, LocalResponseNormalization, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, RnnOutputLayer, SubsamplingLayer,
    ZeroPaddingLayer)


@dataclasses.dataclass
class LeNet:
    """LeNet-5-style CNN (reference: zoo/model/LeNet.java:85-133 — conv5x5
    x20 relu, maxpool2, conv5x5 x50 relu, maxpool2, dense 500, softmax)."""
    height: int = 28
    width: int = 28
    channels: int = 1
    num_classes: int = 10
    seed: int = 1234
    updater: IUpdater = None

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater or Adam(learning_rate=1e-3))
                .list()
                .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5),
                                        stride=(1, 1), activation="relu",
                                        convolution_mode="SAME"))
                .layer(SubsamplingLayer(pooling_type="MAX",
                                        kernel_size=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5),
                                        stride=(1, 1), activation="relu",
                                        convolution_mode="SAME"))
                .layer(SubsamplingLayer(pooling_type="MAX",
                                        kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=500, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss_function="MCXENT"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class SimpleCNN:
    """Compact CNN (reference: zoo/model/SimpleCNN.java — 4 conv blocks
    with BN, dropout head)."""
    height: int = 48
    width: int = 48
    channels: int = 3
    num_classes: int = 10
    seed: int = 1234
    updater: IUpdater = None

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater or Adam(learning_rate=1e-3))
             .list())
        for n_out in (16, 32, 64, 128):
            b = (b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                          activation="relu",
                                          convolution_mode="SAME"))
                 .layer(BatchNormalization())
                 .layer(SubsamplingLayer(pooling_type="MAX",
                                         kernel_size=(2, 2), stride=(2, 2))))
        return (b.layer(DropoutLayer(dropout=0.5))
                .layer(DenseLayer(n_out=256, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss_function="MCXENT"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class AlexNet:
    """AlexNet (reference: zoo/model/AlexNet.java — conv11/4, LRN, conv5,
    LRN, 3x conv3, dense 4096 x2 with dropout)."""
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater or Nesterovs(learning_rate=1e-2,
                                                   momentum=0.9))
                .list()
                .layer(ConvolutionLayer(n_out=96, kernel_size=(11, 11),
                                        stride=(4, 4),
                                        convolution_mode="VALID",
                                        activation="relu"))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="MAX",
                                        kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(5, 5),
                                        convolution_mode="SAME",
                                        activation="relu", bias_init=1.0))
                .layer(LocalResponseNormalization())
                .layer(SubsamplingLayer(pooling_type="MAX",
                                        kernel_size=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="SAME",
                                        activation="relu"))
                .layer(ConvolutionLayer(n_out=384, kernel_size=(3, 3),
                                        convolution_mode="SAME",
                                        activation="relu", bias_init=1.0))
                .layer(ConvolutionLayer(n_out=256, kernel_size=(3, 3),
                                        convolution_mode="SAME",
                                        activation="relu", bias_init=1.0))
                .layer(SubsamplingLayer(pooling_type="MAX",
                                        kernel_size=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(n_out=4096, activation="relu",
                                  dropout=0.5))
                .layer(DenseLayer(n_out=4096, activation="relu",
                                  dropout=0.5))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss_function="MCXENT"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class VGG16:
    """VGG-16 (reference: zoo/model/VGG16.java — 13 conv3x3 + 3 dense)."""
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(learning_rate=1e-2,
                                                momentum=0.9))
             .list())
        for n_out, reps in ((64, 2), (128, 2), (256, 3), (512, 3), (512, 3)):
            for _ in range(reps):
                b = b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                             convolution_mode="SAME",
                                             activation="relu"))
            b = b.layer(SubsamplingLayer(pooling_type="MAX",
                                         kernel_size=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss_function="MCXENT"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class ResNet50:
    """ResNet-50 v1 (reference: zoo/model/ResNet50.java:80-250).

    Stem: zero-pad 3, conv7x7/2, BN, relu, maxpool3x3/2; then bottleneck
    stages 2-5 with block counts [3, 4, 6, 3]; global average pool +
    softmax head. Built as a ComputationGraph with ElementWiseVertex(Add)
    residual shortcuts exactly like the reference's
    identityBlock/convBlock helpers.
    """
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 42
    updater: IUpdater = None

    # ----- block helpers (reference ResNet50.java:94-186) -------------
    def _identity_block(self, g, kernel, filters, stage, block, inp):
        f1, f2, f3 = filters
        n = f"res{stage}{block}"
        (g.add_layer(f"{n}_2a", ConvolutionLayer(
            n_out=f1, kernel_size=(1, 1), convolution_mode="VALID"), inp)
         .add_layer(f"{n}_bn2a", BatchNormalization(), f"{n}_2a")
         .add_layer(f"{n}_act2a", ActivationLayer(activation="relu"),
                    f"{n}_bn2a")
         .add_layer(f"{n}_2b", ConvolutionLayer(
             n_out=f2, kernel_size=kernel, convolution_mode="SAME"),
             f"{n}_act2a")
         .add_layer(f"{n}_bn2b", BatchNormalization(), f"{n}_2b")
         .add_layer(f"{n}_act2b", ActivationLayer(activation="relu"),
                    f"{n}_bn2b")
         .add_layer(f"{n}_2c", ConvolutionLayer(
             n_out=f3, kernel_size=(1, 1), convolution_mode="VALID"),
             f"{n}_act2b")
         .add_layer(f"{n}_bn2c", BatchNormalization(), f"{n}_2c")
         .add_vertex(f"{n}_add", ElementWiseVertex(op="Add"),
                     f"{n}_bn2c", inp)
         .add_layer(f"{n}_out", ActivationLayer(activation="relu"),
                    f"{n}_add"))
        return f"{n}_out"

    def _conv_block(self, g, kernel, filters, stage, block, inp,
                    stride=(2, 2)):
        f1, f2, f3 = filters
        n = f"res{stage}{block}"
        (g.add_layer(f"{n}_2a", ConvolutionLayer(
            n_out=f1, kernel_size=(1, 1), stride=stride,
            convolution_mode="VALID"), inp)
         .add_layer(f"{n}_bn2a", BatchNormalization(), f"{n}_2a")
         .add_layer(f"{n}_act2a", ActivationLayer(activation="relu"),
                    f"{n}_bn2a")
         .add_layer(f"{n}_2b", ConvolutionLayer(
             n_out=f2, kernel_size=kernel, convolution_mode="SAME"),
             f"{n}_act2a")
         .add_layer(f"{n}_bn2b", BatchNormalization(), f"{n}_2b")
         .add_layer(f"{n}_act2b", ActivationLayer(activation="relu"),
                    f"{n}_bn2b")
         .add_layer(f"{n}_2c", ConvolutionLayer(
             n_out=f3, kernel_size=(1, 1), convolution_mode="VALID"),
             f"{n}_act2b")
         .add_layer(f"{n}_bn2c", BatchNormalization(), f"{n}_2c")
         # projection shortcut
         .add_layer(f"{n}_1", ConvolutionLayer(
             n_out=f3, kernel_size=(1, 1), stride=stride,
             convolution_mode="VALID"), inp)
         .add_layer(f"{n}_bn1", BatchNormalization(), f"{n}_1")
         .add_vertex(f"{n}_add", ElementWiseVertex(op="Add"),
                     f"{n}_bn2c", f"{n}_bn1")
         .add_layer(f"{n}_out", ActivationLayer(activation="relu"),
                    f"{n}_add"))
        return f"{n}_out"

    def conf(self):
        g = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(learning_rate=1e-1,
                                                momentum=0.9))
             .graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        # stem (reference "stem-zero"/"stem-cnn1"/"stem-batch1"/maxpool)
        (g.add_layer("stem_zero", ZeroPaddingLayer(padding=(3, 3, 3, 3)),
                     "input")
         .add_layer("stem_conv", ConvolutionLayer(
             n_out=64, kernel_size=(7, 7), stride=(2, 2),
             convolution_mode="VALID"), "stem_zero")
         .add_layer("stem_bn", BatchNormalization(), "stem_conv")
         .add_layer("stem_act", ActivationLayer(activation="relu"),
                    "stem_bn")
         .add_layer("stem_pool", SubsamplingLayer(
             pooling_type="MAX", kernel_size=(3, 3), stride=(2, 2)),
             "stem_act"))
        cur = "stem_pool"
        # stage 2
        cur = self._conv_block(g, (3, 3), (64, 64, 256), 2, "a", cur,
                               stride=(1, 1))
        for blk in "bc":
            cur = self._identity_block(g, (3, 3), (64, 64, 256), 2, blk, cur)
        # stage 3
        cur = self._conv_block(g, (3, 3), (128, 128, 512), 3, "a", cur)
        for blk in "bcd":
            cur = self._identity_block(g, (3, 3), (128, 128, 512), 3, blk,
                                       cur)
        # stage 4
        cur = self._conv_block(g, (3, 3), (256, 256, 1024), 4, "a", cur)
        for blk in "bcdef":
            cur = self._identity_block(g, (3, 3), (256, 256, 1024), 4, blk,
                                       cur)
        # stage 5
        cur = self._conv_block(g, (3, 3), (512, 512, 2048), 5, "a", cur)
        for blk in "bc":
            cur = self._identity_block(g, (3, 3), (512, 512, 2048), 5, blk,
                                       cur)
        # head (reference: avgpool + flatten + OutputLayer; global avg pool
        # makes the head input-size independent)
        (g.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"), cur)
         .add_layer("output", OutputLayer(n_out=self.num_classes,
                                          loss_function="MCXENT"), "gap")
         .set_outputs("output"))
        return g.build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class TextGenLSTM:
    """Character-level text-generation LSTM (reference:
    zoo/model/TextGenerationLSTM.java — 2 stacked LSTMs + RNN softmax
    head)."""
    vocab_size: int = 77
    timesteps: int = 40
    units: int = 256
    seed: int = 12345
    updater: IUpdater = None

    def conf(self):
        return (NeuralNetConfiguration.builder()
                .seed(self.seed)
                .updater(self.updater or Adam(learning_rate=1e-3))
                .list()
                .layer(LSTMLayer(n_out=self.units))
                .layer(LSTMLayer(n_out=self.units))
                .layer(RnnOutputLayer(n_out=self.vocab_size,
                                      loss_function="MCXENT"))
                .set_input_type(InputType.recurrent(self.vocab_size,
                                                    self.timesteps))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class TransformerEncoder:
    """BERT-class transformer encoder for sequence classification (new
    capability; reference reaches BERT only via TF import —
    samediff-import). Token ids → embedding + learned positions → N
    pre-LN encoder blocks → mean-pool → softmax."""
    vocab_size: int = 30522
    max_len: int = 128
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    num_classes: int = 2
    drop_prob: float = 0.1
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        from deeplearning4j_tpu.nn.attention import (
            EmbeddingSequenceLayer, PositionalEmbeddingLayer,
            TransformerEncoderLayer)
        b = (NeuralNetConfiguration.builder()
             .seed(self.seed)
             .updater(self.updater or Adam(learning_rate=1e-4))
             .list()
             .layer(EmbeddingSequenceLayer(n_in=self.vocab_size,
                                           n_out=self.d_model))
             .layer(PositionalEmbeddingLayer(max_len=self.max_len)))
        for _ in range(self.n_layers):
            b = b.layer(TransformerEncoderLayer(
                n_heads=self.n_heads, d_ff=self.d_ff,
                drop_prob=self.drop_prob))
        return (b.layer(GlobalPoolingLayer(pooling_type="AVG"))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss_function="MCXENT"))
                .set_input_type(InputType.sequence_ids(self.max_len))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
