"""Zoo breadth wave 3: VGG19, InceptionResNetV1, FaceNet, NASNet, YOLO2.

Reference parity (architectures; pretrained weights come from the hub /
Keras import path):
- VGG19             → zoo/model/VGG19.java (16 conv3x3 + 3 dense)
- InceptionResNetV1 → zoo/model/InceptionResNetV1.java (stem +
  scaled-residual Inception blocks A/B/C with reductions)
- FaceNet           → zoo/model/FaceNetNN4Small2.java's role: an
  embedding network with L2-normalized output trained with center loss
  (the reference builds it on an inception trunk +
  CenterLossOutputLayer); here the trunk is InceptionResNetV1
- NASNet            → zoo/model/NASNet.java (NASNet-A normal/reduction
  cell stacks; cells here keep the sep-conv branch structure with the
  branch count reduced — each cell is sep3x3+sep5x5+avgpool branch sums
  concatenated — which preserves the scaling skeleton without the
  paper's 5-way genotype)
- YOLO2             → zoo/model/YOLO2.java (full Darknet-19 trunk +
  passthrough/reorg (space-to-depth) merge + Yolo2OutputLayer)

All sizes are constructor-parameterized so unit tests instantiate tiny
variants; defaults match the reference configs.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.learning.updaters import Adam, IUpdater, Nesterovs
from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, ComputationGraph, ConvolutionLayer,
    DenseLayer, DropoutLayer, ElementWiseVertex, GlobalPoolingLayer,
    InputType, L2NormalizeVertex, MergeVertex, MultiLayerNetwork,
    NeuralNetConfiguration, OutputLayer, ScaleVertex,
    SeparableConvolution2DLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.layers_ext import (
    CenterLossOutputLayer, SpaceToDepthLayer, Yolo2OutputLayer)


@dataclasses.dataclass
class VGG19:
    """(reference: zoo/model/VGG19.java — VGG16 with conv counts
    2,2,4,4,4)."""
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        b = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Nesterovs(learning_rate=1e-2,
                                                momentum=0.9)).list())
        for n_out, reps in ((64, 2), (128, 2), (256, 4), (512, 4),
                            (512, 4)):
            for _ in range(reps):
                b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(3, 3),
                                         convolution_mode="SAME",
                                         activation="relu"))
            b.layer(SubsamplingLayer(pooling_type="MAX", kernel_size=(2, 2),
                                     stride=(2, 2)))
        return (b.layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(DenseLayer(n_out=4096, activation="relu"))
                .layer(OutputLayer(n_out=self.num_classes,
                                   loss_function="MCXENT"))
                .set_input_type(InputType.convolutional(
                    self.height, self.width, self.channels))
                .build())

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


def _conv_bn(g, name, inp, n_out, kernel, stride=(1, 1), mode="SAME",
             act="relu"):
    g.add_layer(f"{name}_c", ConvolutionLayer(
        n_out=n_out, kernel_size=kernel, stride=stride,
        convolution_mode=mode, has_bias=False), inp)
    g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_c")
    g.add_layer(name, ActivationLayer(activation=act), f"{name}_bn")
    return name


@dataclasses.dataclass
class InceptionResNetV1:
    """Scaled-residual inception net (reference:
    zoo/model/InceptionResNetV1.java — stem, 5x block35, reduction-A,
    10x block17, reduction-B, 5x block8, avgpool, dropout, embedding).

    ``embedding_size > 0`` appends an L2-normalized embedding (the
    FaceNet configuration); otherwise a softmax head.
    """
    height: int = 160
    width: int = 160
    channels: int = 3
    num_classes: int = 1000
    blocks_a: int = 5
    blocks_b: int = 10
    blocks_c: int = 5
    embedding_size: int = 0
    center_loss: bool = False
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        # Stem (InceptionResNetV1.java: conv 3x3/2 .. conv 3x3/2 256)
        p = _conv_bn(g, "stem1", "input", 32, (3, 3), (2, 2))
        p = _conv_bn(g, "stem2", p, 32, (3, 3))
        p = _conv_bn(g, "stem3", p, 64, (3, 3))
        g.add_layer("stem_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), pooling_type="MAX",
            convolution_mode="SAME"), p)
        p = _conv_bn(g, "stem4", "stem_pool", 80, (1, 1))
        p = _conv_bn(g, "stem5", p, 192, (3, 3))
        p = _conv_bn(g, "stem6", p, 256, (3, 3), (2, 2))

        def resblock(name, inp, width, branches, scale):
            """Concat branches -> 1x1 linear conv to `width` -> scale ->
            residual add -> relu (the block35/17/8 pattern)."""
            outs = []
            for bi, chain in enumerate(branches):
                cur = inp
                for ci, (n_out, kernel) in enumerate(chain):
                    cur = _conv_bn(g, f"{name}_b{bi}_{ci}", cur, n_out,
                                   kernel)
                outs.append(cur)
            g.add_vertex(f"{name}_cat", MergeVertex(), *outs)
            g.add_layer(f"{name}_up", ConvolutionLayer(
                n_out=width, kernel_size=(1, 1), activation="identity"),
                f"{name}_cat")
            g.add_vertex(f"{name}_scale", ScaleVertex(scale_factor=scale),
                         f"{name}_up")
            g.add_vertex(f"{name}_add", ElementWiseVertex(op="Add"),
                         inp, f"{name}_scale")
            g.add_layer(name, ActivationLayer(activation="relu"),
                        f"{name}_add")
            return name

        for i in range(self.blocks_a):       # block35 x5, width 256
            p = resblock(f"a{i}", p, 256,
                         [[(32, (1, 1))],
                          [(32, (1, 1)), (32, (3, 3))],
                          [(32, (1, 1)), (32, (3, 3)), (32, (3, 3))]],
                         0.17)
        # Reduction-A: maxpool + conv3x3/2 384 + 1x1->3x3->3x3/2 256
        g.add_layer("redA_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), pooling_type="MAX",
            convolution_mode="SAME"), p)
        rA1 = _conv_bn(g, "redA_c1", p, 384, (3, 3), (2, 2))
        t = _conv_bn(g, "redA_c2a", p, 192, (1, 1))
        t = _conv_bn(g, "redA_c2b", t, 192, (3, 3))
        rA2 = _conv_bn(g, "redA_c2c", t, 256, (3, 3), (2, 2))
        g.add_vertex("redA", MergeVertex(), "redA_pool", rA1, rA2)
        p, width = "redA", 256 + 384 + 256

        for i in range(self.blocks_b):       # block17 x10
            p = resblock(f"b{i}", p, width,
                         [[(128, (1, 1))],
                          [(128, (1, 1)), (128, (1, 7)), (128, (7, 1))]],
                         0.10)
        # Reduction-B: maxpool + three conv chains
        g.add_layer("redB_pool", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2), pooling_type="MAX",
            convolution_mode="SAME"), p)
        t = _conv_bn(g, "redB_1a", p, 256, (1, 1))
        rB1 = _conv_bn(g, "redB_1b", t, 384, (3, 3), (2, 2))
        t = _conv_bn(g, "redB_2a", p, 256, (1, 1))
        rB2 = _conv_bn(g, "redB_2b", t, 256, (3, 3), (2, 2))
        t = _conv_bn(g, "redB_3a", p, 256, (1, 1))
        t = _conv_bn(g, "redB_3b", t, 256, (3, 3))
        rB3 = _conv_bn(g, "redB_3c", t, 256, (3, 3), (2, 2))
        g.add_vertex("redB", MergeVertex(), "redB_pool", rB1, rB2, rB3)
        p, width = "redB", width + 384 + 256 + 256

        for i in range(self.blocks_c):       # block8 x5
            p = resblock(f"c{i}", p, width,
                         [[(192, (1, 1))],
                          [(192, (1, 1)), (192, (1, 3)), (192, (3, 1))]],
                         0.20)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"), p)
        g.add_layer("drop", DropoutLayer(dropout=0.8), "gap")
        if self.embedding_size:
            g.add_layer("emb", DenseLayer(n_out=self.embedding_size,
                                          activation="identity"), "drop")
            g.add_vertex("embedding", L2NormalizeVertex(), "emb")
            if self.center_loss:
                g.add_layer("out", CenterLossOutputLayer(
                    n_out=self.num_classes), "embedding")
                return g.set_outputs("out").build()
            g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                           loss_function="MCXENT"),
                        "embedding")
            return g.set_outputs("out").build()
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       loss_function="MCXENT"), "drop")
        return g.set_outputs("out").build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class FaceNet:
    """Face-embedding net (reference: zoo/model/FaceNetNN4Small2.java —
    inception trunk, 128-d L2-normalized embedding, center loss). Train
    with class labels; use activations at 'embedding' for verification."""
    height: int = 160
    width: int = 160
    channels: int = 3
    num_classes: int = 1000
    embedding_size: int = 128
    blocks_a: int = 5
    blocks_b: int = 10
    blocks_c: int = 5
    seed: int = 42
    updater: IUpdater = None

    def build(self) -> ComputationGraph:
        return InceptionResNetV1(
            height=self.height, width=self.width, channels=self.channels,
            num_classes=self.num_classes, blocks_a=self.blocks_a,
            blocks_b=self.blocks_b, blocks_c=self.blocks_c,
            embedding_size=self.embedding_size, center_loss=True,
            seed=self.seed, updater=self.updater).build()


@dataclasses.dataclass
class NASNet:
    """NASNet-A-class cell-stacked net (reference: zoo/model/NASNet.java:
    stem -> (normal x N, reduction) x3 -> pool/softmax; `penultimate
    filters` scale like the reference's mobile=1056 config)."""
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    cells_per_stack: int = 4
    stem_filters: int = 32
    filters: int = 44            # mobile config: 1056 / 24 ≈ 44 per cell
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        p = _conv_bn(g, "stem", "input", self.stem_filters, (3, 3), (2, 2))

        def sep(name, inp, n_out, kernel, stride=(1, 1)):
            g.add_layer(f"{name}_s", SeparableConvolution2DLayer(
                n_out=n_out, kernel_size=kernel, stride=stride,
                convolution_mode="SAME"), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_s")
            g.add_layer(name, ActivationLayer(activation="relu"),
                        f"{name}_bn")
            return name

        def normal_cell(name, inp, f):
            # Branch sums then concat (NASNet-A normal cell skeleton).
            fit = _conv_bn(g, f"{name}_fit", inp, f, (1, 1))
            b1a = sep(f"{name}_b1a", fit, f, (3, 3))
            b1b = sep(f"{name}_b1b", fit, f, (5, 5))
            g.add_vertex(f"{name}_add1", ElementWiseVertex(op="Add"),
                         b1a, b1b)
            g.add_layer(f"{name}_pool", SubsamplingLayer(
                kernel_size=(3, 3), stride=(1, 1), pooling_type="AVG",
                convolution_mode="SAME"), fit)
            g.add_vertex(f"{name}_add2", ElementWiseVertex(op="Add"),
                         f"{name}_pool", fit)
            b3 = sep(f"{name}_b3", fit, f, (3, 3))
            g.add_vertex(name, MergeVertex(), f"{name}_add1",
                         f"{name}_add2", b3)
            return name, 3 * f

        def reduction_cell(name, inp, f):
            r1 = sep(f"{name}_r1", inp, f, (5, 5), (2, 2))
            r2 = sep(f"{name}_r2", inp, f, (7, 7), (2, 2))
            g.add_layer(f"{name}_pool", SubsamplingLayer(
                kernel_size=(3, 3), stride=(2, 2), pooling_type="MAX",
                convolution_mode="SAME"), inp)
            pfit = _conv_bn(g, f"{name}_pfit", f"{name}_pool", f, (1, 1))
            g.add_vertex(name, MergeVertex(), r1, r2, pfit)
            return name, 3 * f

        f = self.filters
        for stack in range(3):
            for i in range(self.cells_per_stack):
                p, _ = normal_cell(f"n{stack}_{i}", p, f)
            if stack < 2:
                p, _ = reduction_cell(f"r{stack}", p, f * 2)
                f *= 2
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"), p)
        g.add_layer("out", OutputLayer(n_out=self.num_classes,
                                       loss_function="MCXENT"), "gap")
        return g.set_outputs("out").build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class YOLO2:
    """Full YOLOv2 (reference: zoo/model/YOLO2.java — Darknet-19 trunk,
    passthrough route from the /16 feature map via space-to-depth (the
    'reorg' layer), concat, 3x3 conv, 1x1 detection conv,
    Yolo2OutputLayer)."""
    height: int = 416
    width: int = 416
    channels: int = 3
    num_classes: int = 20
    anchors: Tuple[float, ...] = (0.57273, 0.677385, 1.87446, 2.06253,
                                  3.33843, 5.47434, 7.88282, 3.52778,
                                  9.77052, 9.16828)
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        n_anchors = len(self.anchors) // 2
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def dconv(name, inp, n_out, k):
            g.add_layer(f"{name}_c", ConvolutionLayer(
                n_out=n_out, kernel_size=(k, k), convolution_mode="SAME",
                has_bias=False), inp)
            g.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_c")
            g.add_layer(name, ActivationLayer(activation="leaky_relu"),
                        f"{name}_bn")
            return name

        def pool(name, inp):
            g.add_layer(name, SubsamplingLayer(
                kernel_size=(2, 2), stride=(2, 2), pooling_type="MAX"), inp)
            return name

        # Darknet-19 trunk (Darknet19.java plan), tapping the /16 map.
        p = dconv("c1", "input", 32, 3)
        p = pool("p1", p)
        p = dconv("c2", p, 64, 3)
        p = pool("p2", p)
        p = dconv("c3", p, 128, 3)
        p = dconv("c4", p, 64, 1)
        p = dconv("c5", p, 128, 3)
        p = pool("p3", p)
        p = dconv("c6", p, 256, 3)
        p = dconv("c7", p, 128, 1)
        p = dconv("c8", p, 256, 3)
        p = pool("p4", p)
        p = dconv("c9", p, 512, 3)
        p = dconv("c10", p, 256, 1)
        p = dconv("c11", p, 512, 3)
        p = dconv("c12", p, 256, 1)
        passthrough = dconv("c13", p, 512, 3)    # /16 feature map
        p = pool("p5", passthrough)
        p = dconv("c14", p, 1024, 3)
        p = dconv("c15", p, 512, 1)
        p = dconv("c16", p, 1024, 3)
        p = dconv("c17", p, 512, 1)
        p = dconv("c18", p, 1024, 3)
        # Detection head (YOLO2.java): two 3x3 1024 convs; passthrough
        # route = 1x1 64 conv + reorg(2) concatenated before the last conv.
        p = dconv("h1", p, 1024, 3)
        p = dconv("h2", p, 1024, 3)
        r = dconv("route", passthrough, 64, 1)
        g.add_layer("reorg", SpaceToDepthLayer(block_size=2), r)
        g.add_vertex("cat", MergeVertex(), "reorg", p)
        p = dconv("h3", "cat", 1024, 3)
        g.add_layer("det", ConvolutionLayer(
            n_out=n_anchors * (5 + self.num_classes), kernel_size=(1, 1),
            convolution_mode="VALID"), p)
        g.add_layer("yolo", Yolo2OutputLayer(anchors=self.anchors), "det")
        return g.set_outputs("yolo").build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()
