"""Zoo breadth wave 2: SqueezeNet, UNet, Xception, Darknet19, TinyYOLO.

Reference parity (architectures, not pretrained weights):
- SqueezeNet → zoo/model/SqueezeNet.java (fire modules: squeeze 1x1 +
  expand 1x1/3x3 concat)
- UNet       → zoo/model/UNet.java (4-level encoder/decoder with skip
  concats, sigmoid pixel head)
- Xception   → zoo/model/Xception.java (separable convs + residual
  shortcuts; depth trimmed by `middle_blocks` — default 8 like the
  reference's middle flow)
- Darknet19  → zoo/model/Darknet19.java (3x3/1x1 alternation, BN+leaky)
- TinyYOLO   → zoo/model/TinyYOLO.java (Darknet-ish trunk +
  Yolo2OutputLayer detection head)

All run NHWC internally (cnn_data_format default) with the external NCHW
contract; UNet's decoder uses Deconvolution + MergeVertex skip concats.
"""
from __future__ import annotations

import dataclasses
from typing import Tuple

from deeplearning4j_tpu.learning.updaters import Adam, IUpdater
from deeplearning4j_tpu.nn import (
    ActivationLayer, BatchNormalization, ComputationGraph, ConvolutionLayer,
    Deconvolution2DLayer, GlobalPoolingLayer, InputType, MergeVertex,
    MultiLayerNetwork, NeuralNetConfiguration, OutputLayer,
    SeparableConvolution2DLayer, SubsamplingLayer, Yolo2OutputLayer)


@dataclasses.dataclass
class SqueezeNet:
    """(reference: zoo/model/SqueezeNet.java)"""
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 42
    updater: IUpdater = None

    def _fire(self, g, name, inp, squeeze, expand):
        (g.add_layer(f"{name}_sq", ConvolutionLayer(
            n_out=squeeze, kernel_size=(1, 1), activation="relu",
            convolution_mode="VALID"), inp)
         .add_layer(f"{name}_e1", ConvolutionLayer(
             n_out=expand, kernel_size=(1, 1), activation="relu",
             convolution_mode="VALID"), f"{name}_sq")
         .add_layer(f"{name}_e3", ConvolutionLayer(
             n_out=expand, kernel_size=(3, 3), activation="relu",
             convolution_mode="SAME"), f"{name}_sq")
         .add_vertex(f"{name}", MergeVertex(), f"{name}_e1", f"{name}_e3"))
        return name

    def conf(self):
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        g.add_layer("conv1", ConvolutionLayer(
            n_out=64, kernel_size=(3, 3), stride=(2, 2), activation="relu",
            convolution_mode="VALID"), "input")
        g.add_layer("pool1", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), "conv1")
        prev = self._fire(g, "fire2", "pool1", 16, 64)
        prev = self._fire(g, "fire3", prev, 16, 64)
        g.add_layer("pool3", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = self._fire(g, "fire4", "pool3", 32, 128)
        prev = self._fire(g, "fire5", prev, 32, 128)
        g.add_layer("pool5", SubsamplingLayer(
            kernel_size=(3, 3), stride=(2, 2)), prev)
        prev = self._fire(g, "fire6", "pool5", 48, 192)
        prev = self._fire(g, "fire7", prev, 48, 192)
        prev = self._fire(g, "fire8", prev, 64, 256)
        prev = self._fire(g, "fire9", prev, 64, 256)
        g.add_layer("conv10", ConvolutionLayer(
            n_out=self.num_classes, kernel_size=(1, 1), activation="relu",
            convolution_mode="VALID"), prev)
        g.add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"), "conv10")
        g.add_layer("out", OutputLayer(
            n_out=self.num_classes, loss_function="MCXENT",
            has_bias=True), "gap")
        return g.set_outputs("out").build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class UNet:
    """(reference: zoo/model/UNet.java; depth trimmed by `features`)"""
    height: int = 64
    width: int = 64
    channels: int = 1
    features: int = 16          # reference uses 64; scalable
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        f = self.features
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))

        def conv_block(name, inp, n):
            (g.add_layer(f"{name}a", ConvolutionLayer(
                n_out=n, kernel_size=(3, 3), activation="relu",
                convolution_mode="SAME"), inp)
             .add_layer(f"{name}b", ConvolutionLayer(
                 n_out=n, kernel_size=(3, 3), activation="relu",
                 convolution_mode="SAME"), f"{name}a"))
            return f"{name}b"

        e1 = conv_block("enc1", "input", f)
        g.add_layer("pool1", SubsamplingLayer(kernel_size=(2, 2)), e1)
        e2 = conv_block("enc2", "pool1", 2 * f)
        g.add_layer("pool2", SubsamplingLayer(kernel_size=(2, 2)), e2)
        mid = conv_block("mid", "pool2", 4 * f)
        g.add_layer("up2", Deconvolution2DLayer(
            n_out=2 * f, kernel_size=(2, 2), stride=(2, 2),
            activation="relu"), mid)
        g.add_vertex("cat2", MergeVertex(), "up2", e2)
        d2 = conv_block("dec2", "cat2", 2 * f)
        g.add_layer("up1", Deconvolution2DLayer(
            n_out=f, kernel_size=(2, 2), stride=(2, 2),
            activation="relu"), d2)
        g.add_vertex("cat1", MergeVertex(), "up1", e1)
        d1 = conv_block("dec1", "cat1", f)
        # per-pixel sigmoid head (reference: 1x1 conv + sigmoid)
        from deeplearning4j_tpu.nn import CnnLossLayer
        g.add_layer("head", ConvolutionLayer(
            n_out=1, kernel_size=(1, 1), convolution_mode="VALID"), d1)
        g.add_layer("out", CnnLossLayer(loss_function="XENT",
                                        activation="sigmoid"), "head")
        return g.set_outputs("out").build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


@dataclasses.dataclass
class Xception:
    """(reference: zoo/model/Xception.java; middle flow depth scalable)"""
    height: int = 299
    width: int = 299
    channels: int = 3
    num_classes: int = 1000
    middle_blocks: int = 8
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        g = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).graph_builder()
             .add_inputs("input")
             .set_input_types(InputType.convolutional(
                 self.height, self.width, self.channels)))
        (g.add_layer("conv1", ConvolutionLayer(
            n_out=32, kernel_size=(3, 3), stride=(2, 2), activation="relu",
            convolution_mode="VALID"), "input")
         .add_layer("bn1", BatchNormalization(), "conv1")
         .add_layer("conv2", ConvolutionLayer(
             n_out=64, kernel_size=(3, 3), activation="relu",
             convolution_mode="SAME"), "bn1")
         .add_layer("bn2", BatchNormalization(), "conv2"))
        prev, width = "bn2", 64

        def xception_block(name, inp, n_in, n_out, relu_first=True):
            cur = inp
            if relu_first:
                g.add_layer(f"{name}_act0", ActivationLayer(
                    activation="relu"), cur)
                cur = f"{name}_act0"
            (g.add_layer(f"{name}_s1", SeparableConvolution2DLayer(
                n_out=n_out, kernel_size=(3, 3),
                convolution_mode="SAME"), cur)
             .add_layer(f"{name}_bn1", BatchNormalization(), f"{name}_s1")
             .add_layer(f"{name}_act1", ActivationLayer(activation="relu"),
                        f"{name}_bn1")
             .add_layer(f"{name}_s2", SeparableConvolution2DLayer(
                 n_out=n_out, kernel_size=(3, 3),
                 convolution_mode="SAME"), f"{name}_act1")
             .add_layer(f"{name}_bn2", BatchNormalization(), f"{name}_s2")
             .add_layer(f"{name}_pool", SubsamplingLayer(
                 kernel_size=(3, 3), stride=(2, 2),
                 convolution_mode="SAME"), f"{name}_bn2")
             .add_layer(f"{name}_short", ConvolutionLayer(
                 n_out=n_out, kernel_size=(1, 1), stride=(2, 2),
                 convolution_mode="SAME"), inp))
            from deeplearning4j_tpu.nn import ElementWiseVertex
            g.add_vertex(f"{name}", ElementWiseVertex(op="Add"),
                         f"{name}_pool", f"{name}_short")
            return name

        for n_out, name in ((128, "entry2"), (256, "entry3"),
                            (728, "entry4")):
            prev = xception_block(name, prev, width, n_out,
                                  relu_first=(name != "entry2"))
            width = n_out

        from deeplearning4j_tpu.nn import ElementWiseVertex
        for i in range(self.middle_blocks):
            nm = f"mid{i}"
            cur = prev
            for j in range(3):
                (g.add_layer(f"{nm}_act{j}", ActivationLayer(
                    activation="relu"), cur)
                 .add_layer(f"{nm}_s{j}", SeparableConvolution2DLayer(
                     n_out=728, kernel_size=(3, 3),
                     convolution_mode="SAME"), f"{nm}_act{j}")
                 .add_layer(f"{nm}_bn{j}", BatchNormalization(),
                            f"{nm}_s{j}"))
                cur = f"{nm}_bn{j}"
            g.add_vertex(nm, ElementWiseVertex(op="Add"), cur, prev)
            prev = nm

        (g.add_layer("exit_s1", SeparableConvolution2DLayer(
            n_out=1024, kernel_size=(3, 3), activation="relu",
            convolution_mode="SAME"), prev)
         .add_layer("exit_bn1", BatchNormalization(), "exit_s1")
         .add_layer("exit_s2", SeparableConvolution2DLayer(
             n_out=1536, kernel_size=(3, 3), activation="relu",
             convolution_mode="SAME"), "exit_bn1")
         .add_layer("gap", GlobalPoolingLayer(pooling_type="AVG"),
                    "exit_s2")
         .add_layer("out", OutputLayer(n_out=self.num_classes,
                                       loss_function="MCXENT"), "gap"))
        return g.set_outputs("out").build()

    def build(self) -> ComputationGraph:
        return ComputationGraph(self.conf()).init()


def _darknet_conv(b, n_out, kernel):
    b.layer(ConvolutionLayer(n_out=n_out, kernel_size=(kernel, kernel),
                             convolution_mode="SAME", has_bias=False))
    b.layer(BatchNormalization())
    b.layer(ActivationLayer(activation="leaky_relu"))
    return b


@dataclasses.dataclass
class Darknet19:
    """(reference: zoo/model/Darknet19.java)"""
    height: int = 224
    width: int = 224
    channels: int = 3
    num_classes: int = 1000
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        b = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).list())
        plan = [(32, 3, True), (64, 3, True),
                (128, 3, False), (64, 1, False), (128, 3, True),
                (256, 3, False), (128, 1, False), (256, 3, True),
                (512, 3, False), (256, 1, False), (512, 3, False),
                (256, 1, False), (512, 3, True),
                (1024, 3, False), (512, 1, False), (1024, 3, False),
                (512, 1, False), (1024, 3, False)]
        for n_out, k, pool in plan:
            _darknet_conv(b, n_out, k)
            if pool:
                b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        b.layer(ConvolutionLayer(n_out=self.num_classes, kernel_size=(1, 1),
                                 convolution_mode="VALID"))
        b.layer(GlobalPoolingLayer(pooling_type="AVG"))
        b.layer(OutputLayer(n_out=self.num_classes, loss_function="MCXENT"))
        return b.set_input_type(InputType.convolutional(
            self.height, self.width, self.channels)).build()

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()


@dataclasses.dataclass
class TinyYOLO:
    """(reference: zoo/model/TinyYOLO.java — Darknet trunk + YOLOv2 head;
    anchors in grid units)"""
    height: int = 416
    width: int = 416
    channels: int = 3
    num_classes: int = 20
    anchors: Tuple[float, ...] = (1.08, 1.19, 3.42, 4.41, 6.63, 11.38,
                                  9.42, 5.11, 16.62, 10.52)
    seed: int = 42
    updater: IUpdater = None

    def conf(self):
        n_anchors = len(self.anchors) // 2
        b = (NeuralNetConfiguration.builder().seed(self.seed)
             .updater(self.updater or Adam(1e-3)).list())
        for i, n_out in enumerate((16, 32, 64, 128, 256, 512)):
            _darknet_conv(b, n_out, 3)
            if i < 5:
                b.layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        _darknet_conv(b, 1024, 3)
        _darknet_conv(b, 1024, 3)
        b.layer(ConvolutionLayer(
            n_out=n_anchors * (5 + self.num_classes), kernel_size=(1, 1),
            convolution_mode="VALID"))
        b.layer(Yolo2OutputLayer(anchors=self.anchors))
        return b.set_input_type(InputType.convolutional(
            self.height, self.width, self.channels)).build()

    def build(self) -> MultiLayerNetwork:
        return MultiLayerNetwork(self.conf()).init()
