"""GPT-style autoregressive decoder built directly on SameDiff.

Reference parity: the reference's transformer story is the imported-BERT
benchmark plus attention layers (SURVEY §2.3 zoo; attention vertices
`deeplearning4j-nn/.../layers/recurrent` and
`libnd4j/.../generic/nn/multi_head_dot_product_attention.cpp:34`). It has
no native decoder-LM; this model is the TPU-first flagship config — the
compute-dense benchmark where MXU utilization is actually reachable:

- pre-LN residual blocks, erf-gelu MLP, learned positions (GPT-2 layout);
- the attention core is ONE fused ``scaled_dot_product_attention`` op
  (f32 scores/softmax, bf16 matmuls under mixed precision);
- every block records inside ``sd.remat_scope`` — the whole layer is one
  ``jax.checkpoint`` region, so live activation memory is per-layer
  boundaries only and batch*seq can grow to MXU-saturating sizes;
- weight-tied LM head (embedding matrix reused for logits), sparse
  softmax-CE on integer targets — no [B,S,vocab] one-hot ever exists.

Train step = SameDiff's single jitted fwd+bwd+updater program.
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32768
    hidden_size: int = 2048
    num_layers: int = 12
    num_heads: int = 16
    intermediate_size: int = 8192
    max_seq_len: int = 1024
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-5
    remat: bool = True          # one jax.checkpoint region per block
    tie_embeddings: bool = True

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads


# ~510M params: the compute-dense flagship (BENCH config gpt_medium) —
# sized so f32 masters + Adam slots + grads + bf16 compute copies +
# remat-bounded activations fill (but fit) one v5e chip's 16 GB HBM
GPT_MEDIUM = GPTConfig(hidden_size=1536, num_layers=16,
                       intermediate_size=6144, num_heads=12)
GPT_TINY = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_seq_len=64)


def _layer_norm(sd, scope, x, width, eps):
    g = sd.var(f"{scope}/gamma", value=np.ones(width, np.float32))
    b = sd.var(f"{scope}/beta", value=np.zeros(width, np.float32))
    return sd.invoke("layer_norm", [x, g, b], {"epsilon": eps},
                     name=f"{scope}/ln")


def _dense(sd, rng, scope, x, n_in, n_out, std):
    w = sd.var(f"{scope}/kernel",
               value=(rng.standard_normal((n_in, n_out)) * std)
               .astype(np.float32))
    b = sd.var(f"{scope}/bias", value=np.zeros(n_out, np.float32))
    h = sd.invoke("matmul", [x, w], name=f"{scope}/matmul")
    return sd.invoke("bias_add", [h, b], name=f"{scope}/bias")


def build_gpt(cfg: GPTConfig, batch: int, seq_len: int, seed: int = 0):
    """Build the decoder LM as a SameDiff graph.

    Placeholders: ``input_ids`` [batch, seq] int32, ``targets``
    [batch, seq] int32 (next-token ids). Outputs: ``logits``
    [batch, seq, vocab] and scalar ``loss`` (set as the loss variable).
    """
    from deeplearning4j_tpu.autodiff import SameDiff

    if seq_len > cfg.max_seq_len:
        raise ValueError(f"seq_len {seq_len} > max_seq_len {cfg.max_seq_len}")
    H, A, D = cfg.hidden_size, cfg.num_heads, cfg.head_size
    rng = np.random.default_rng(seed)
    std = cfg.initializer_range
    # GPT-2 scales residual-out projections by 1/sqrt(2L)
    res_std = std / np.sqrt(2.0 * cfg.num_layers)

    sd = SameDiff()
    ids = sd.placeholder("input_ids", shape=(batch, seq_len), dtype="int32")
    targets = sd.placeholder("targets", shape=(batch, seq_len), dtype="int32")

    wte = sd.var("wte", value=(rng.standard_normal((cfg.vocab_size, H))
                               * std).astype(np.float32))
    wpe = sd.var("wpe", value=(rng.standard_normal((cfg.max_seq_len, H))
                               * std).astype(np.float32))
    x = sd.invoke("embedding_lookup", [wte, ids], name="tok_emb")
    pos = sd.invoke("slice", [wpe], {"begin": (0, 0), "size": (seq_len, H)},
                    name="pos_slice")
    x = x.add(pos, name="emb")

    for i in range(cfg.num_layers):
        sc = f"h{i}"
        ctx = sd.remat_scope(sc) if cfg.remat else _null_ctx()
        with ctx:
            y = _layer_norm(sd, f"{sc}/ln_1", x, H, cfg.layer_norm_eps)
            qkv = _dense(sd, rng, f"{sc}/attn/qkv", y, H, 3 * H, std)
            # fused-kernel layout is PER-HEAD blocks [q_a|k_a|v_a] (not
            # [Q|K|V]): a contiguous shard of the 3H output dim then
            # holds complete heads, so Megatron column-parallel sharding
            # (parallel/sharding.py transformer rules) never straddles a
            # q/k/v boundary — zero resharding inside the block
            qkv = sd.invoke("reshape", [qkv],
                            {"shape": (batch, seq_len, A, 3 * D)},
                            name=f"{sc}/attn/split_heads")
            qkv = sd.invoke("permute", [qkv], {"axes": (0, 2, 1, 3)},
                            name=f"{sc}/attn/heads_t")   # [B, A, S, 3D]
            q, k, v = sd.invoke("split", [qkv],
                                {"num_split": 3, "axis": 3},
                                name=f"{sc}/attn/qkv_split", n_outputs=3)
            att = sd.invoke("scaled_dot_product_attention", [q, k, v],
                            {"causal": True}, name=f"{sc}/attn/sdpa")
            att = sd.invoke("permute", [att], {"axes": (0, 2, 1, 3)},
                            name=f"{sc}/attn/merge_t")
            att = sd.invoke("reshape", [att],
                            {"shape": (batch, seq_len, H)},
                            name=f"{sc}/attn/merge")
            att = _dense(sd, rng, f"{sc}/attn/proj", att, H, H, res_std)
            x = x.add(att, name=f"{sc}/res_1")
            y = _layer_norm(sd, f"{sc}/ln_2", x, H, cfg.layer_norm_eps)
            y = _dense(sd, rng, f"{sc}/mlp/fc", y, H, cfg.intermediate_size,
                       std)
            y = sd.invoke("gelu", [y], name=f"{sc}/mlp/act")
            y = _dense(sd, rng, f"{sc}/mlp/proj", y, cfg.intermediate_size,
                       H, res_std)
            x = x.add(y, name=f"{sc}/res_2")

    x = _layer_norm(sd, "ln_f", x, H, cfg.layer_norm_eps)
    if cfg.tie_embeddings:
        logits = sd.invoke("einsum", [x, wte],
                           {"equation": "bsh,vh->bsv"}, name="logits")
    else:
        head = sd.var("lm_head", value=(rng.standard_normal((H, cfg.vocab_size))
                                        * std).astype(np.float32))
        logits = sd.invoke("matmul", [x, head], name="logits")
    loss = sd.invoke("sparse_softmax_cross_entropy", [logits, targets],
                     name="loss")
    sd.set_loss_variables([loss])
    return sd


def _null_ctx():
    import contextlib
    return contextlib.nullcontext()
