"""GPT-style autoregressive decoder built directly on SameDiff.

Reference parity: the reference's transformer story is the imported-BERT
benchmark plus attention layers (SURVEY §2.3 zoo; attention vertices
`deeplearning4j-nn/.../layers/recurrent` and
`libnd4j/.../generic/nn/multi_head_dot_product_attention.cpp:34`). It has
no native decoder-LM; this model is the TPU-first flagship config — the
compute-dense benchmark where MXU utilization is actually reachable:

- pre-LN residual blocks, erf-gelu MLP, learned positions (GPT-2 layout);
- the attention core is ONE fused ``scaled_dot_product_attention`` op
  (f32 scores/softmax, bf16 matmuls under mixed precision);
- every block records inside ``sd.remat_scope`` — the whole layer is one
  ``jax.checkpoint`` region, so live activation memory is per-layer
  boundaries only and batch*seq can grow to MXU-saturating sizes;
- weight-tied LM head (embedding matrix reused for logits), sparse
  softmax-CE on integer targets — no [B,S,vocab] one-hot ever exists.

Train step = SameDiff's single jitted fwd+bwd+updater program.
"""
from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32768
    hidden_size: int = 2048
    num_layers: int = 12
    num_heads: int = 16
    intermediate_size: int = 8192
    max_seq_len: int = 1024
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-5
    remat: bool = True          # one jax.checkpoint region per block
    tie_embeddings: bool = True

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads


# ~510M params: the compute-dense flagship (BENCH config gpt_medium) —
# sized so f32 masters + Adam slots + grads + bf16 compute copies +
# remat-bounded activations fill (but fit) one v5e chip's 16 GB HBM
GPT_MEDIUM = GPTConfig(hidden_size=1536, num_layers=16,
                       intermediate_size=6144, num_heads=12)
GPT_TINY = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                     num_heads=4, intermediate_size=128, max_seq_len=64)


def _layer_norm(sd, scope, x, width, eps):
    g = sd.var(f"{scope}/gamma", value=np.ones(width, np.float32))
    b = sd.var(f"{scope}/beta", value=np.zeros(width, np.float32))
    return sd.invoke("layer_norm", [x, g, b], {"epsilon": eps},
                     name=f"{scope}/ln")


def _dense(sd, rng, scope, x, n_in, n_out, std):
    w = sd.var(f"{scope}/kernel",
               value=(rng.standard_normal((n_in, n_out)) * std)
               .astype(np.float32))
    b = sd.var(f"{scope}/bias", value=np.zeros(n_out, np.float32))
    h = sd.invoke("matmul", [x, w], name=f"{scope}/matmul")
    return sd.invoke("bias_add", [h, b], name=f"{scope}/bias")


def build_gpt(cfg: GPTConfig, batch: int, seq_len: int, seed: int = 0):
    """Build the decoder LM as a SameDiff graph.

    Placeholders: ``input_ids`` [batch, seq] int32, ``targets``
    [batch, seq] int32 (next-token ids). Outputs: ``logits``
    [batch, seq, vocab] and scalar ``loss`` (set as the loss variable).
    """
    from deeplearning4j_tpu.autodiff import SameDiff

    if seq_len > cfg.max_seq_len:
        raise ValueError(f"seq_len {seq_len} > max_seq_len {cfg.max_seq_len}")
    H, A, D = cfg.hidden_size, cfg.num_heads, cfg.head_size
    rng = np.random.default_rng(seed)
    std = cfg.initializer_range
    # GPT-2 scales residual-out projections by 1/sqrt(2L)
    res_std = std / np.sqrt(2.0 * cfg.num_layers)

    sd = SameDiff()
    ids = sd.placeholder("input_ids", shape=(batch, seq_len), dtype="int32")
    targets = sd.placeholder("targets", shape=(batch, seq_len), dtype="int32")

    wte = sd.var("wte", value=(rng.standard_normal((cfg.vocab_size, H))
                               * std).astype(np.float32))
    wpe = sd.var("wpe", value=(rng.standard_normal((cfg.max_seq_len, H))
                               * std).astype(np.float32))
    x = sd.invoke("embedding_lookup", [wte, ids], name="tok_emb")
    pos = sd.invoke("slice", [wpe], {"begin": (0, 0), "size": (seq_len, H)},
                    name="pos_slice")
    x = x.add(pos, name="emb")

    for i in range(cfg.num_layers):
        sc = f"h{i}"
        ctx = sd.remat_scope(sc) if cfg.remat else _null_ctx()
        with ctx:
            y = _layer_norm(sd, f"{sc}/ln_1", x, H, cfg.layer_norm_eps)
            qkv = _dense(sd, rng, f"{sc}/attn/qkv", y, H, 3 * H, std)
            # fused-kernel layout is PER-HEAD blocks [q_a|k_a|v_a] (not
            # [Q|K|V]): a contiguous shard of the 3H output dim then
            # holds complete heads, so Megatron column-parallel sharding
            # (parallel/sharding.py transformer rules) never straddles a
            # q/k/v boundary — zero resharding inside the block
            qkv = sd.invoke("reshape", [qkv],
                            {"shape": (batch, seq_len, A, 3 * D)},
                            name=f"{sc}/attn/split_heads")
            qkv = sd.invoke("permute", [qkv], {"axes": (0, 2, 1, 3)},
                            name=f"{sc}/attn/heads_t")   # [B, A, S, 3D]
            q, k, v = sd.invoke("split", [qkv],
                                {"num_split": 3, "axis": 3},
                                name=f"{sc}/attn/qkv_split", n_outputs=3)
            att = sd.invoke("scaled_dot_product_attention", [q, k, v],
                            {"causal": True}, name=f"{sc}/attn/sdpa")
            att = sd.invoke("permute", [att], {"axes": (0, 2, 1, 3)},
                            name=f"{sc}/attn/merge_t")
            att = sd.invoke("reshape", [att],
                            {"shape": (batch, seq_len, H)},
                            name=f"{sc}/attn/merge")
            att = _dense(sd, rng, f"{sc}/attn/proj", att, H, H, res_std)
            x = x.add(att, name=f"{sc}/res_1")
            y = _layer_norm(sd, f"{sc}/ln_2", x, H, cfg.layer_norm_eps)
            y = _dense(sd, rng, f"{sc}/mlp/fc", y, H, cfg.intermediate_size,
                       std)
            y = sd.invoke("gelu", [y], name=f"{sc}/mlp/act")
            y = _dense(sd, rng, f"{sc}/mlp/proj", y, cfg.intermediate_size,
                       H, res_std)
            x = x.add(y, name=f"{sc}/res_2")

    x = _layer_norm(sd, "ln_f", x, H, cfg.layer_norm_eps)
    if cfg.tie_embeddings:
        logits = sd.invoke("einsum", [x, wte],
                           {"equation": "bsh,vh->bsv"}, name="logits")
    else:
        head = sd.var("lm_head", value=(rng.standard_normal((H, cfg.vocab_size))
                                        * std).astype(np.float32))
        logits = sd.invoke("matmul", [x, head], name="logits")
    loss = sd.invoke("sparse_softmax_cross_entropy", [logits, targets],
                     name="loss")
    sd.set_loss_variables([loss])
    return sd


def _null_ctx():
    import contextlib
    return contextlib.nullcontext()


# ----------------------------------------------------------------------
# decode-mode graph hook (serving/generative.py — continuous batching)
# ----------------------------------------------------------------------
def gpt_param_names(cfg: GPTConfig):
    """The trained-variable names :func:`build_gpt` creates — the
    contract between the training graph and the decode functions below
    (the generative spec pulls arrays from the SameDiff by these
    names, the same by-name convention as ``ServingSpec.sync`` /
    ``ParallelInference.reload_from``)."""
    names = ["wte", "wpe", "ln_f/gamma", "ln_f/beta"]
    for i in range(cfg.num_layers):
        sc = f"h{i}"
        for part in ("ln_1/gamma", "ln_1/beta",
                     "attn/qkv/kernel", "attn/qkv/bias",
                     "attn/proj/kernel", "attn/proj/bias",
                     "ln_2/gamma", "ln_2/beta",
                     "mlp/fc/kernel", "mlp/fc/bias",
                     "mlp/proj/kernel", "mlp/proj/bias"):
            names.append(f"{sc}/{part}")
    if not cfg.tie_embeddings:
        names.append("lm_head")
    return names


def gpt_decode_fns(cfg: GPTConfig, quantize_weights: bool = False,
                   kv_scales=None):
    """Pure-jax ``(prefill_fn, decode_fn, verify_fn)`` mirroring
    :func:`build_gpt`'s math op-for-op (one-pass layer norm with
    ``rsqrt``, per-head-block fused qkv layout, f32 attention
    scores/softmax, tanh-gelu, tied logits) but in DECODE MODE:
    attention reads/writes preallocated per-slot KV cache slabs instead
    of recomputing the full sequence.

    KV slab layout (one array each for K and V, shared by every layer so
    a serving step donates exactly two buffers)::

        [num_layers, max_slots, heads, max_seq, head_dim]

    - ``prefill_fn(params, kc, vc, io)`` with
      ``io = {"tokens": [L] int32, "length": () int32, "slot": () int32}``
      runs the full causal forward over one request's (bucket-padded)
      prompt, writes its K/V rows into cache slot ``io["slot"]`` and
      returns ``(kc, vc, next_token, last_logits)`` — the greedy first
      generated token from the last REAL prompt position
      (``length - 1``; padded rows never influence it, causal mask).
    - ``decode_fn(params, kc, vc, io)`` with
      ``io = {"tokens": [S] int32, "positions": [S] int32,
      "active": [S] bool}`` advances EVERY active slot one token in one
      dispatch: per-slot KV written in place at that slot's position
      (inactive slots' caches untouched), attention masked to
      ``index <= position`` with masked V rows zeroed under the mask —
      so a retired slot's stale (even poisoned/NaN) cache rows can
      never leak into its successor, bit-exactly (tested). Returns
      ``(kc, vc, next_tokens, logits)``.
    - ``verify_fn(params, kc, vc, io)`` with ``io = {"tokens": [S, W]
      int32, "positions": [S] int32, "active": [S] bool}`` is the
      speculative-decoding verifier (Leviathan et al.): column 0 of the
      window is each slot's last emitted token, columns 1..W-1 a
      draft's proposals. It writes all W KV rows per active slot
      (positions ``p0..p0+W-1``) and returns ``(kc, vc, out [S, W],
      logits [S, W, vocab])`` where ``out[s, j]`` is the target's
      greedy token AFTER consuming window tokens ``0..j`` — row j of a
      W-token causal forward, so ``out[s, 0]`` is bit-identical to
      ``decode_fn`` fed the same token. The host accepts the longest
      prefix where the drafted column ``j+1`` equals ``out[:, j]`` and
      rewinds positions past it — the masked-KV discipline (stale rows
      are masked until overwritten) makes the rollback free.

    All are shape-static per (bucket, max_slots, window): the serving
    tier compiles ONE decode program, one verify program per window
    width, plus one prefill program per pow2 prompt bucket
    (docs/serving.md "Generative serving" / "Decode speed").

    ``quantize_weights=True`` expects the param dict from
    :func:`gpt_quantize_params`: matmul weights and embeddings carried
    as int8 payloads plus per-output-channel f32 ``<name>::scale``
    arrays; the dequant is applied to the [..., n_out] matmul PRODUCT
    (or folded into the activation for the tied logits einsum), so the
    weight bytes read per decode step drop 4x without an f32 copy ever
    materializing. ``kv_scales={"k": [L, A, D], "v": [L, A, D]}``
    (from :func:`gpt_kv_scales`) turns the slabs into int8: K/V are
    quantized per (layer, head, channel) at write and dequantized at
    gather, inside the same compiled step.
    """
    import jax
    import jax.numpy as jnp

    H, A, D, L = (cfg.hidden_size, cfg.num_heads, cfg.head_size,
                  cfg.num_layers)
    eps = cfg.layer_norm_eps
    scale = 1.0 / np.sqrt(D)        # matches ops scaled_dot_product_attention
    QW = bool(quantize_weights)
    KQ = kv_scales is not None
    # scales become jaxpr constants at trace time: [L, A, D] each
    ksc = np.asarray(kv_scales["k"], np.float32) if KQ else None
    vsc = np.asarray(kv_scales["v"], np.float32) if KQ else None

    def _ln(x, g, b):
        # one-pass moments + rsqrt, exactly ops/nn_ops.py layer_norm's
        # f32 path (x is f32 here)
        mean = jnp.mean(x, axis=-1, keepdims=True)
        m2 = jnp.mean(x * x, axis=-1, keepdims=True)
        var = jnp.maximum(m2 - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        return (x - mean) * inv * g + b

    def _matmul(p, n, x):
        # int8 path: matmul the raw int8 payload upcast in-register,
        # per-output-channel scale applied to the [..., n_out] product
        # — the dequant rides the matmul epilogue instead of
        # materializing an f32 weight copy
        if QW:
            return (x @ p[n].astype(jnp.float32)) * p[n + "::scale"]
        return x @ p[n]

    def _mlp(p, sc, x):
        y = _matmul(p, f"{sc}/mlp/fc/kernel", x) + p[f"{sc}/mlp/fc/bias"]
        y = jax.nn.gelu(y, approximate=True)    # ops gelu default
        return _matmul(p, f"{sc}/mlp/proj/kernel", y) \
            + p[f"{sc}/mlp/proj/bias"]

    def _tok_emb(p, tokens):
        e = jnp.take(p["wte"], tokens, axis=0)
        if QW:
            e = e.astype(jnp.float32) * p["wte::scale"]
        return e

    def _logits(p, x):
        if cfg.tie_embeddings:
            if QW:
                # (wte_i8 * s_h) contracted over h == wte_i8 contracted
                # with (x * s_h): fold the per-hidden-channel scale into
                # the small activation, keep the big operand int8
                return jnp.einsum("...h,vh->...v", x * p["wte::scale"],
                                  p["wte"].astype(jnp.float32))
            return jnp.einsum("...h,vh->...v", x, p["wte"])
        return _matmul(p, "lm_head", x)

    def _q_store(x, dt, s):
        # symmetric int8 at write: one round+clip per fresh K/V row
        if s is None:
            return x.astype(dt)
        return jnp.clip(jnp.round(x / s), -127, 127).astype(dt)

    def _q_load(x, s):
        # dequant at gather, fused into the score/att matmul producers
        if s is None:
            return x
        return x.astype(jnp.float32) * s

    def prefill_fn(params, kc, vc, io):
        p = params
        tokens, length, slot = io["tokens"], io["length"], io["slot"]
        Lb = tokens.shape[0]
        x = _tok_emb(p, tokens) + p["wpe"][:Lb]             # [Lb, H]
        cm = jnp.tril(jnp.ones((Lb, Lb), bool))
        for i in range(L):
            sc = f"h{i}"
            y = _ln(x, p[f"{sc}/ln_1/gamma"], p[f"{sc}/ln_1/beta"])
            qkv = _matmul(p, f"{sc}/attn/qkv/kernel", y) \
                + p[f"{sc}/attn/qkv/bias"]
            # per-head blocks [q_a|k_a|v_a] — build_gpt's fused layout
            qkv = jnp.transpose(qkv.reshape(Lb, A, 3 * D), (1, 0, 2))
            q, k, v = jnp.split(qkv, 3, axis=-1)        # [A, Lb, D]
            scores = jnp.einsum(
                "aqd,akd->aqk", q, k,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(cm, scores, jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
            att = jnp.einsum("aqk,akd->aqd", probs, v)
            # write this slot's prompt K/V rows (positions 0..Lb-1);
            # rows past the real length hold padding-token K/V — decode
            # masks them until its own writes land there. All start
            # indices int32 (dynamic_update_slice requires one type;
            # x64 mode would make bare python ints int64)
            z = jnp.asarray(0, jnp.int32)
            starts = (jnp.asarray(i, jnp.int32),
                      jnp.asarray(slot, jnp.int32), z, z, z)
            kc = jax.lax.dynamic_update_slice(
                kc, _q_store(k, kc.dtype,
                             ksc[i][:, None, :] if KQ else None)[None, None],
                starts)
            vc = jax.lax.dynamic_update_slice(
                vc, _q_store(v, vc.dtype,
                             vsc[i][:, None, :] if KQ else None)[None, None],
                starts)
            att = jnp.transpose(att, (1, 0, 2)).reshape(Lb, H)
            att = _matmul(p, f"{sc}/attn/proj/kernel", att) \
                + p[f"{sc}/attn/proj/bias"]
            x = x + att
            y = _ln(x, p[f"{sc}/ln_2/gamma"], p[f"{sc}/ln_2/beta"])
            x = x + _mlp(p, sc, y)
        x = _ln(x, p["ln_f/gamma"], p["ln_f/beta"])
        h_last = jax.lax.dynamic_slice_in_dim(
            x, jnp.maximum(length - 1, 0), 1, axis=0)       # [1, H]
        logits = _logits(p, h_last)[0]                      # [vocab]
        return kc, vc, jnp.argmax(logits).astype(jnp.int32), logits

    def decode_fn(params, kc, vc, io):
        p = params
        tokens, active = io["tokens"], io["active"]
        S, T = kc.shape[1], kc.shape[3]
        pos = jnp.clip(io["positions"], 0, T - 1)
        x = _tok_emb(p, tokens) \
            + jnp.take(p["wpe"], pos, axis=0)               # [S, H]
        si = jnp.arange(S)[:, None]
        ai = jnp.arange(A)[None, :]
        # attend to indices <= position; everything later in the slab
        # is a future write or a retired occupant's stale rows
        mask = jnp.arange(T)[None, None, :] <= pos[:, None, None]
        for i in range(L):
            sc = f"h{i}"
            y = _ln(x, p[f"{sc}/ln_1/gamma"], p[f"{sc}/ln_1/beta"])
            qkv = _matmul(p, f"{sc}/attn/qkv/kernel", y) \
                + p[f"{sc}/attn/qkv/bias"]
            q, k, v = jnp.split(qkv.reshape(S, A, 3 * D), 3, axis=-1)
            # in-place per-slot writes at each slot's own position;
            # inactive slots keep their existing rows (forensics — and
            # a free slot's cache is fully rewritten by prefill anyway)
            cur_k = kc[i, si, ai, pos[:, None]]
            cur_v = vc[i, si, ai, pos[:, None]]
            k_st = _q_store(k, kc.dtype, ksc[i][None] if KQ else None)
            v_st = _q_store(v, vc.dtype, vsc[i][None] if KQ else None)
            kc = kc.at[i, si, ai, pos[:, None]].set(
                jnp.where(active[:, None, None], k_st, cur_k))
            vc = vc.at[i, si, ai, pos[:, None]].set(
                jnp.where(active[:, None, None], v_st, cur_v))
            ctx_k = _q_load(kc[i], ksc[i][None, :, None, :] if KQ else None)
            ctx_v = _q_load(vc[i], vsc[i][None, :, None, :] if KQ else None)
            scores = jnp.einsum(
                "sad,satd->sat", q, ctx_k,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(mask, scores, jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(ctx_v.dtype)
            # zero masked V rows: a softmax weight of exactly 0 times a
            # NaN/Inf stale row would still be NaN — the where makes
            # slot reuse provably independent of retired-cache contents
            v_safe = jnp.where(mask[..., None], ctx_v, 0)
            att = jnp.einsum("sat,satd->sad", probs, v_safe)
            att = att.reshape(S, H)
            att = _matmul(p, f"{sc}/attn/proj/kernel", att) \
                + p[f"{sc}/attn/proj/bias"]
            x = x + att
            y = _ln(x, p[f"{sc}/ln_2/gamma"], p[f"{sc}/ln_2/beta"])
            x = x + _mlp(p, sc, y)
        x = _ln(x, p["ln_f/gamma"], p["ln_f/beta"])
        logits = _logits(p, x)                              # [S, vocab]
        return kc, vc, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
            logits

    def verify_fn(params, kc, vc, io):
        p = params
        tokens, active = io["tokens"], io["active"]         # [S, W], [S]
        S, W = tokens.shape
        T = kc.shape[3]
        pos = jnp.clip(io["positions"][:, None]
                       + jnp.arange(W, dtype=jnp.int32)[None, :],
                       0, T - 1)                            # [S, W]
        x = _tok_emb(p, tokens) \
            + jnp.take(p["wpe"], pos, axis=0)               # [S, W, H]
        si = jnp.arange(S)
        ai = jnp.arange(A)
        # window row w attends to global index <= its own position —
        # the causal mask over history + the in-window prefix
        mask = jnp.arange(T)[None, None, :] <= pos[:, :, None]  # [S, W, T]
        # rows beyond each slot's LAST window position are stale
        # (retired occupants / future writes) and may be poisoned;
        # in-window rows masked for earlier w are FRESH finite writes
        # whose -1e30 score gives an exactly-0 weight — so zeroing by
        # the per-slot upper bound is the same poisoned-slab discipline
        # as decode_fn's full mask, without a [S,W,T,D] where
        vmask = jnp.arange(T)[None, :] <= pos[:, -1][:, None]   # [S, T]
        for i in range(L):
            sc = f"h{i}"
            y = _ln(x, p[f"{sc}/ln_1/gamma"], p[f"{sc}/ln_1/beta"])
            qkv = _matmul(p, f"{sc}/attn/qkv/kernel", y) \
                + p[f"{sc}/attn/qkv/bias"]
            q, k, v = jnp.split(qkv.reshape(S, W, A, 3 * D), 3, axis=-1)
            # scatter all W rows per slot at positions p0..p0+W-1;
            # inactive slots keep their existing rows (same contract as
            # decode_fn)
            idx = (i, si[:, None, None], ai[None, None, :],
                   pos[:, :, None])
            cur_k = kc[idx]
            cur_v = vc[idx]
            k_st = _q_store(k, kc.dtype,
                            ksc[i][None, None] if KQ else None)
            v_st = _q_store(v, vc.dtype,
                            vsc[i][None, None] if KQ else None)
            ok = active[:, None, None, None]
            kc = kc.at[idx].set(jnp.where(ok, k_st, cur_k))
            vc = vc.at[idx].set(jnp.where(ok, v_st, cur_v))
            ctx_k = _q_load(kc[i], ksc[i][None, :, None, :] if KQ else None)
            ctx_v = _q_load(vc[i], vsc[i][None, :, None, :] if KQ else None)
            scores = jnp.einsum(
                "swad,satd->swat", q, ctx_k,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(mask[:, :, None, :], scores,
                               jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(ctx_v.dtype)
            v_safe = jnp.where(vmask[:, None, :, None], ctx_v, 0)
            att = jnp.einsum("swat,satd->swad", probs, v_safe)
            att = att.reshape(S, W, H)
            att = _matmul(p, f"{sc}/attn/proj/kernel", att) \
                + p[f"{sc}/attn/proj/bias"]
            x = x + att
            y = _ln(x, p[f"{sc}/ln_2/gamma"], p[f"{sc}/ln_2/beta"])
            x = x + _mlp(p, sc, y)
        x = _ln(x, p["ln_f/gamma"], p["ln_f/beta"])
        logits = _logits(p, x)                          # [S, W, vocab]
        return kc, vc, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
            logits

    return prefill_fn, decode_fn, verify_fn


def gpt_paged_decode_fns(cfg: GPTConfig, block_size: int,
                         max_blocks_per_req: int,
                         quantize_weights: bool = False, kv_scales=None):
    """Pure-jax ``(prefill_fn, decode_fn, verify_fn)`` over PAGED KV
    slabs — the same math as :func:`gpt_decode_fns` op-for-op, but
    attention reads/writes fixed-size token BLOCKS addressed through
    per-request block tables (vLLM's PagedAttention layout, Kwon et al.
    SOSP '23) instead of one contiguous ``max_seq`` row per slot.

    KV slab layout (one array each for K and V)::

        [num_layers, num_blocks, heads, block_size, head_dim]

    Block 0 is the NULL block: never handed out by the pool, the target
    of every unused table entry and every inactive decode lane's write —
    so inactive-lane scatters are harmless by construction and gathered
    trash is provably masked (V rows zeroed under the mask, the same
    poisoned-cache discipline as the slotted decode).

    - ``prefill_fn(params, kc, vc, io)`` with ``io = {"tokens": [Lb]
      int32 (the bucket-padded prompt SUFFIX after any prefix-cache
      hit), "length": () int32 (real suffix length), "hist": () int32
      (cached-prefix length, a multiple of block_size), "table": [MAXB]
      int32}`` scatters the suffix K/V into its table's blocks, attends
      causally over the WHOLE table (cached prefix + fresh suffix) and
      returns ``(kc, vc, next_token, last_logits)`` — the greedy token
      from global position ``hist + length - 1``. ONE program shape
      serves both the cold path (``hist = 0``) and every prefix hit.
    - ``decode_fn(params, kc, vc, io)`` with ``io = {"tokens": [S],
      "positions": [S], "active": [S] bool, "tables": [S, MAXB] int32,
      "write_block": [S] int32, "write_off": [S] int32}`` advances
      every active lane one token in ONE dispatch: the new K/V lands at
      host-computed ``(write_block, write_off)`` (inactive lanes write
      the null block), each lane attends over its own gathered table
      masked to ``index <= position``.
    - ``verify_fn(params, kc, vc, io)`` — the speculative-decoding
      verifier over paged slabs: ``io`` carries a [S, W] token window
      plus [S, W] ``write_block``/``write_off`` (host-computed per
      window position; inactive lanes point every column at the null
      block) and returns ``(kc, vc, out [S, W], logits [S, W, vocab])``
      with the same row-j semantics as the dense
      ``gpt_decode_fns`` verifier.

    Because a table slot ``u`` covers exactly global positions
    ``[u * block_size, (u+1) * block_size)``, the gathered context is
    position-ordered — with ``max_blocks_per_req * block_size ==
    max_seq`` it is ELEMENTWISE identical to the dense slab's context,
    so greedy outputs match the dense server bit-for-bit
    (tests/test_paged.py).

    ``quantize_weights`` / ``kv_scales`` follow the
    :func:`gpt_decode_fns` contract: int8 weight payloads with
    ``::scale`` dequant in the matmul epilogue, and int8 KV blocks
    quantized per (layer, head, channel) at write / dequantized at
    gather — which DOUBLES vs f16 (4x vs f32) the tokens a fixed-byte
    ``BlockPool`` holds, compounding with prefix caching.
    """
    import jax
    import jax.numpy as jnp

    H, A, D, L = (cfg.hidden_size, cfg.num_heads, cfg.head_size,
                  cfg.num_layers)
    BS = int(block_size)
    MAXB = int(max_blocks_per_req)
    T = MAXB * BS                   # gathered context length per request
    eps = cfg.layer_norm_eps
    scale = 1.0 / np.sqrt(D)
    QW = bool(quantize_weights)
    KQ = kv_scales is not None
    ksc = np.asarray(kv_scales["k"], np.float32) if KQ else None
    vsc = np.asarray(kv_scales["v"], np.float32) if KQ else None

    def _ln(x, g, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        m2 = jnp.mean(x * x, axis=-1, keepdims=True)
        var = jnp.maximum(m2 - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        return (x - mean) * inv * g + b

    def _matmul(p, n, x):
        if QW:
            return (x @ p[n].astype(jnp.float32)) * p[n + "::scale"]
        return x @ p[n]

    def _mlp(p, sc, x):
        y = _matmul(p, f"{sc}/mlp/fc/kernel", x) + p[f"{sc}/mlp/fc/bias"]
        y = jax.nn.gelu(y, approximate=True)
        return _matmul(p, f"{sc}/mlp/proj/kernel", y) \
            + p[f"{sc}/mlp/proj/bias"]

    def _tok_emb(p, tokens):
        e = jnp.take(p["wte"], tokens, axis=0)
        if QW:
            e = e.astype(jnp.float32) * p["wte::scale"]
        return e

    def _logits(p, x):
        if cfg.tie_embeddings:
            if QW:
                return jnp.einsum("...h,vh->...v", x * p["wte::scale"],
                                  p["wte"].astype(jnp.float32))
            return jnp.einsum("...h,vh->...v", x, p["wte"])
        return _matmul(p, "lm_head", x)

    def _q_store(x, dt, s):
        if s is None:
            return x.astype(dt)
        return jnp.clip(jnp.round(x / s), -127, 127).astype(dt)

    def _q_load(x, s):
        if s is None:
            return x
        return x.astype(jnp.float32) * s

    def prefill_fn(params, kc, vc, io):
        p = params
        tokens, length = io["tokens"], io["length"]
        hist, table = io["hist"], io["table"]
        Lb = tokens.shape[0]
        ai = jnp.arange(A)
        # global positions of the suffix rows; clip keeps the padded
        # tail's wpe lookups in range (those rows never reach logits)
        g = hist + jnp.arange(Lb, dtype=jnp.int32)
        gpos = jnp.clip(g, 0, cfg.max_seq_len - 1)
        x = _tok_emb(p, tokens) \
            + jnp.take(p["wpe"], gpos, axis=0)               # [Lb, H]
        # scatter targets: suffix row j lands in table slot g//BS at
        # offset g%BS; padding rows (j >= length) land in null block 0
        slot_of = jnp.clip(g // BS, 0, MAXB - 1)
        blk = jnp.where(jnp.arange(Lb) < length, table[slot_of], 0)
        off = jnp.clip(g, 0, T - 1) % BS
        # causal mask over the gathered context: key index t is a
        # GLOBAL position (table slot u holds positions [u*BS,(u+1)*BS))
        cm = jnp.arange(T)[None, :] <= g[:, None]            # [Lb, T]
        # rows past hist+length are unwritten blocks / null-block trash
        valid = jnp.arange(T)[None, :] < hist + length       # [1, T]
        for i in range(L):
            sc = f"h{i}"
            y = _ln(x, p[f"{sc}/ln_1/gamma"], p[f"{sc}/ln_1/beta"])
            qkv = _matmul(p, f"{sc}/attn/qkv/kernel", y) \
                + p[f"{sc}/attn/qkv/bias"]
            qkv = jnp.transpose(qkv.reshape(Lb, A, 3 * D), (1, 0, 2))
            q, k, v = jnp.split(qkv, 3, axis=-1)             # [A, Lb, D]
            # write the suffix K/V FIRST, then gather the whole table —
            # suffix self-attention reads its own fresh rows
            kc = kc.at[i, blk[None, :], ai[:, None], off[None, :]].set(
                _q_store(k, kc.dtype, ksc[i][:, None, :] if KQ else None))
            vc = vc.at[i, blk[None, :], ai[:, None], off[None, :]].set(
                _q_store(v, vc.dtype, vsc[i][:, None, :] if KQ else None))
            ctx_k = _q_load(jnp.transpose(kc[i][table], (1, 0, 2, 3))
                            .reshape(A, T, D),
                            ksc[i][:, None, :] if KQ else None)
            ctx_v = _q_load(jnp.transpose(vc[i][table], (1, 0, 2, 3))
                            .reshape(A, T, D),
                            vsc[i][:, None, :] if KQ else None)
            # zero unwritten rows BEFORE the matmuls: null-block trash
            # (even NaN-poisoned) must not reach any reduction
            ctx_k = jnp.where(valid[0][:, None], ctx_k, 0)
            ctx_v = jnp.where(valid[0][:, None], ctx_v, 0)
            scores = jnp.einsum(
                "aqd,akd->aqk", q, ctx_k,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(cm[None], scores, jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(ctx_v.dtype)
            att = jnp.einsum("aqk,akd->aqd", probs, ctx_v)
            att = jnp.transpose(att, (1, 0, 2)).reshape(Lb, H)
            att = _matmul(p, f"{sc}/attn/proj/kernel", att) \
                + p[f"{sc}/attn/proj/bias"]
            x = x + att
            y = _ln(x, p[f"{sc}/ln_2/gamma"], p[f"{sc}/ln_2/beta"])
            x = x + _mlp(p, sc, y)
        x = _ln(x, p["ln_f/gamma"], p["ln_f/beta"])
        h_last = jax.lax.dynamic_slice_in_dim(
            x, jnp.maximum(length - 1, 0), 1, axis=0)        # [1, H]
        logits = _logits(p, h_last)[0]
        return kc, vc, jnp.argmax(logits).astype(jnp.int32), logits

    def decode_fn(params, kc, vc, io):
        p = params
        tokens, active = io["tokens"], io["active"]
        tables = io["tables"]                                # [S, MAXB]
        wb, wo = io["write_block"], io["write_off"]
        S = tokens.shape[0]
        pos = jnp.clip(io["positions"], 0, cfg.max_seq_len - 1)
        x = _tok_emb(p, tokens) \
            + jnp.take(p["wpe"], pos, axis=0)                # [S, H]
        ai = jnp.arange(A)
        # attend to global index <= position; later table rows are
        # unwritten blocks or another layer of the null block
        mask = jnp.arange(T)[None, None, :] <= pos[:, None, None]
        for i in range(L):
            sc = f"h{i}"
            y = _ln(x, p[f"{sc}/ln_1/gamma"], p[f"{sc}/ln_1/beta"])
            qkv = _matmul(p, f"{sc}/attn/qkv/kernel", y) \
                + p[f"{sc}/attn/qkv/bias"]
            q, k, v = jnp.split(qkv.reshape(S, A, 3 * D), 3, axis=-1)
            # unconditional scatter: the host points inactive lanes at
            # the null block, so no active request's rows are touched
            # (active lanes own disjoint blocks — no write collisions)
            kc = kc.at[i, wb[:, None], ai[None, :], wo[:, None]].set(
                _q_store(k, kc.dtype, ksc[i][None] if KQ else None))
            vc = vc.at[i, wb[:, None], ai[None, :], wo[:, None]].set(
                _q_store(v, vc.dtype, vsc[i][None] if KQ else None))
            ctx_k = _q_load(jnp.transpose(kc[i][tables], (0, 2, 1, 3, 4))
                            .reshape(S, A, T, D),
                            ksc[i][None, :, None, :] if KQ else None)
            ctx_v = _q_load(jnp.transpose(vc[i][tables], (0, 2, 1, 3, 4))
                            .reshape(S, A, T, D),
                            vsc[i][None, :, None, :] if KQ else None)
            scores = jnp.einsum(
                "sad,satd->sat", q, ctx_k,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(mask, scores, jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(ctx_v.dtype)
            # zero masked V rows — same poisoned-slab-reuse discipline
            # as the slotted decode: weight 0 x NaN trash is still NaN
            v_safe = jnp.where(mask[..., None], ctx_v, 0)
            att = jnp.einsum("sat,satd->sad", probs, v_safe)
            att = att.reshape(S, H)
            att = _matmul(p, f"{sc}/attn/proj/kernel", att) \
                + p[f"{sc}/attn/proj/bias"]
            x = x + att
            y = _ln(x, p[f"{sc}/ln_2/gamma"], p[f"{sc}/ln_2/beta"])
            x = x + _mlp(p, sc, y)
        x = _ln(x, p["ln_f/gamma"], p["ln_f/beta"])
        logits = _logits(p, x)                               # [S, vocab]
        return kc, vc, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
            logits

    def verify_fn(params, kc, vc, io):
        p = params
        tokens, active = io["tokens"], io["active"]          # [S, W]
        tables = io["tables"]                                # [S, MAXB]
        wb, wo = io["write_block"], io["write_off"]          # [S, W]
        S, W = tokens.shape
        pos = jnp.clip(io["positions"][:, None]
                       + jnp.arange(W, dtype=jnp.int32)[None, :],
                       0, cfg.max_seq_len - 1)               # [S, W]
        x = _tok_emb(p, tokens) \
            + jnp.take(p["wpe"], pos, axis=0)                # [S, W, H]
        ai = jnp.arange(A)
        mask = jnp.arange(T)[None, None, :] <= pos[:, :, None]
        # per-slot stale-row bound — see the dense verify_fn: in-window
        # rows masked for earlier w are fresh finite writes, rows past
        # the window's last position may be poisoned trash
        vmask = jnp.arange(T)[None, :] <= pos[:, -1][:, None]
        for i in range(L):
            sc = f"h{i}"
            y = _ln(x, p[f"{sc}/ln_1/gamma"], p[f"{sc}/ln_1/beta"])
            qkv = _matmul(p, f"{sc}/attn/qkv/kernel", y) \
                + p[f"{sc}/attn/qkv/bias"]
            q, k, v = jnp.split(qkv.reshape(S, W, A, 3 * D), 3, axis=-1)
            # unconditional [S, W] scatter: active lanes own disjoint
            # in-order (block, off) pairs, inactive lanes' W columns all
            # target the null block (colliding writes there are trash
            # over trash by construction)
            kc = kc.at[i, wb[:, :, None], ai[None, None, :],
                       wo[:, :, None]].set(
                _q_store(k, kc.dtype,
                         ksc[i][None, None] if KQ else None))
            vc = vc.at[i, wb[:, :, None], ai[None, None, :],
                       wo[:, :, None]].set(
                _q_store(v, vc.dtype,
                         vsc[i][None, None] if KQ else None))
            ctx_k = _q_load(jnp.transpose(kc[i][tables], (0, 2, 1, 3, 4))
                            .reshape(S, A, T, D),
                            ksc[i][None, :, None, :] if KQ else None)
            ctx_v = _q_load(jnp.transpose(vc[i][tables], (0, 2, 1, 3, 4))
                            .reshape(S, A, T, D),
                            vsc[i][None, :, None, :] if KQ else None)
            scores = jnp.einsum(
                "swad,satd->swat", q, ctx_k,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(mask[:, :, None, :], scores,
                               jnp.float32(-1e30))
            probs = jax.nn.softmax(scores, axis=-1).astype(ctx_v.dtype)
            v_safe = jnp.where(vmask[:, None, :, None], ctx_v, 0)
            att = jnp.einsum("swat,satd->swad", probs, v_safe)
            att = att.reshape(S, W, H)
            att = _matmul(p, f"{sc}/attn/proj/kernel", att) \
                + p[f"{sc}/attn/proj/bias"]
            x = x + att
            y = _ln(x, p[f"{sc}/ln_2/gamma"], p[f"{sc}/ln_2/beta"])
            x = x + _mlp(p, sc, y)
        x = _ln(x, p["ln_f/gamma"], p["ln_f/beta"])
        logits = _logits(p, x)                           # [S, W, vocab]
        return kc, vc, jnp.argmax(logits, axis=-1).astype(jnp.int32), \
            logits

    return prefill_fn, decode_fn, verify_fn


def _quantized_param_names(cfg: GPTConfig):
    """The matmul weights + embeddings that carry int8 payloads under
    ``quantize_weights`` — the big operands whose bytes dominate decode
    HBM traffic. Layer norms and biases stay f32 (tiny, precision-
    critical)."""
    names = [n for n in gpt_param_names(cfg) if n.endswith("/kernel")]
    names.append("wte")
    if not cfg.tie_embeddings:
        names.append("lm_head")
    return names


def gpt_quantize_params(raw: dict, cfg: GPTConfig) -> dict:
    """Symmetric per-output-channel int8 of the decode parameters:
    every ``/kernel`` plus the embedding matrix becomes an int8 payload
    with a float32 ``<name>::scale`` companion (absmax scales via
    :func:`evaluation.calibration.channel_scales` — weights have no
    outlier tail worth clipping, so every value stays representable).
    ``wte``'s channels are the HIDDEN axis, so the same scale serves
    the embedding take and the tied-logits einsum. Pure: re-pulling
    after ``fit()`` + ``update_model()`` re-quantizes the new weights.
    """
    from deeplearning4j_tpu.evaluation.calibration import channel_scales

    out = {}
    qnames = set(_quantized_param_names(cfg))
    for n, a in raw.items():
        if n in qnames:
            w = np.asarray(a, np.float32)
            s = channel_scales(w, method="absmax")          # [n_out]
            out[n] = np.clip(np.round(w / s), -127, 127).astype(np.int8)
            out[n + "::scale"] = s
        else:
            out[n] = a
    return out


def gpt_kv_scales(sd, cfg: GPTConfig, prompts=None,
                  method: str = "quantile", quantile: float = 0.9995):
    """Calibrate per-(layer, head, channel) int8 scales for the KV
    cache: run the FULL-PRECISION prefill over calibration prompts on a
    one-slot slab, read back the K/V rows it wrote, and feed them
    through :func:`evaluation.calibration.channel_scales` (quantile
    clipping by default — K/V activations have outlier tails that
    absmax would let starve the int8 grid). Returns ``{"k": [L, A, D],
    "v": [L, A, D]}`` float32, the ``kv_scales`` contract of
    :func:`gpt_decode_fns` / :func:`gpt_paged_decode_fns`.

    ``prompts=None`` synthesizes a small deterministic prompt set —
    fine for smoke use; real deployments should pass prompts drawn
    from their actual traffic distribution (docs/serving.md "Decode
    speed")."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.evaluation.calibration import channel_scales

    names = gpt_param_names(cfg)
    params = {n: sd._arrays[n] for n in names}
    prefill_fn, _, _ = gpt_decode_fns(cfg)
    jit_prefill = jax.jit(prefill_fn)
    if prompts is None:
        rng = np.random.default_rng(0)
        span = min(32, cfg.max_seq_len - 1)
        prompts = [rng.integers(0, cfg.vocab_size, size=span)
                   for _ in range(4)]
    k_rows, v_rows = [], []
    for pr in prompts:
        pr = np.asarray(pr, np.int32).reshape(-1)
        Lp = int(pr.size)
        shape = (cfg.num_layers, 1, cfg.num_heads, Lp, cfg.head_size)
        kc, vc, _, _ = jit_prefill(
            params, jnp.zeros(shape, jnp.float32),
            jnp.zeros(shape, jnp.float32),
            {"tokens": pr, "length": np.int32(Lp), "slot": np.int32(0)})
        k_rows.append(np.asarray(kc)[:, 0])         # [L, A, Lp, D]
        v_rows.append(np.asarray(vc)[:, 0])

    def _scales(rows):
        obs = np.concatenate(rows, axis=2)          # [L, A, N, D]
        flat = np.transpose(obs, (2, 0, 1, 3)).reshape(obs.shape[2], -1)
        s = channel_scales(flat, method=method, quantile=quantile)
        return s.reshape(cfg.num_layers, cfg.num_heads, cfg.head_size)

    return {"k": _scales(k_rows), "v": _scales(v_rows)}


def _check_decode_params(sd, cfg: GPTConfig):
    names = gpt_param_names(cfg)
    missing = [n for n in names if n not in sd._arrays]
    if missing:
        raise ValueError(
            f"graph is missing decode parameters {missing[:4]}"
            f"{'...' if len(missing) > 4 else ''} — was it built by "
            f"zoo.gpt.build_gpt with this config?")
    return names


def _params_pull(sd, cfg: GPTConfig, names, quantize_weights: bool):
    if quantize_weights:
        return lambda: gpt_quantize_params(
            {n: sd._arrays[n] for n in names}, cfg)
    return lambda: {n: sd._arrays[n] for n in names}


def gpt_paged_spec(sd, cfg: GPTConfig, quantize_weights: bool = False,
                   quantize_kv: bool = False, calibration_prompts=None):
    """The PAGED decode-mode graph hook: a
    :class:`~deeplearning4j_tpu.serving.paged.PagedGenerativeSpec` over
    a trained :func:`build_gpt` graph — what
    ``serving.paged.PagedGenerativeServer`` consumes. Same by-name
    parameter sync as :func:`gpt_generative_spec`; the decode functions
    are built per (block_size, max_blocks_per_req) geometry by the
    server (and memoized, so every server over the same model and
    geometry shares one compile set).

    ``quantize_weights`` serves int8 weight payloads (4x fewer weight
    bytes per decode step); ``quantize_kv`` makes the BLOCK POOL int8 —
    ``kv_dtype`` flips to ``"int8"``, so the server's equal-byte pool
    holds 4x the f32 token capacity — with scales calibrated via
    :func:`gpt_kv_scales` over ``calibration_prompts``."""
    from deeplearning4j_tpu.serving.paged import PagedGenerativeSpec

    names = _check_decode_params(sd, cfg)
    kv_scales = gpt_kv_scales(sd, cfg, prompts=calibration_prompts) \
        if quantize_kv else None
    return PagedGenerativeSpec(
        params=_params_pull(sd, cfg, names, quantize_weights),
        make_fns=lambda block_size, max_blocks: gpt_paged_decode_fns(
            cfg, block_size, max_blocks,
            quantize_weights=quantize_weights, kv_scales=kv_scales),
        kv_shape=lambda num_blocks, block_size: (
            cfg.num_layers, int(num_blocks), cfg.num_heads,
            int(block_size), cfg.head_size),
        vocab_size=cfg.vocab_size,
        max_seq_len=cfg.max_seq_len,
        num_heads=cfg.num_heads,
        kv_dtype="int8" if quantize_kv else "float32")


def gpt_generative_spec(sd, cfg: GPTConfig, quantize_weights: bool = False,
                        quantize_kv: bool = False,
                        calibration_prompts=None):
    """The decode-mode graph hook: a
    :class:`~deeplearning4j_tpu.serving.generative.GenerativeSpec` over
    a trained :func:`build_gpt` graph — what
    ``serving.generative.GenerativeServer`` consumes. Parameters are
    pulled from the SameDiff BY NAME at sync time, so further ``fit()``
    followed by ``server.update_model()`` serves the new weights (the
    quantized pull re-quantizes them). The spec carries the verify
    program, so any server over it can act as a speculative-decoding
    TARGET; a second (smaller) spec passed as ``draft_spec=`` acts as
    the draft. ``quantize_weights`` / ``quantize_kv`` follow the
    :func:`gpt_paged_spec` contract (int8 payloads + ``kv_dtype``
    flip), with KV scales calibrated over ``calibration_prompts``."""
    from deeplearning4j_tpu.serving.generative import GenerativeSpec

    names = _check_decode_params(sd, cfg)
    kv_scales = gpt_kv_scales(sd, cfg, prompts=calibration_prompts) \
        if quantize_kv else None
    prefill_fn, decode_fn, verify_fn = gpt_decode_fns(
        cfg, quantize_weights=quantize_weights, kv_scales=kv_scales)
    return GenerativeSpec(
        params=_params_pull(sd, cfg, names, quantize_weights),
        prefill=prefill_fn,
        decode=decode_fn,
        kv_shape=lambda max_slots, max_seq: (
            cfg.num_layers, int(max_slots), cfg.num_heads, int(max_seq),
            cfg.head_size),
        vocab_size=cfg.vocab_size,
        max_seq_len=cfg.max_seq_len,
        kv_dtype="int8" if quantize_kv else "float32",
        verify=verify_fn)
