"""Model zoo (reference: deeplearning4j-zoo zoo/model/*.java).

Architecture definitions only — the reference's pretrained-weight download
machinery (ZooModel.initPretrained) is replaced by Keras/TF import and
checkpoint loading. Each model exposes ``build() -> network`` (initialized,
ready for fit/output), mirroring ZooModel.init().
"""
from deeplearning4j_tpu.zoo.models import (
    AlexNet, LeNet, ResNet50, SimpleCNN, TextGenLSTM, TransformerEncoder,
    VGG16)

__all__ = ["LeNet", "SimpleCNN", "AlexNet", "VGG16", "ResNet50",
           "TextGenLSTM", "TransformerEncoder"]
