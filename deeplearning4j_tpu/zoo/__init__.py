"""Model zoo (reference: deeplearning4j-zoo zoo/model/*.java).

Architecture definitions only — the reference's pretrained-weight download
machinery (ZooModel.initPretrained) is replaced by Keras/TF import and
checkpoint loading. Each model exposes ``build() -> network`` (initialized,
ready for fit/output), mirroring ZooModel.init().
"""
from deeplearning4j_tpu.zoo.models import (
    AlexNet, LeNet, ResNet50, SimpleCNN, TextGenLSTM, TransformerEncoder,
    VGG16)
from deeplearning4j_tpu.zoo.models_ext import (
    Darknet19, SqueezeNet, TinyYOLO, UNet, Xception)
from deeplearning4j_tpu.zoo.models_wave3 import (
    FaceNet, InceptionResNetV1, NASNet, VGG19, YOLO2)
from deeplearning4j_tpu.zoo.bert import BERT_BASE, BERT_TINY, BertConfig, bert_base
from deeplearning4j_tpu.zoo.gpt import GPT_MEDIUM, GPT_TINY, GPTConfig, build_gpt

__all__ = ["LeNet", "SimpleCNN", "AlexNet", "VGG16", "ResNet50",
           "TextGenLSTM", "TransformerEncoder", "SqueezeNet", "UNet",
           "Xception", "Darknet19", "TinyYOLO", "VGG19", "InceptionResNetV1",
           "FaceNet", "NASNet", "YOLO2", "BertConfig", "BERT_BASE",
           "BERT_TINY", "bert_base", "GPTConfig", "GPT_MEDIUM", "GPT_TINY",
           "build_gpt"]
