"""Data pipeline (reference: org.nd4j.linalg.dataset + deeplearning4j-data)."""
from deeplearning4j_tpu.dataset.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.dataset.iterators import (
    ArrayDataSetIterator, AsyncDataSetIterator, BenchmarkDataSetIterator,
    DataSetIterator, DeviceCachedIterator, EarlyTerminationIterator,
    ListDataSetIterator, MultipleEpochsIterator, SamplingDataSetIterator)
from deeplearning4j_tpu.dataset.normalizers import (
    ImagePreProcessingScaler, Normalizer, NormalizerMinMaxScaler,
    NormalizerStandardize)
from deeplearning4j_tpu.dataset.mnist import (
    MnistDataSetIterator, load_mnist, synthetic_mnist)
from deeplearning4j_tpu.dataset.vision import (
    Cifar10DataSetIterator, EmnistDataSetIterator, SvhnDataSetIterator,
    TinyImageNetDataSetIterator, load_cifar10, load_emnist, load_svhn,
    load_tiny_imagenet, synthetic_cifar10)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ArrayDataSetIterator",
    "ListDataSetIterator", "DeviceCachedIterator", "AsyncDataSetIterator",
    "BenchmarkDataSetIterator", "MultipleEpochsIterator",
    "EarlyTerminationIterator", "SamplingDataSetIterator", "Normalizer",
    "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler", "MnistDataSetIterator", "load_mnist",
    "synthetic_mnist", "Cifar10DataSetIterator", "EmnistDataSetIterator",
    "load_cifar10", "load_emnist", "synthetic_cifar10",
    "SvhnDataSetIterator", "TinyImageNetDataSetIterator", "load_svhn",
    "load_tiny_imagenet",
]
