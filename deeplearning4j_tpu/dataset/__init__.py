"""Data pipeline (reference: org.nd4j.linalg.dataset + deeplearning4j-data)."""
from deeplearning4j_tpu.dataset.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.dataset.iterators import (
    ArrayDataSetIterator, AsyncDataSetIterator, BenchmarkDataSetIterator,
    DataSetIterator, DeviceCachedIterator, EarlyTerminationIterator,
    ListDataSetIterator, MultipleEpochsIterator, SamplingDataSetIterator)
from deeplearning4j_tpu.dataset.normalizers import (
    ImagePreProcessingScaler, Normalizer, NormalizerMinMaxScaler,
    NormalizerStandardize)
from deeplearning4j_tpu.dataset.mnist import (
    MnistDataSetIterator, load_mnist, synthetic_mnist)

__all__ = [
    "DataSet", "MultiDataSet", "DataSetIterator", "ArrayDataSetIterator",
    "ListDataSetIterator", "DeviceCachedIterator", "AsyncDataSetIterator",
    "BenchmarkDataSetIterator", "MultipleEpochsIterator",
    "EarlyTerminationIterator", "SamplingDataSetIterator", "Normalizer",
    "NormalizerStandardize", "NormalizerMinMaxScaler",
    "ImagePreProcessingScaler", "MnistDataSetIterator", "load_mnist",
    "synthetic_mnist",
]
