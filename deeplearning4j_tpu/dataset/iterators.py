"""DataSet iterators.

Reference parity: org.nd4j.linalg.dataset.api.iterator.DataSetIterator and
the utility iterators (deeplearning4j-utility-iterators): Async prefetch
(AsyncDataSetIterator.java:32), Existing/List/INDArray iterators,
BenchmarkDataSetIterator, MultipleEpochsIterator, EarlyTermination,
Sampling.

TPU-native addition: DeviceCachedIterator — uploads the whole dataset to
HBM ONCE and yields device-resident slices, so the training loop's only
host↔device traffic is the dispatch stream. On a tunneled chip (or any
host-bottlenecked feed) this is the difference between transfer-bound and
compute-bound training; the reference's nearest analogue is workspace-
cached DataSets, which still live host-side.

For datasets that do NOT fit in HBM (or host RAM), the disk-backed
counterpart is ``datapipe.StreamingDataPipeline``: checksummed shard
directories, supervised parallel prefetch, and seekable mid-epoch
resume state — a DataSetIterator like everything here, so it drops into
any fit()/RetryingIterator/AsyncDataSetIterator composition
(docs/data_pipeline.md).
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.dataset.dataset import DataSet


class DataSetIterator:
    """Base protocol: iterable of (features, labels) or DataSet batches."""

    def reset(self) -> None: ...

    def __iter__(self):
        raise NotImplementedError

    def batch_size(self) -> Optional[int]:
        return getattr(self, "_batch", None)


class ArrayDataSetIterator(DataSetIterator):
    """Batches over in-memory arrays (reference: INDArrayDataSetIterator)."""

    def __init__(self, features, labels, batch_size: int = 32,
                 shuffle: bool = False, seed: Optional[int] = None):
        self.X = np.asarray(features)
        self.Y = np.asarray(labels)
        self._batch = batch_size
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        idx = np.arange(len(self.X))
        if self._shuffle:
            self._rng.shuffle(idx)
        for i in range(0, len(idx), self._batch):
            j = idx[i:i + self._batch]
            yield self.X[j], self.Y[j]


class ListDataSetIterator(DataSetIterator):
    """Iterates a list of DataSets (reference: ListDataSetIterator)."""

    def __init__(self, datasets: Sequence[DataSet], batch_size: Optional[int] = None):
        if batch_size is not None:
            merged = DataSet.merge(list(datasets))
            datasets = merged.batch_by(batch_size)
        self._datasets = list(datasets)
        self._batch = batch_size

    def __iter__(self):
        for d in self._datasets:
            yield d.features, d.labels


class DeviceCachedIterator(DataSetIterator):
    """Uploads features/labels to device(s) once; yields device slices.

    With a sharding, data lands pre-sharded over the mesh (the 'data' axis)
    and every epoch's batches are zero-copy views of HBM.
    """

    def __init__(self, features, labels, batch_size: int = 32, sharding=None):
        import jax
        import jax.numpy as jnp
        def _is_multi(v):
            # multi-input = a list/tuple OF ARRAYS; nested python lists
            # (e.g. [[1., 2.], [3., 4.]]) stay a single 2-d array exactly
            # as np.asarray always treated them
            return isinstance(v, (list, tuple)) and len(v) > 0 and \
                all(hasattr(e, "ndim") for e in v)

        self._multi_f = _is_multi(features)
        self._multi_l = _is_multi(labels)
        feats = [np.asarray(f) for f in features] if self._multi_f \
            else [np.asarray(features)]
        labs = [np.asarray(l) for l in labels] if self._multi_l \
            else [np.asarray(labels)]
        lens = {len(a) for a in feats + labs}
        if len(lens) != 1:
            raise ValueError(
                f"all feature/label arrays must share the leading length; "
                f"got {[len(a) for a in feats]} / {[len(a) for a in labs]}")
        n = (len(feats[0]) // batch_size) * batch_size
        if n == 0:
            raise ValueError("dataset smaller than one batch")
        self._batch = batch_size
        self._n = n

        def _put(a):
            return jax.device_put(a[:n], sharding) if sharding is not None \
                else jnp.asarray(a[:n])

        self.Xs = [_put(f) for f in feats]
        self.Ys = [_put(l) for l in labs]

    # single-input views (back-compat)
    @property
    def X(self):
        return self.Xs[0]

    @property
    def Y(self):
        return self.Ys[0]

    def __iter__(self):
        for i in range(0, self._n, self._batch):
            fs = [x[i:i + self._batch] for x in self.Xs]
            ls = [y[i:i + self._batch] for y in self.Ys]
            yield (fs if self._multi_f else fs[0],
                   ls if self._multi_l else ls[0])

    def stacked_batches(self):
        """Device-resident batches stacked on a leading steps axis —
        feeds SameDiff's scanned whole-epoch train step (([X...], [Y...])
        with each array of shape (steps, batch, ...))."""
        steps = self._n // self._batch

        def _stk(a):
            return a.reshape(steps, self._batch, *a.shape[1:])

        return [_stk(x) for x in self.Xs], [_stk(y) for y in self.Ys]


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch (reference: AsyncDataSetIterator.java:32,
    wrapped around fit() inputs at MultiLayerNetwork.java:1678).

    Shutdown-safe: the worker uses a bounded put that polls a stop flag,
    and the consumer's ``finally`` (run on normal exhaustion AND on
    ``GeneratorExit`` when a consumer abandons the generator mid-epoch)
    sets the flag, drains the queue, and joins the thread — an abandoned
    iteration can no longer strand a daemon thread blocked on ``q.put``
    forever.

    Worker-thread failures travel IN the stream: the worker enqueues a
    poisoned sentinel carrying the exception and the index of the batch
    that failed to materialize, and the consumer re-raises it — in
    stream order, after the batches that preceded it — as a structured
    ``faults.DataPipelineError`` (the original exception chained as
    ``__cause__``). An epoch can no longer end silently short, and the
    recovery rail learns WHICH batch died."""

    _END = object()

    def __init__(self, wrapped: DataSetIterator, queue_size: int = 4):
        self._wrapped = wrapped
        self._queue_size = queue_size
        self._last_thread: Optional[threading.Thread] = None  # test hook

    def reset(self):
        if hasattr(self._wrapped, "reset"):
            self._wrapped.reset()

    def __iter__(self):
        q: "queue.Queue" = queue.Queue(maxsize=self._queue_size)
        stop = threading.Event()

        class _Poison:
            def __init__(self, error: BaseException, batch_index: int):
                self.error = error
                self.batch_index = batch_index

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            index = 0                   # batch currently being produced
            try:
                for item in self._wrapped:
                    if not put(item):
                        return          # consumer gone
                    index += 1
            except BaseException as e:   # poisoned sentinel, in-stream
                put(_Poison(e, index))
                return
            finally:
                put(self._END)

        t = threading.Thread(target=worker, daemon=True)
        self._last_thread = t
        t.start()
        poison: List[_Poison] = []
        try:
            while True:
                item = q.get()
                if item is self._END:
                    break
                if isinstance(item, _Poison):
                    poison.append(item)
                    break
                yield item
        finally:
            stop.set()
            while True:                  # unblock a worker stuck on put
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)
        if poison:
            from deeplearning4j_tpu.faults.errors import DataPipelineError
            p = poison[0]
            raise DataPipelineError(
                f"async prefetch worker failed producing batch "
                f"{p.batch_index}: {p.error!r}",
                batch_index=p.batch_index,
                cause="async_worker") from p.error


class BenchmarkDataSetIterator(DataSetIterator):
    """Synthetic fixed batches (reference: BenchmarkDataSetIterator.java —
    same batch object yielded n times; measures pure train throughput).

    ``device_cached=True`` uploads the one batch to HBM ONCE and yields
    the resident array every step — without it, every step pays a
    redundant host→device transfer of identical bytes, and a dispatch-
    bound benchmark measures the PCIe/tunnel instead of the model.
    ``stacked_batches()`` additionally exposes the scanned-tier
    contract: the batch broadcast along a leading steps axis. NOTE the
    broadcast is committed to HBM (n_batches × batch bytes — XLA needs
    a concrete scan operand); for step counts where that doesn't fit,
    keep ``device_cached=False`` and train through the fused-window
    tier (``fused_steps``), whose stager stages K batches at a time."""

    def __init__(self, feature_shape: Sequence[int], n_classes: int,
                 n_batches: int, seed: int = 0, regression: bool = False,
                 device_cached: bool = False):
        rng = np.random.default_rng(seed)
        self._X = rng.normal(size=tuple(feature_shape)).astype(np.float32)
        if regression:
            self._Y = rng.normal(size=(feature_shape[0], n_classes)).astype(np.float32)
        else:
            self._Y = np.eye(n_classes, dtype=np.float32)[
                rng.integers(0, n_classes, feature_shape[0])]
        self._n = n_batches
        self._batch = feature_shape[0]
        self._device_cached = device_cached
        self._dev = None
        if device_cached:
            # the scanned tier routes on hasattr(it, "stacked_batches"),
            # so the method is exposed per-instance, only in cached mode
            self.stacked_batches = self._stacked_batches

    def _device_batch(self):
        if self._dev is None:
            import jax.numpy as jnp
            self._dev = (jnp.asarray(self._X), jnp.asarray(self._Y))
        return self._dev

    def __iter__(self):
        if self._device_cached:
            X, Y = self._device_batch()
        else:
            X, Y = self._X, self._Y
        for _ in range(self._n):
            yield X, Y

    def _stacked_batches(self):
        """Scanned-tier contract (see DeviceCachedIterator): the single
        batch broadcast to (n_batches, batch, ...) on device."""
        import jax.numpy as jnp
        X, Y = self._device_batch()
        return ([jnp.broadcast_to(X[None], (self._n, *X.shape))],
                [jnp.broadcast_to(Y[None], (self._n, *Y.shape))])


class MultipleEpochsIterator(DataSetIterator):
    """Replays the wrapped iterator N times as one pass (reference:
    MultipleEpochsIterator)."""

    def __init__(self, wrapped: DataSetIterator, n_epochs: int):
        self._wrapped = wrapped
        self._n = n_epochs

    def reset(self):
        if hasattr(self._wrapped, "reset"):
            self._wrapped.reset()

    def __iter__(self):
        for _ in range(self._n):
            self.reset()
            yield from self._wrapped


class EarlyTerminationIterator(DataSetIterator):
    """Caps batches per pass (reference: EarlyTerminationDataSetIterator)."""

    def __init__(self, wrapped: DataSetIterator, max_batches: int):
        self._wrapped = wrapped
        self._max = max_batches

    def reset(self):
        if hasattr(self._wrapped, "reset"):
            self._wrapped.reset()

    def __iter__(self):
        for i, item in enumerate(self._wrapped):
            if i >= self._max:
                break
            yield item


class SamplingDataSetIterator(DataSetIterator):
    """Random with-replacement batches (reference: SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch_size: int, n_batches: int,
                 seed: Optional[int] = None):
        self._ds = dataset
        self._batch = batch_size
        self._n = n_batches
        self._rng = np.random.default_rng(seed)

    def __iter__(self):
        for _ in range(self._n):
            idx = self._rng.integers(0, self._ds.num_examples(), self._batch)
            yield self._ds.features[idx], self._ds.labels[idx]
