"""MNIST / EMNIST-style idx dataset loading.

Reference parity: deeplearning4j-datasets MnistDataSetIterator
(datasets/iterator/impl/MnistDataSetIterator.java) + the idx-file fetchers.
This environment has no network egress, so the loader reads idx files from
a directory when present (``MNIST_DIR`` env var or explicit path, same
ubyte file names the reference downloads) and otherwise falls back to a
deterministic synthetic digit set (class-dependent strokes) so examples,
tests and benchmarks run hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.dataset.dataset import DataSet
from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator

_FILES = {
    "train_images": "train-images-idx3-ubyte",
    "train_labels": "train-labels-idx1-ubyte",
    "test_images": "t10k-images-idx3-ubyte",
    "test_labels": "t10k-labels-idx1-ubyte",
}


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">HBB", f.read(4))
        _, dtype_code, ndim = magic
        dims = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(dims)


def _find(dir_: str, base: str) -> Optional[str]:
    for cand in (base, base + ".gz"):
        p = os.path.join(dir_, cand)
        if os.path.exists(p):
            return p
    return None


def synthetic_mnist(n: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic learnable digit-like data: each class is a distinct
    bright 7x7 patch pattern + noise."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    X = rng.normal(0.1, 0.05, size=(n, 1, 28, 28)).astype(np.float32)
    for c in range(10):
        r, col = divmod(c, 4)
        mask = labels == c
        X[mask, 0, 7 * r:7 * r + 7, 7 * col:7 * col + 7] += 0.8
    return np.clip(X, 0, 1), labels.astype(np.int64)


def load_mnist(train: bool = True, data_dir: Optional[str] = None,
               n_synthetic: int = 8192):
    """(features NCHW float32 in [0,1], int labels). Real data when idx
    files exist, synthetic otherwise."""
    data_dir = data_dir or os.environ.get("MNIST_DIR", "/root/data/mnist")
    key = "train" if train else "test"
    img = _find(data_dir, _FILES[f"{key}_images"]) if os.path.isdir(data_dir) else None
    lab = _find(data_dir, _FILES[f"{key}_labels"]) if os.path.isdir(data_dir) else None
    if img and lab:
        X = _read_idx(img).astype(np.float32)[:, None, :, :] / 255.0
        y = _read_idx(lab).astype(np.int64)
        return X, y
    return synthetic_mnist(n_synthetic if train else n_synthetic // 4,
                           seed=0 if train else 1)


class MnistDataSetIterator(ArrayDataSetIterator):
    """Reference: MnistDataSetIterator(batch, train) — yields
    (features (B,1,28,28), one-hot labels (B,10))."""

    def __init__(self, batch_size: int = 128, train: bool = True,
                 shuffle: bool = True, seed: int = 6,
                 data_dir: Optional[str] = None, n_synthetic: int = 8192):
        X, y = load_mnist(train=train, data_dir=data_dir,
                          n_synthetic=n_synthetic)
        Y = np.eye(10, dtype=np.float32)[y]
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)
        self.raw_labels = y
