"""DataSet / MultiDataSet containers.

Reference parity: org.nd4j.linalg.dataset.DataSet (features+labels+masks,
shuffle/split/batchBy/save-load) and MultiDataSet (multi-input/output).
Arrays are host numpy until they enter a training step — the device feed
is the iterator's job (device-cached/prefetch iterators in iterators.py).
"""
from __future__ import annotations

import io
import zipfile
from typing import List, Optional, Sequence, Tuple

import numpy as np


class DataSet:
    def __init__(self, features, labels, features_mask=None, labels_mask=None):
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self.features_mask = None if features_mask is None else np.asarray(features_mask)
        self.labels_mask = None if labels_mask is None else np.asarray(labels_mask)

    # ------------------------------------------------------------------
    def num_examples(self) -> int:
        return len(self.features)

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        idx = np.random.default_rng(seed).permutation(self.num_examples())
        return self._take(idx)

    def _take(self, idx) -> "DataSet":
        return DataSet(
            self.features[idx], self.labels[idx],
            None if self.features_mask is None else self.features_mask[idx],
            None if self.labels_mask is None else self.labels_mask[idx])

    def split_test_and_train(self, frac_train: float,
                             seed: Optional[int] = None
                             ) -> Tuple["DataSet", "DataSet"]:
        """(train, test) split (reference: DataSet.splitTestAndTrain)."""
        n = self.num_examples()
        idx = np.random.default_rng(seed).permutation(n) if seed is not None \
            else np.arange(n)
        k = int(round(n * frac_train))
        return self._take(idx[:k]), self._take(idx[k:])

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        return [self._take(slice(i, i + batch_size))
                for i in range(0, self.num_examples(), batch_size)]

    def sample(self, n: int, seed: Optional[int] = None) -> "DataSet":
        idx = np.random.default_rng(seed).choice(self.num_examples(), n,
                                                 replace=False)
        return self._take(idx)

    # ------------------------------------------------------------------
    def save(self, path) -> None:
        arrays = {"features": self.features, "labels": self.labels}
        if self.features_mask is not None:
            arrays["features_mask"] = self.features_mask
        if self.labels_mask is not None:
            arrays["labels_mask"] = self.labels_mask
        np.savez_compressed(path, **arrays)

    @staticmethod
    def load(path) -> "DataSet":
        with np.load(path) as npz:
            return DataSet(npz["features"], npz["labels"],
                           npz.get("features_mask"), npz.get("labels_mask"))

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]))

    def __repr__(self):
        return (f"DataSet(features={self.features.shape}, "
                f"labels={self.labels.shape})")


class MultiDataSet:
    """Multi-input/output container (reference:
    org.nd4j.linalg.dataset.MultiDataSet)."""

    def __init__(self, features: Sequence, labels: Sequence,
                 features_masks=None, labels_masks=None):
        self.features = [np.asarray(f) for f in features]
        self.labels = [np.asarray(l) for l in labels]
        self.features_masks = features_masks
        self.labels_masks = labels_masks

    def num_examples(self) -> int:
        return len(self.features[0])

    def __repr__(self):
        return (f"MultiDataSet(features={[f.shape for f in self.features]}, "
                f"labels={[l.shape for l in self.labels]})")
