"""CIFAR-10 / EMNIST dataset loading.

Reference parity: deeplearning4j-datasets Cifar10DataSetIterator +
EmnistDataSetIterator (datasets/iterator/impl/). Same hermetic policy as
mnist.py: real files when a data directory is present (the exact formats
the reference downloads — CIFAR-10 python pickle batches, EMNIST idx
files), deterministic synthetic fallback otherwise.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.dataset.iterators import ArrayDataSetIterator
from deeplearning4j_tpu.dataset.mnist import _find, _read_idx

CIFAR10_LABELS = ["airplane", "automobile", "bird", "cat", "deer", "dog",
                  "frog", "horse", "ship", "truck"]

# EMNIST splits and class counts (reference: EmnistDataSetIterator.Set)
EMNIST_SETS = {"balanced": 47, "byclass": 62, "bymerge": 47, "digits": 10,
               "letters": 26, "mnist": 10}


def synthetic_cifar10(n: int, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic 32x32 RGB: class-dependent color blocks."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n)
    X = rng.normal(0.35, 0.1, size=(n, 3, 32, 32)).astype(np.float32)
    for c in range(10):
        mask = labels == c
        ch = c % 3
        r, col = divmod(c, 4)
        X[mask, ch, 8 * r:8 * r + 8, 8 * col:8 * col + 8] += 0.5
    return np.clip(X, 0, 1), labels.astype(np.int64)


def load_cifar10(train: bool = True, data_dir: Optional[str] = None,
                 n_synthetic: int = 4096):
    """(features NCHW float32 in [0,1], int labels). Reads the stock
    cifar-10-batches-py pickles when present."""
    data_dir = data_dir or os.environ.get("CIFAR10_DIR",
                                          "/root/data/cifar10")
    batch_dir = os.path.join(data_dir, "cifar-10-batches-py")
    if not os.path.isdir(batch_dir):
        batch_dir = data_dir
    names = [f"data_batch_{i}" for i in range(1, 6)] if train \
        else ["test_batch"]
    paths = [os.path.join(batch_dir, n) for n in names]
    if all(os.path.exists(p) for p in paths):
        xs, ys = [], []
        for p in paths:
            with open(p, "rb") as fh:
                d = pickle.load(fh, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.uint8))
            ys.extend(d[b"labels"])
        X = (np.concatenate(xs).reshape(-1, 3, 32, 32).astype(np.float32)
             / 255.0)
        return X, np.asarray(ys, np.int64)
    return synthetic_cifar10(n_synthetic if train else n_synthetic // 4,
                             seed=0 if train else 1)


def load_emnist(split: str = "balanced", train: bool = True,
                data_dir: Optional[str] = None, n_synthetic: int = 4096):
    """(features NCHW float32, int labels) for an EMNIST split; idx files
    named emnist-<split>-{train,test}-{images-idx3,labels-idx1}-ubyte."""
    if split not in EMNIST_SETS:
        raise ValueError(f"unknown EMNIST split {split!r}; "
                         f"have {sorted(EMNIST_SETS)}")
    data_dir = data_dir or os.environ.get("EMNIST_DIR", "/root/data/emnist")
    key = "train" if train else "test"
    img = lab = None
    if os.path.isdir(data_dir):
        img = _find(data_dir, f"emnist-{split}-{key}-images-idx3-ubyte")
        lab = _find(data_dir, f"emnist-{split}-{key}-labels-idx1-ubyte")
    if img and lab:
        X = _read_idx(img).astype(np.float32)[:, None, :, :] / 255.0
        y = _read_idx(lab).astype(np.int64)
        # EMNIST 'letters' labels are 1-based in the source files
        if split == "letters":
            y = y - 1
        return X, y
    n_classes = EMNIST_SETS[split]
    rng = np.random.default_rng(2 if train else 3)
    n = n_synthetic if train else n_synthetic // 4
    labels = rng.integers(0, n_classes, n)
    X = rng.normal(0.1, 0.05, size=(n, 1, 28, 28)).astype(np.float32)
    for c in range(n_classes):
        mask = labels == c
        r, col = divmod(c % 16, 4)
        X[mask, 0, 7 * r:7 * r + 6, 7 * col:7 * col + 6] += \
            0.5 + 0.4 * (c // 16)
    return np.clip(X, 0, 1), labels.astype(np.int64)


class Cifar10DataSetIterator(ArrayDataSetIterator):
    """Reference: Cifar10DataSetIterator(batch) — (B,3,32,32) + one-hot."""

    def __init__(self, batch_size: int = 128, train: bool = True,
                 shuffle: bool = True, seed: int = 6,
                 data_dir: Optional[str] = None, n_synthetic: int = 4096):
        X, y = load_cifar10(train=train, data_dir=data_dir,
                            n_synthetic=n_synthetic)
        Y = np.eye(10, dtype=np.float32)[y]
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)
        self.raw_labels = y


class EmnistDataSetIterator(ArrayDataSetIterator):
    """Reference: EmnistDataSetIterator(set, batch, train)."""

    def __init__(self, split: str = "balanced", batch_size: int = 128,
                 train: bool = True, shuffle: bool = True, seed: int = 6,
                 data_dir: Optional[str] = None, n_synthetic: int = 4096):
        X, y = load_emnist(split, train=train, data_dir=data_dir,
                           n_synthetic=n_synthetic)
        n_classes = EMNIST_SETS[split]
        Y = np.eye(n_classes, dtype=np.float32)[y]
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)
        self.raw_labels = y
        self.num_classes = n_classes


# ---------------------------------------------------------------------------
# SVHN / Tiny ImageNet (reference: datasets/fetchers/SvhnDataFetcher +
# TinyImageNetDataSetIterator, deeplearning4j-datasets)

def synthetic_rgb(n: int, size: int, n_classes: int, seed: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Learnable synthetic RGB: class-dependent color patches."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, n_classes, n)
    X = rng.normal(0.35, 0.1, size=(n, 3, size, size)).astype(np.float32)
    q = max(size // 4, 1)
    for c in range(n_classes):
        mask = labels == c
        ch = c % 3
        r, col = divmod((c // 3) % 16, 4)
        X[mask, ch, q * (r % 4):q * (r % 4) + q,
          q * (col % 4):q * (col % 4) + q] += 0.5
    return np.clip(X, 0, 1), labels.astype(np.int64)


def load_svhn(train: bool = True, data_dir: Optional[str] = None,
              n_synthetic: int = 4096):
    """Street View House Numbers, cropped-digit format (reference:
    SvhnDataFetcher — {train,test}_32x32.mat). Returns (NCHW float32 in
    [0,1], int labels 0-9); label '10' in the source files means digit 0."""
    data_dir = data_dir or os.environ.get("SVHN_DIR", "/root/data/svhn")
    name = ("train" if train else "test") + "_32x32.mat"
    path = os.path.join(data_dir, name)
    if os.path.exists(path):
        try:
            from scipy.io import loadmat
        except ImportError as e:
            # never silently substitute synthetic data for present files
            raise RuntimeError(
                f"SVHN file {path} exists but scipy is unavailable to "
                f"decode it") from e
        d = loadmat(path)
        X = (d["X"].transpose(3, 2, 0, 1).astype(np.float32) / 255.0)
        y = d["y"].reshape(-1).astype(np.int64) % 10
        return X, y
    return synthetic_rgb(n_synthetic if train else n_synthetic // 4,
                         32, 10, seed=4 if train else 5)


def load_tiny_imagenet(train: bool = True, data_dir: Optional[str] = None,
                       n_synthetic: int = 2048, n_classes: int = 200):
    """Tiny ImageNet-200, 64x64 (reference: TinyImageNetDataSetIterator /
    TinyImageNetFetcher). Directory layout: tiny-imagenet-200/train/<wnid>/
    images/*.JPEG and val/ with val_annotations.txt."""
    data_dir = data_dir or os.environ.get("TINY_IMAGENET_DIR",
                                          "/root/data/tiny-imagenet")
    root = os.path.join(data_dir, "tiny-imagenet-200")
    if not os.path.isdir(root):
        root = data_dir
    wnids_file = os.path.join(root, "wnids.txt")
    if os.path.exists(wnids_file):
        try:
            from PIL import Image
        except ImportError as e:
            raise RuntimeError(
                f"Tiny ImageNet tree at {root} exists but PIL is "
                f"unavailable to decode it") from e
        with open(wnids_file) as fh:
            wnids = [w.strip() for w in fh if w.strip()]
        table = {w: i for i, w in enumerate(wnids)}
        # accumulate uint8 (4x smaller than float32); scale once at the end
        xs, ys = [], []
        if train:
            for w in wnids:
                d = os.path.join(root, "train", w, "images")
                if not os.path.isdir(d):
                    continue
                for f in sorted(os.listdir(d)):
                    img = Image.open(os.path.join(d, f)).convert("RGB")
                    xs.append(np.asarray(img, np.uint8))
                    ys.append(table[w])
        else:
            ann = os.path.join(root, "val", "val_annotations.txt")
            if os.path.exists(ann):
                with open(ann) as fh:
                    for line in fh:
                        parts = line.split("\t")
                        if len(parts) < 2:
                            continue
                        p = os.path.join(root, "val", "images", parts[0])
                        img = Image.open(p).convert("RGB")
                        xs.append(np.asarray(img, np.uint8))
                        ys.append(table[parts[1]])
        if xs:
            X = (np.stack(xs).transpose(0, 3, 1, 2).astype(np.float32)
                 / 255.0)
            return X, np.asarray(ys, np.int64)
    return synthetic_rgb(n_synthetic if train else n_synthetic // 4,
                         64, n_classes, seed=6 if train else 7)


class SvhnDataSetIterator(ArrayDataSetIterator):
    """Reference: SvhnDataFetcher-backed iterator — (B,3,32,32) + one-hot."""

    def __init__(self, batch_size: int = 128, train: bool = True,
                 shuffle: bool = True, seed: int = 6,
                 data_dir: Optional[str] = None, n_synthetic: int = 4096):
        X, y = load_svhn(train=train, data_dir=data_dir,
                         n_synthetic=n_synthetic)
        Y = np.eye(10, dtype=np.float32)[y]
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)
        self.raw_labels = y


class TinyImageNetDataSetIterator(ArrayDataSetIterator):
    """Reference: TinyImageNetDataSetIterator — (B,3,64,64) + one-hot 200."""

    def __init__(self, batch_size: int = 128, train: bool = True,
                 shuffle: bool = True, seed: int = 6,
                 data_dir: Optional[str] = None, n_synthetic: int = 2048,
                 n_classes: int = 200):
        X, y = load_tiny_imagenet(train=train, data_dir=data_dir,
                                  n_synthetic=n_synthetic,
                                  n_classes=n_classes)
        Y = np.eye(n_classes, dtype=np.float32)[y]
        super().__init__(X, Y, batch_size=batch_size, shuffle=shuffle,
                         seed=seed)
        self.raw_labels = y
        self.num_classes = n_classes
