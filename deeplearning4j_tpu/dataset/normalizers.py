"""Data normalizers.

Reference parity: org.nd4j.linalg.dataset.api.preprocessor —
NormalizerStandardize (z-score), NormalizerMinMaxScaler,
ImagePreProcessingScaler (pixel /255 into [a,b]). Same fit/transform/
revert contract incl. fit(iterator) streaming statistics; serde to npz.
"""
from __future__ import annotations

from typing import Optional

import numpy as np


class Normalizer:
    def fit(self, data) -> "Normalizer":
        """Accepts an array, DataSet, or iterator of batches."""
        it = self._as_feature_batches(data)
        self._fit_batches(it)
        return self

    @staticmethod
    def _as_feature_batches(data):
        from deeplearning4j_tpu.dataset.dataset import DataSet
        if isinstance(data, DataSet):
            return [data.features]
        if isinstance(data, np.ndarray):
            return [data]
        def gen():
            for batch in data:
                if isinstance(batch, DataSet):
                    yield batch.features
                elif isinstance(batch, (tuple, list)):
                    yield np.asarray(batch[0])
                else:
                    yield np.asarray(batch)
        return gen()

    def _fit_batches(self, batches):
        raise NotImplementedError

    def transform(self, features):
        raise NotImplementedError

    def revert(self, features):
        raise NotImplementedError

    def preprocess(self, dataset) -> None:
        """In-place DataSet transform (reference: preProcess(DataSet))."""
        dataset.features = self.transform(dataset.features)

    def save(self, path) -> None:
        np.savez(path, __class__=type(self).__name__, **self._state())

    @staticmethod
    def load(path) -> "Normalizer":
        with np.load(path, allow_pickle=False) as npz:
            cls = {c.__name__: c for c in
                   [NormalizerStandardize, NormalizerMinMaxScaler,
                    ImagePreProcessingScaler]}[str(npz["__class__"])]
            obj = cls.__new__(cls)
            obj._load_state(npz)
            return obj


class NormalizerStandardize(Normalizer):
    """Per-feature z-score over the batch axis (reference:
    NormalizerStandardize; streaming via Welford-style moment sums)."""

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def _fit_batches(self, batches):
        n, s, s2 = 0, 0.0, 0.0
        for f in batches:
            f = np.asarray(f, np.float64)
            flat = f.reshape(len(f), -1)
            n += len(flat)
            s = s + flat.sum(0)
            s2 = s2 + (flat ** 2).sum(0)
        mean = s / n
        var = np.maximum(s2 / n - mean ** 2, 0.0)
        self.mean = mean
        self.std = np.sqrt(var)
        self.std[self.std == 0] = 1.0

    def transform(self, features):
        f = np.asarray(features)
        shape = f.shape
        out = (f.reshape(len(f), -1) - self.mean) / self.std
        return out.reshape(shape).astype(f.dtype if
                                         np.issubdtype(f.dtype, np.floating)
                                         else np.float32)

    def revert(self, features):
        f = np.asarray(features)
        shape = f.shape
        out = f.reshape(len(f), -1) * self.std + self.mean
        return out.reshape(shape)

    def _state(self):
        return {"mean": self.mean, "std": self.std}

    def _load_state(self, npz):
        self.mean = npz["mean"]
        self.std = npz["std"]


class NormalizerMinMaxScaler(Normalizer):
    """Scale each feature to [min_range, max_range] (reference:
    NormalizerMinMaxScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        self.min_range = min_range
        self.max_range = max_range
        self.data_min: Optional[np.ndarray] = None
        self.data_max: Optional[np.ndarray] = None

    def _fit_batches(self, batches):
        lo, hi = None, None
        for f in batches:
            flat = np.asarray(f, np.float64).reshape(len(f), -1)
            bmin, bmax = flat.min(0), flat.max(0)
            lo = bmin if lo is None else np.minimum(lo, bmin)
            hi = bmax if hi is None else np.maximum(hi, bmax)
        self.data_min, self.data_max = lo, hi

    def _scale(self):
        rng = self.data_max - self.data_min
        rng[rng == 0] = 1.0
        return rng

    def transform(self, features):
        f = np.asarray(features)
        shape = f.shape
        x = (f.reshape(len(f), -1) - self.data_min) / self._scale()
        out = x * (self.max_range - self.min_range) + self.min_range
        return out.reshape(shape).astype(np.float32)

    def revert(self, features):
        f = np.asarray(features)
        shape = f.shape
        x = (f.reshape(len(f), -1) - self.min_range) / \
            (self.max_range - self.min_range)
        out = x * self._scale() + self.data_min
        return out.reshape(shape)

    def _state(self):
        return {"data_min": self.data_min, "data_max": self.data_max,
                "range": np.array([self.min_range, self.max_range])}

    def _load_state(self, npz):
        self.data_min = npz["data_min"]
        self.data_max = npz["data_max"]
        self.min_range, self.max_range = npz["range"]


class ImagePreProcessingScaler(Normalizer):
    """Pixel scaling x/255 → [a, b] (reference: ImagePreProcessingScaler)."""

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0,
                 max_pixel: float = 255.0):
        self.min_range = min_range
        self.max_range = max_range
        self.max_pixel = max_pixel

    def _fit_batches(self, batches):
        pass  # stateless

    def fit(self, data):
        return self

    def transform(self, features):
        f = np.asarray(features, np.float32)
        return f / self.max_pixel * (self.max_range - self.min_range) \
            + self.min_range

    def revert(self, features):
        f = np.asarray(features)
        return (f - self.min_range) / (self.max_range - self.min_range) \
            * self.max_pixel

    def _state(self):
        return {"params": np.array([self.min_range, self.max_range,
                                    self.max_pixel])}

    def _load_state(self, npz):
        self.min_range, self.max_range, self.max_pixel = npz["params"]
