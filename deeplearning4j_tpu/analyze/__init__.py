"""analyze/ — pre-compile static analysis of SameDiff graphs and
TrainingConfigs.

Reference parity: DL4J's ``OpValidation`` + SameDiff shape-inference
checks (PAPER.md layer map L3) front-load graph validation so user
errors surface as named diagnostics instead of native-runtime crashes.
Here the native runtime is XLA: a wrong shape, a bf16 accumulation, or
a ShardingSpec that cannot bind otherwise dies inside jit with a
traceback naming none of the user's variables. The analyzer walks the
graph + config **without compiling or executing** — abstract
``jax.eval_shape`` per op, pure config checks — and emits structured
:class:`Finding`\\ s (rule id, severity, variable/op provenance, fix
hint).

Entry points:

- ``SameDiff.fit()`` / ``SameDiff.precompile()`` run
  :func:`analyze_training` automatically (``TrainingConfig.analyze``:
  ``True`` = warn on errors and proceed, ``"strict"`` = raise
  :class:`GraphAnalysisError` before any compile, ``False`` = off);
- ``ParallelInference(analyze=...)`` runs :func:`analyze_inference`
  over the serving graph at construction;
- ``python -m deeplearning4j_tpu.analyze model.zip`` lints a
  serialized model + config from the command line;
- findings publish as ``{"type": "analysis"}`` records
  (``AnalysisReport.to_record``) rendered by ui/report's "Static
  analysis" panel and folded into ``dl4j_analysis_*`` metrics.

Rule catalog + severities + the strict-mode contract:
docs/static_analysis.md.
"""
from __future__ import annotations

import time as _time
from typing import Optional, Sequence

from deeplearning4j_tpu.analyze.findings import (RULES, SEVERITIES,
                                                 AnalysisReport, Finding,
                                                 GraphAnalysisError,
                                                 GraphAnalysisWarning,
                                                 Rule, finding)
from deeplearning4j_tpu.analyze import configpass, graphpass, numerics
from deeplearning4j_tpu.analyze.servingpass import (
    analyze_fleet_config, analyze_generative_config,
    analyze_speculation_config)


def _graph_size(sd):
    return len(sd._vars), len(sd._ops)


#: rules the inference (serving) analysis actually runs — no config
#: rules (no TrainingConfig), no loss/dead-loss/CE-tail checks (a
#: serving graph legitimately leaves its training half unreached).
#: rules_run in a report counts EXECUTED rules, not the catalog.
_INFERENCE_RULES = frozenset({
    "graph.shape_mismatch", "graph.undefined_input",
    "graph.unused_placeholder", "graph.name_shadowing",
    "graph.state_alias", "numerics.lowp_loss_accum",
    "numerics.lowp_reduction", "numerics.unguarded_log",
    "numerics.unguarded_div"})

_CONFIG_RULES = frozenset(r for r in RULES if r.startswith("config."))

#: serving-capacity rules (analyze/servingpass.py) run only under
#: :func:`analyze_generative_config` / :func:`analyze_fleet_config` /
#: :func:`analyze_speculation_config` — never part of a training or
#: graph-inference report's executed-rule count.
_SERVING_RULES = frozenset(r for r in RULES if r.startswith("serving."))


def analyze_training(sd, tc=None, has_listeners: Optional[bool] = None,
                     device_count: Optional[int] = None,
                     batch_size: Optional[int] = None,
                     context: str = "fit") -> AnalysisReport:
    """Full analysis of a training graph + config: shape/dtype
    inference over the loss subgraph, graph hygiene, numerics hazards
    under the config's MixedPrecision policy, and config/composition
    lint. Never compiles, never touches a device.

    ``has_listeners`` is the fit-context bit (None = unknown, e.g.
    precompile) consulted by the tensorstats-unobserved knob check;
    ``device_count`` bounds the sharding checks (None = skip the
    device-divisibility half)."""
    t0 = _time.perf_counter()
    tc = tc if tc is not None else sd.training_config
    report = AnalysisReport(context=context)
    report.n_vars, report.n_ops = _graph_size(sd)
    # executed-rule count, not the catalog size: with no config the 8
    # config rules are skipped, and claiming they ran would read as
    # "config lint passed" on a record where it never executed
    report.rules_run = (len(RULES) - len(_SERVING_RULES)
                        - (len(_CONFIG_RULES) if tc is None else 0))

    # resolve the analysis outputs the way the train step will
    loss_names: Sequence[str] = ()
    try:
        loss_names = sd._resolve_loss()
    except ValueError as e:
        report.add(finding(
            "graph.invalid_loss", "loss_variables", str(e),
            fix_hint="set_loss_variables() before training"))
    outputs = tuple(loss_names) + tuple(sd._state_updates.values())
    if not outputs:
        outputs = tuple(sd.outputs())

    mp = getattr(tc, "mixed_precision", None) if tc is not None else None
    facts = graphpass.infer_avals(sd, outputs, batch_size=batch_size)
    report.extend(facts.findings)
    if mp is not None:
        # a second, policy-cast walk: the dtypes XLA will actually run
        # (shape findings come from the natural walk only — the policy
        # walk exists for the numerics pass)
        policy_facts = graphpass.infer_avals(
            sd, outputs, compute_dtype=mp.compute_dtype,
            softmax_dtype=getattr(mp, "softmax_dtype", None),
            batch_size=batch_size)
    else:
        policy_facts = facts

    report.extend(graphpass.check_loss_variables(sd, facts, loss_names))
    report.extend(graphpass.check_placeholder_hygiene(sd, facts))
    report.extend(graphpass.check_dead_ops(sd, facts))
    report.extend(graphpass.check_state_updates(sd, facts))

    report.extend(numerics.check_lowp_accumulation(sd, policy_facts))
    report.extend(numerics.check_nonfinite_prone(sd, facts))
    report.extend(numerics.check_ce_tail_policy(sd, policy_facts, mp))

    if tc is not None:
        report.extend(configpass.check_mappings(sd, facts, tc))
        report.extend(configpass.check_cadence(tc))
        report.extend(configpass.check_sharding(sd, tc, device_count))
        report.extend(configpass.check_knobs(tc, has_listeners))

    report.seconds = _time.perf_counter() - t0
    return report


def analyze_inference(sd, outputs: Optional[Sequence[str]] = None,
                      inputs: Optional[Sequence[str]] = None
                      ) -> AnalysisReport:
    """Graph-only analysis of an inference graph (the serving path):
    shape/dtype inference over the requested outputs, hygiene, and the
    non-finite-prone numerics checks. No config rules — serving has no
    TrainingConfig — and no dead-loss check: a serving graph sliced
    out of a training graph legitimately leaves its loss machinery
    unreached. ``inputs`` scopes the unused-placeholder check to the
    declared serving inputs (ParallelInference passes its spec's)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving")
    report.n_vars, report.n_ops = _graph_size(sd)
    report.rules_run = len(_INFERENCE_RULES)
    outs = tuple(outputs) if outputs else tuple(sd.outputs())
    facts = graphpass.infer_avals(sd, outs)
    report.extend(facts.findings)
    report.extend(graphpass.check_placeholder_hygiene(
        sd, facts, restrict_to=inputs))
    report.extend(graphpass.check_state_updates(sd, facts))
    report.extend(numerics.check_lowp_accumulation(sd, facts))
    report.extend(numerics.check_nonfinite_prone(sd, facts))
    report.seconds = _time.perf_counter() - t0
    return report


def analyze_model(model, **kw) -> AnalysisReport:
    """Analyze anything graph-shaped: a SameDiff, or a MultiLayerNetwork
    / ComputationGraph (their training graph + config)."""
    sd = getattr(model, "samediff", model)
    if getattr(sd, "training_config", None) is not None:
        return analyze_training(sd, **kw)
    return analyze_inference(sd)


__all__ = ["RULES", "SEVERITIES", "Rule", "Finding", "finding",
           "AnalysisReport", "GraphAnalysisError", "GraphAnalysisWarning",
           "analyze_training", "analyze_inference", "analyze_model",
           "analyze_generative_config", "analyze_fleet_config",
           "analyze_speculation_config"]
