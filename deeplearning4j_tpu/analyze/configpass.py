"""Config/composition lint: TrainingConfig knobs checked against the
graph and the device topology BEFORE anything compiles.

Every rule here encodes a constraint that today only surfaces at
dispatch time (or never): feature/label mappings that cannot feed the
graph, the fused/accum cadence alignment documented in
docs/training_performance.md, donated buffers read after the step,
ShardingSpecs that cannot bind (via the pure
``ShardingSpec.validate`` — shared with ``build()``), sharding rules
that match nothing, and armed chaos/tensorstats knobs.
"""
from __future__ import annotations

from typing import List, Optional, Set

from deeplearning4j_tpu.analyze.findings import Finding, finding
from deeplearning4j_tpu.analyze.graphpass import GraphFacts


def check_mappings(sd, facts: GraphFacts, tc) -> List[Finding]:
    from deeplearning4j_tpu.autodiff.variable import VariableType
    out: List[Finding] = []
    feats = list(getattr(tc, "data_set_feature_mapping", ()) or ())
    labels = list(getattr(tc, "data_set_label_mapping", ()) or ())
    for field, names in (("data_set_feature_mapping", feats),
                         ("data_set_label_mapping", labels)):
        for n in names:
            v = sd._vars.get(n)
            if v is None:
                out.append(finding(
                    "config.mapping_unknown", f"{field}:{n}",
                    f"{field} names {n!r}, which is not in the graph",
                    fix_hint="map the placeholder names the graph "
                             "declares"))
            elif v.var_type != VariableType.PLACEHOLDER:
                out.append(finding(
                    "config.mapping_unknown", f"{field}:{n}",
                    f"{field} names {n!r}, a {v.var_type.value} — "
                    f"feeding it would shadow the stored value",
                    fix_hint="map a PLACEHOLDER; convert the variable "
                             "if it was meant to be fed"))
    if feats or labels:
        mapped = set(feats) | set(labels)
        consumed: Set[str] = set()
        for opn in facts.live_ops:
            consumed.update(sd._ops[opn].inputs)
        for ph in sd.placeholders():
            if ph in consumed and ph not in mapped:
                out.append(finding(
                    "config.mapping_incomplete", ph,
                    f"placeholder {ph!r} feeds the loss but is in "
                    f"neither feature nor label mapping — tuple "
                    f"batches cannot supply it",
                    fix_hint="add it to a mapping, or fit with dict "
                             "batches keyed by placeholder name"))
    return out


def check_cadence(tc) -> List[Finding]:
    fused = max(1, int(getattr(tc, "fused_steps", 1) or 1))
    accum = max(1, int(getattr(tc, "accum_steps", 1) or 1))
    if accum > 1 and fused % accum != 0:
        return [finding(
            "config.cadence_misalignment",
            f"fused_steps={fused}/accum_steps={accum}",
            f"fused_steps={fused} is not a multiple of "
            f"accum_steps={accum}: window boundaries land "
            f"mid-accumulation-cycle, so checkpoint flushes cannot "
            f"capture the partial accumulator and a rollback restarts "
            f"that cycle from zeros",
            fix_hint="keep fused_steps a multiple of accum_steps "
                     "(docs/training_performance.md, "
                     "docs/fault_tolerance.md)")]
    return []


def check_sharding(sd, tc, device_count: Optional[int]) -> List[Finding]:
    spec = getattr(tc, "sharding", None)
    if spec is None:
        return []
    if not hasattr(spec, "validate"):
        # a live ShardingStrategy on the config: its mesh already bound
        spec = spec.to_spec() if hasattr(spec, "to_spec") else None
        if spec is None:
            return []
    out: List[Finding] = []
    params = {n: tuple(a.shape)
              for n, a in sd.trainable_params().items()}
    try:
        spec.validate(params=params, device_count=device_count)
    except ValueError as e:
        out.append(finding(
            "config.sharding_invalid", "TrainingConfig.sharding",
            str(e),
            fix_hint="ShardingSpec axes must multiply into the device "
                     "count and divide every matched parameter dim "
                     "(docs/elastic_training.md)"))
    for rule in getattr(spec, "rules", ()) or ():
        if not any(rule.matches(n) for n in params):
            out.append(finding(
                "config.sharding_unmatched_rule", rule.pattern,
                f"ShardingRule {rule.pattern!r} matches zero of the "
                f"{len(params)} parameters — the intended layout "
                f"silently degrades to the preset/replication",
                fix_hint="check the pattern against "
                         "sd.trainable_params() names"))
    return out


def check_knobs(tc, has_listeners: Optional[bool]) -> List[Finding]:
    out: List[Finding] = []
    if getattr(tc, "_chaos_spec", None) is not None:
        out.append(finding(
            "config.chaos_armed", "TrainingConfig._chaos_spec",
            "a faults/chaos injection spec is armed on this config — "
            "deterministic faults (NaN gradients, poisoned batches) "
            "will fire during this fit",
            fix_hint="chaos specs are for drills; clear the spec for "
                     "production fits"))
    if getattr(tc, "tensorstats", None) is not None \
            and has_listeners is False:
        out.append(finding(
            "config.tensorstats_unobserved", "TrainingConfig.tensorstats",
            "tensorstats is configured but this fit has no listeners: "
            "the stats are silently skipped, and attaching listeners "
            "later retraces the step program (a second compiled "
            "signature)",
            fix_hint="attach a MonitorListener/StatsListener, or drop "
                     "tensorstats for listener-free fits"))
    return out


__all__ = ["check_mappings", "check_cadence", "check_sharding",
           "check_knobs"]
