"""CLI: lint a serialized SameDiff model (+ its training config).

::

    python -m deeplearning4j_tpu.analyze model.zip            # human text
    python -m deeplearning4j_tpu.analyze model.zip --json     # one record
    python -m deeplearning4j_tpu.analyze model.zip --strict   # warns fail
    python -m deeplearning4j_tpu.analyze --rules              # catalog

Exit codes: 0 clean (or info-only), 1 error-severity findings
(``--strict``: warn-severity too), 2 usage/load failure. Runs on CPU
with no compile — safe in CI against any committed model artifact.
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.analyze",
        description="pre-compile static analysis of a serialized "
                    "SameDiff model + training config "
                    "(docs/static_analysis.md)")
    ap.add_argument("model", nargs="?",
                    help="path to a SameDiff .zip (autodiff/serde) or "
                         "a nn model .zip (nn/model_serde)")
    ap.add_argument("--json", action="store_true",
                    help="emit the {'type': 'analysis'} record as JSON")
    ap.add_argument("--strict", action="store_true",
                    help="warn-severity findings also fail (exit 1)")
    ap.add_argument("--batch-size", type=int, default=None,
                    help="resolve -1 placeholder batch dims to this "
                         "extent (default: a substitute extent that "
                         "suppresses batch-dim artifacts)")
    ap.add_argument("--rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.analyze import (RULES, analyze_training,
                                            analyze_inference)
    if args.rules:
        for r in RULES.values():
            print(f"{r.rule_id:<32} {r.severity:<5} {r.summary}")
        return 0
    if not args.model:
        ap.print_usage(sys.stderr)
        print("error: a model path (or --rules) is required",
              file=sys.stderr)
        return 2

    from deeplearning4j_tpu.autodiff import serde
    try:
        sd = serde.load(args.model)
    except Exception as e:
        print(f"error: cannot load {args.model!r}: {e}", file=sys.stderr)
        return 2
    if getattr(sd, "training_config", None) is not None:
        report = analyze_training(sd, batch_size=args.batch_size)
    else:
        report = analyze_inference(sd)
    report.context = "cli"

    if args.json:
        print(json.dumps(report.to_record()))
    else:
        print(report.render())
    if report.errors() or (args.strict and report.warnings()):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
