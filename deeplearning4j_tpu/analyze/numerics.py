"""Numerics hazard passes.

Runs over the :class:`~deeplearning4j_tpu.analyze.graphpass.GraphFacts`
of a *policy walk*: when the TrainingConfig carries a MixedPrecision
policy, the abstract interpretation casts params/constants/placeholders
to the compute dtype exactly like the train step's trace does
(``SameDiff._build_step_parts``), so the dtypes inspected here are the
dtypes XLA will run — not the f32 the graph was declared in.

Three hazard families (tentpole pass 3):
- low-precision accumulation: a loss op whose scalar output is bf16/f16
  (the accumulation ate the training signal), or any large reduction
  accumulating in bf16/f16;
- non-finite-prone patterns: ``log``/``divide`` with no positivity /
  zero guard between the value and the op;
- policy hints: the PROFILE.md f32-CE-tail delta (bf16 compute with
  ``MixedPrecision.softmax_dtype`` unset).
"""
from __future__ import annotations

import math
from typing import List

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.analyze.findings import Finding, finding
from deeplearning4j_tpu.analyze.graphpass import (GraphFacts, _LOWP,
                                                  provenance_chain)
from deeplearning4j_tpu.ops import registry

#: reduction ops whose accumulator follows the input dtype
_REDUCE_OPS = {"reduce_sum", "reduce_mean", "cumsum"}

#: minimum reduced-element count before a bf16 accumulator is flagged
#: (bf16 has an 8-bit mantissa: once the running sum is ~256x a term,
#: additions round to nothing — small reductions like pooling windows
#: are fine)
LOWP_REDUCTION_MIN_ELEMENTS = 4096

#: ops whose outputs are strictly positive — a log/div over them needs
#: no guard
_POSITIVE_OPS = {"exp", "sigmoid", "softplus"}

#: softmax-CE loss ops the ce_tail_f32 hint applies to
_SOFTMAX_CE_OPS = {"softmax_cross_entropy", "softmax_cross_entropy_loss",
                   "sparse_softmax_cross_entropy"}


def _const_array(sd, name: str):
    from deeplearning4j_tpu.autodiff.variable import VariableType
    v = sd._vars.get(name)
    if v is not None and v.var_type == VariableType.CONSTANT:
        return sd._arrays.get(name)
    return None


def _guarded(sd, name: str, positive: bool) -> bool:
    """Is variable ``name`` safe to log (positive=True: needs > 0) or
    divide by (positive=False: needs != 0)? Walks ONE producer hop —
    the idioms this recognizes are the repo's own guard patterns
    (``x.div(norm.add(eps))``, ``maximum(x, eps)``, clip attrs)."""
    const = _const_array(sd, name)
    if const is not None:
        a = np.asarray(const)
        if a.size == 0:
            return False
        return bool((a > 0).all() if positive else (a != 0).all())
    prod = sd._producer.get(name)
    if prod is None:
        return False                       # raw placeholder/param
    node = sd._ops[prod]
    if node.op in _POSITIVE_OPS:
        return True
    if node.op in ("maximum", "add"):
        # guarded when one side is a constant that enforces the bound
        # (add of a positive eps bounds away from zero only when the
        # other operand is nonnegative — accepted: it is THE idiom)
        for i in node.inputs:
            ca = _const_array(sd, i)
            if ca is not None and np.asarray(ca).size \
                    and (np.asarray(ca) > 0).all():
                return True
        return False
    if node.op in ("clip", "clip_by_value"):
        lo = node.attrs.get("min", node.attrs.get("clip_value_min"))
        try:
            return lo is not None and float(lo) > 0
        except (TypeError, ValueError):
            return False
    if node.op in ("softmax",) and not positive:
        # softmax rows are nonzero in exact math; denominator use is
        # the normalization idiom
        return True
    return False


def check_nonfinite_prone(sd, facts: GraphFacts) -> List[Finding]:
    out: List[Finding] = []
    for opn in facts.live_ops:
        node = sd._ops[opn]
        if node.op == "log" and node.inputs:
            x = node.inputs[0]
            if not _guarded(sd, x, positive=True):
                out.append(finding(
                    "numerics.unguarded_log", opn,
                    f"op {opn!r} takes log({x}) with no positivity "
                    f"guard between them",
                    fix_hint="log(maximum(x, eps)) or clip first — a "
                             "single 0 poisons the loss with -inf",
                    provenance=provenance_chain(sd, [x], facts.env)))
        elif node.op == "divide" and len(node.inputs) >= 2:
            den = node.inputs[1]
            if not _guarded(sd, den, positive=False):
                out.append(finding(
                    "numerics.unguarded_div", opn,
                    f"op {opn!r} divides by {den!r} with no zero "
                    f"guard",
                    fix_hint="divide by (x + eps) or maximum(x, eps)",
                    provenance=provenance_chain(sd, [den], facts.env)))
    return out


def check_lowp_accumulation(sd, facts: GraphFacts) -> List[Finding]:
    """bf16/f16 accumulations: loss ops whose scalar output stayed
    low-precision under the policy walk, and large reductions whose
    input AND output are low-precision (the accumulator follows)."""
    out: List[Finding] = []
    for opn in facts.live_ops:
        node = sd._ops[opn]
        try:
            o = registry.get_op(node.op)
        except KeyError:
            continue
        out_av = facts.env.get(node.outputs[0]) if node.outputs else None
        if out_av is None:
            continue
        if o.category == "loss":
            if out_av.ndim == 0 and out_av.dtype in _LOWP:
                out.append(finding(
                    "numerics.lowp_loss_accum", opn,
                    f"loss op {opn!r} ({node.op}) reduces to a "
                    f"{out_av.dtype} scalar under the compute-dtype "
                    f"policy — the per-example sum loses the training "
                    f"signal past ~256 terms",
                    fix_hint="reduce with an f32 accumulator "
                             "(jnp.sum(..., dtype=jnp.float32)); the "
                             "built-in loss ops already do"))
            continue
        if node.op in _REDUCE_OPS:
            in_av = facts.env.get(node.inputs[0]) if node.inputs else None
            if in_av is None or in_av.dtype not in _LOWP \
                    or out_av.dtype not in _LOWP:
                continue
            reduced = (math.prod(in_av.shape)
                       // max(1, math.prod(out_av.shape)))
            if reduced >= LOWP_REDUCTION_MIN_ELEMENTS:
                out.append(finding(
                    "numerics.lowp_reduction", opn,
                    f"op {opn!r} ({node.op}) reduces {reduced} "
                    f"elements in {in_av.dtype} — the accumulator "
                    f"rounds away the tail of the sum",
                    fix_hint="pass dtype=jnp.float32 to the reduction "
                             "(XLA still reads bf16 inputs at full "
                             "rate)",
                    provenance=provenance_chain(
                        sd, node.inputs[:1], facts.env)))
    return out


def check_ce_tail_policy(sd, facts: GraphFacts, mp) -> List[Finding]:
    """The PROFILE.md f32-CE delta as a hint: bf16 compute, a softmax-CE
    loss in the live graph, and no softmax_dtype policy — the
    [batch..., vocab] f32 tail is the step's largest tensor."""
    if mp is None or getattr(mp, "softmax_dtype", None) is not None:
        return []
    cdt = str(getattr(mp, "compute_dtype", "")).lower()
    if cdt not in ("bfloat16", "bf16", "float16", "f16", "half"):
        return []
    out: List[Finding] = []
    for opn in facts.live_ops:
        node = sd._ops[opn]
        if node.op in _SOFTMAX_CE_OPS:
            in_av = facts.env.get(node.inputs[0]) if node.inputs else None
            vocab = in_av.shape[-1] if in_av is not None and in_av.ndim \
                else "?"
            out.append(finding(
                "numerics.ce_tail_f32", opn,
                f"loss op {opn!r} ({node.op}) runs its log-softmax "
                f"tail in f32 under bf16 compute (vocab {vocab}) — "
                f"the largest f32 tensor in the step (PROFILE.md)",
                fix_hint="MixedPrecision(softmax_dtype='bfloat16') "
                         "keeps the tail bf16; the scalar loss still "
                         "accumulates f32 "
                         "(docs/training_performance.md)"))
    return out


__all__ = ["check_nonfinite_prone", "check_lowp_accumulation",
           "check_ce_tail_policy", "LOWP_REDUCTION_MIN_ELEMENTS"]
