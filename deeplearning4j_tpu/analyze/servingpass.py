"""Serving-config lint: capacity checks a generative server would only
surface at construction time (or worse, as a backend OOM), run as pure
arithmetic over the spec + knobs — the serving analogue of
analyze/configpass.py.

- ``serving.dense_kv_exceeds_headroom`` — the dense continuous-batching
  server preallocates ``2 x max_slots x max_seq`` rows of KV up front,
  so a capacity plan that looks innocuous ("max_slots=64,
  max_seq=8192") can exceed the chip's free HBM before a single request
  arrives. ``GenerativeServer`` refuses such a config at construction
  (monitor/memstats.check_headroom); this pass flags it at LINT time
  instead, with the fix the refusal cannot suggest by itself: the paged
  server (serving/paged) allocates the same budget as a block pool, so
  capacity scales with tokens actually held rather than the worst case
  — docs/serving.md "Paged KV & prefix caching".
- ``serving.fleet_slo_unreachable`` — the fleet-plan twin
  (:func:`analyze_fleet_config`): pure admission math over ``replicas
  × slots × p99 decode-step estimate`` vs the TTFT SLO at the stated
  arrival rate. A plan that cannot meet its deadline under Little's
  law will shed/queue forever no matter how the router places — the
  lint says so before a replica is started, with the two fixes the
  runtime cannot apply itself (more replicas, or a relaxed deadline).
"""
from __future__ import annotations

import time as _time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.analyze.findings import AnalysisReport, finding


def dense_kv_slab_bytes(spec, max_slots: int,
                        max_seq_len: Optional[int] = None) -> int:
    """Bytes of the dense server's two KV slabs for this spec + knobs
    (``kv_shape(max_slots, max_seq)`` twice, in ``spec.kv_dtype``)."""
    from deeplearning4j_tpu.ndarray.dtype import DataType
    msl = int(max_seq_len or spec.max_seq_len)
    shape = tuple(spec.kv_shape(int(max_slots), msl))
    itemsize = DataType.from_any(
        getattr(spec, "kv_dtype", "float32")).np.itemsize
    return 2 * int(np.prod(shape)) * itemsize


def check_dense_kv_headroom(spec, max_slots: int,
                            max_seq_len: Optional[int] = None,
                            headroom_bytes: Optional[int] = None):
    """Findings for one dense serving config. ``headroom_bytes`` is
    the capacity-plan budget; None reads the live device headroom
    (monitor/memstats.projected_headroom — None again on CPU, where the
    check is a no-op exactly like the construction-time guard)."""
    if headroom_bytes is None:
        from deeplearning4j_tpu.monitor import memstats
        headroom_bytes = memstats.projected_headroom()
    if headroom_bytes is None:
        return []
    need = dense_kv_slab_bytes(spec, max_slots, max_seq_len)
    if need <= int(headroom_bytes):
        return []
    msl = int(max_seq_len or spec.max_seq_len)
    return [finding(
        "serving.dense_kv_exceeds_headroom",
        f"kv_slab[{max_slots}x{msl}]",
        f"dense KV slabs need ~{need / 2**20:.1f} MiB "
        f"({max_slots} slots x {msl} positions preallocated) but the "
        f"headroom guard allows {int(headroom_bytes) / 2**20:.1f} MiB "
        f"— GenerativeServer would refuse this config at construction",
        fix_hint="serve paged: serving.paged.PagedGenerativeServer("
                 "spec, kv_hbm_bytes=<budget>) sizes the pool by "
                 "tokens actually held (+ prefix caching), or lower "
                 "max_slots/max_seq_len")]


def check_fleet_slo(replicas: int, max_slots: int,
                    p99_decode_step_ms: float, ttft_slo_ms: float,
                    arrival_rate_rps: float,
                    mean_new_tokens: float = 16.0):
    """Findings for one fleet capacity plan — worst-case admission
    arithmetic, no servers constructed.

    Two ways a plan is unreachable:

    - **floor**: serving the FIRST token takes at least one decode
      step, so ``p99_decode_step_ms > ttft_slo_ms`` fails even an idle
      fleet;
    - **saturation**: a request occupies a slot for ``mean_new_tokens
      × p99_decode_step_ms``; by Little's law the offered load needs
      ``arrival_rate × service_s`` concurrent slots. When that exceeds
      ``replicas × max_slots`` the queue grows without bound and p99
      TTFT diverges — every admission estimate the servers shed on
      (``(queue_depth + 1) × p99 step``) eventually exceeds any
      deadline.
    """
    step_ms = float(p99_decode_step_ms)
    slo_ms = float(ttft_slo_ms)
    rate = float(arrival_rate_rps)
    service_s = float(mean_new_tokens) * step_ms / 1000.0
    slots_needed = rate * service_s
    capacity = int(replicas) * int(max_slots)
    subject = f"fleet[{int(replicas)}x{int(max_slots)}]"
    out = []
    if step_ms > slo_ms:
        out.append(finding(
            "serving.fleet_slo_unreachable", subject,
            f"one p99 decode step ({step_ms:.1f} ms) already exceeds "
            f"the TTFT SLO ({slo_ms:.1f} ms) — no replica count can "
            f"serve a first token inside the deadline",
            fix_hint="relax the TTFT deadline past one decode step, "
                     "or make the step faster (smaller model, fewer "
                     "active slots per step)"))
    elif slots_needed > capacity:
        need_replicas = int(np.ceil(slots_needed / max(1, max_slots)))
        out.append(finding(
            "serving.fleet_slo_unreachable", subject,
            f"offered load needs ~{slots_needed:.1f} concurrent slots "
            f"({arrival_rate_rps:g} req/s x {mean_new_tokens:g} tokens "
            f"x {step_ms:.1f} ms p99 step) but the fleet has "
            f"{capacity} ({replicas} replicas x {max_slots} slots) — "
            f"queues grow without bound and p99 TTFT diverges past "
            f"the {slo_ms:.1f} ms SLO",
            fix_hint=f"raise the fleet to >= {need_replicas} replicas "
                     f"(or add slots/relax the deadline/shed at a "
                     f"lower arrival rate)"))
    return out


def analyze_fleet_config(replicas: int, max_slots: int,
                         p99_decode_step_ms: float, ttft_slo_ms: float,
                         arrival_rate_rps: float,
                         mean_new_tokens: float = 16.0
                         ) -> AnalysisReport:
    """Lint one fleet capacity plan (replica count + per-replica knobs
    + SLO + offered load) — the entry point
    ``serving.fleet_slo_unreachable`` runs under
    (``context="serving_config"``, like the per-server lint)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving_config")
    report.rules_run = 1
    report.extend(check_fleet_slo(replicas, max_slots,
                                  p99_decode_step_ms, ttft_slo_ms,
                                  arrival_rate_rps, mean_new_tokens))
    report.seconds = _time.perf_counter() - t0
    return report


def analyze_generative_config(spec, max_slots: int,
                              max_seq_len: Optional[int] = None,
                              headroom_bytes: Optional[int] = None
                              ) -> AnalysisReport:
    """Lint one generative serving capacity plan (spec + knobs) without
    constructing a server or touching a device — the entry point the
    serving rules run under (``context="serving_config"``)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving_config")
    report.rules_run = 1
    report.extend(check_dense_kv_headroom(
        spec, max_slots, max_seq_len, headroom_bytes))
    report.seconds = _time.perf_counter() - t0
    return report


__all__ = ["analyze_fleet_config", "analyze_generative_config",
           "check_dense_kv_headroom", "check_fleet_slo",
           "dense_kv_slab_bytes"]
