"""Serving-config lint: capacity checks a generative server would only
surface at construction time (or worse, as a backend OOM), run as pure
arithmetic over the spec + knobs — the serving analogue of
analyze/configpass.py.

- ``serving.dense_kv_exceeds_headroom`` — the dense continuous-batching
  server preallocates ``2 x max_slots x max_seq`` rows of KV up front,
  so a capacity plan that looks innocuous ("max_slots=64,
  max_seq=8192") can exceed the chip's free HBM before a single request
  arrives. ``GenerativeServer`` refuses such a config at construction
  (monitor/memstats.check_headroom); this pass flags it at LINT time
  instead, with the fix the refusal cannot suggest by itself: the paged
  server (serving/paged) allocates the same budget as a block pool, so
  capacity scales with tokens actually held rather than the worst case
  — docs/serving.md "Paged KV & prefix caching".
- ``serving.speculation_misconfig`` — the speculative-decoding pairing
  lint (:func:`analyze_speculation_config`): a draft whose vocab
  differs from the target's, or whose ``max_seq_len`` is shorter than
  the served window, is refused by ``GenerativeServer`` at
  construction — flagged here as an **error** at lint time. A draft at
  least as LARGE (by parameter count) as its target constructs fine
  and still emits the target's exact tokens, it just cannot speed
  anything up — drafting costs more than it saves — so that variant is
  demoted to a **warning** with the fix the runtime cannot pick for
  you: a smaller zoo config (docs/serving.md "Decode speed").
- ``serving.fleet_slo_unreachable`` — the fleet-plan twin
  (:func:`analyze_fleet_config`): pure admission math over ``replicas
  × slots × p99 decode-step estimate`` vs the TTFT SLO at the stated
  arrival rate. A plan that cannot meet its deadline under Little's
  law will shed/queue forever no matter how the router places — the
  lint says so before a replica is started, with the two fixes the
  runtime cannot apply itself (more replicas, or a relaxed deadline).
"""
from __future__ import annotations

import time as _time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.analyze.findings import AnalysisReport, finding


def dense_kv_slab_bytes(spec, max_slots: int,
                        max_seq_len: Optional[int] = None) -> int:
    """Bytes of the dense server's two KV slabs for this spec + knobs
    (``kv_shape(max_slots, max_seq)`` twice, in ``spec.kv_dtype``)."""
    from deeplearning4j_tpu.ndarray.dtype import DataType
    msl = int(max_seq_len or spec.max_seq_len)
    shape = tuple(spec.kv_shape(int(max_slots), msl))
    itemsize = DataType.from_any(
        getattr(spec, "kv_dtype", "float32")).np.itemsize
    return 2 * int(np.prod(shape)) * itemsize


def check_dense_kv_headroom(spec, max_slots: int,
                            max_seq_len: Optional[int] = None,
                            headroom_bytes: Optional[int] = None):
    """Findings for one dense serving config. ``headroom_bytes`` is
    the capacity-plan budget; None reads the live device headroom
    (monitor/memstats.projected_headroom — None again on CPU, where the
    check is a no-op exactly like the construction-time guard)."""
    if headroom_bytes is None:
        from deeplearning4j_tpu.monitor import memstats
        headroom_bytes = memstats.projected_headroom()
    if headroom_bytes is None:
        return []
    need = dense_kv_slab_bytes(spec, max_slots, max_seq_len)
    if need <= int(headroom_bytes):
        return []
    msl = int(max_seq_len or spec.max_seq_len)
    return [finding(
        "serving.dense_kv_exceeds_headroom",
        f"kv_slab[{max_slots}x{msl}]",
        f"dense KV slabs need ~{need / 2**20:.1f} MiB "
        f"({max_slots} slots x {msl} positions preallocated) but the "
        f"headroom guard allows {int(headroom_bytes) / 2**20:.1f} MiB "
        f"— GenerativeServer would refuse this config at construction",
        fix_hint="serve paged: serving.paged.PagedGenerativeServer("
                 "spec, kv_hbm_bytes=<budget>) sizes the pool by "
                 "tokens actually held (+ prefix caching), or lower "
                 "max_slots/max_seq_len")]


def check_fleet_slo(replicas: int, max_slots: int,
                    p99_decode_step_ms: float, ttft_slo_ms: float,
                    arrival_rate_rps: float,
                    mean_new_tokens: float = 16.0):
    """Findings for one fleet capacity plan — worst-case admission
    arithmetic, no servers constructed.

    Two ways a plan is unreachable:

    - **floor**: serving the FIRST token takes at least one decode
      step, so ``p99_decode_step_ms > ttft_slo_ms`` fails even an idle
      fleet;
    - **saturation**: a request occupies a slot for ``mean_new_tokens
      × p99_decode_step_ms``; by Little's law the offered load needs
      ``arrival_rate × service_s`` concurrent slots. When that exceeds
      ``replicas × max_slots`` the queue grows without bound and p99
      TTFT diverges — every admission estimate the servers shed on
      (``(queue_depth + 1) × p99 step``) eventually exceeds any
      deadline.
    """
    step_ms = float(p99_decode_step_ms)
    slo_ms = float(ttft_slo_ms)
    rate = float(arrival_rate_rps)
    service_s = float(mean_new_tokens) * step_ms / 1000.0
    slots_needed = rate * service_s
    capacity = int(replicas) * int(max_slots)
    subject = f"fleet[{int(replicas)}x{int(max_slots)}]"
    out = []
    if step_ms > slo_ms:
        out.append(finding(
            "serving.fleet_slo_unreachable", subject,
            f"one p99 decode step ({step_ms:.1f} ms) already exceeds "
            f"the TTFT SLO ({slo_ms:.1f} ms) — no replica count can "
            f"serve a first token inside the deadline",
            fix_hint="relax the TTFT deadline past one decode step, "
                     "or make the step faster (smaller model, fewer "
                     "active slots per step)"))
    elif slots_needed > capacity:
        need_replicas = int(np.ceil(slots_needed / max(1, max_slots)))
        out.append(finding(
            "serving.fleet_slo_unreachable", subject,
            f"offered load needs ~{slots_needed:.1f} concurrent slots "
            f"({arrival_rate_rps:g} req/s x {mean_new_tokens:g} tokens "
            f"x {step_ms:.1f} ms p99 step) but the fleet has "
            f"{capacity} ({replicas} replicas x {max_slots} slots) — "
            f"queues grow without bound and p99 TTFT diverges past "
            f"the {slo_ms:.1f} ms SLO",
            fix_hint=f"raise the fleet to >= {need_replicas} replicas "
                     f"(or add slots/relax the deadline/shed at a "
                     f"lower arrival rate)"))
    return out


def _spec_param_count(spec) -> Optional[int]:
    """Total parameter element count of a GenerativeSpec-shaped object
    (``spec.params()`` -> name->array mapping); None when the spec
    carries no params (the size check is then skipped)."""
    params = getattr(spec, "params", None)
    if not callable(params):
        return None
    try:
        items = dict(params())
    except TypeError:
        return None
    if not items:
        return None
    return int(sum(int(np.prod(np.shape(v)) or 1)
                   for v in items.values()))


def check_speculation(spec, draft_spec, speculate_k: int = 4):
    """Findings for one draft/target speculation pairing — the checks
    ``GenerativeServer(draft_spec=...)`` enforces at construction, plus
    the economics check it deliberately does not."""
    out = []
    tv = int(getattr(spec, "vocab_size", 0) or 0)
    dv = int(getattr(draft_spec, "vocab_size", 0) or 0)
    if tv and dv and tv != dv:
        out.append(finding(
            "serving.speculation_misconfig", "draft_spec.vocab_size",
            f"draft vocab ({dv}) != target vocab ({tv}) — drafted "
            f"token ids index a different embedding table, so the "
            f"server refuses the pairing at construction",
            fix_hint="draft with a model trained on the SAME "
                     "vocabulary (e.g. a num_layers-truncated copy of "
                     "the target config)"))
    tm = int(getattr(spec, "max_seq_len", 0) or 0)
    dm = int(getattr(draft_spec, "max_seq_len", 0) or 0)
    if tm and dm and dm < tm:
        out.append(finding(
            "serving.speculation_misconfig", "draft_spec.max_seq_len",
            f"draft max_seq_len ({dm}) < served max_seq_len ({tm}) — "
            f"the draft KV cache cannot cover the tail of a "
            f"full-length generation, so the server refuses the "
            f"pairing at construction",
            fix_hint=f"raise the draft config's max_seq_len to >= {tm} "
                     f"(its KV slab is the cheap one)"))
    tp = _spec_param_count(spec)
    dp = _spec_param_count(draft_spec)
    if tp and dp and dp >= tp:
        out.append(finding(
            "serving.speculation_misconfig", "draft_spec",
            f"draft has {dp} parameters vs the target's {tp} — "
            f"speculation only pays when drafting is much cheaper "
            f"than verifying; this pairing still emits the target's "
            f"exact tokens but each round costs speculate_k="
            f"{int(speculate_k)} full-size dispatches plus the verify",
            fix_hint="draft with a much smaller config — e.g. "
                     "zoo.gpt.GPT_TINY, or dataclasses.replace("
                     "target_cfg, num_layers=2) fed to "
                     "gpt_generative_spec",
            severity="warn"))
    return out


def analyze_speculation_config(spec, draft_spec,
                               speculate_k: int = 4) -> AnalysisReport:
    """Lint one speculative-decoding pairing (target spec + draft spec
    + window) without constructing a server — the entry point
    ``serving.speculation_misconfig`` runs under
    (``context="serving_config"``, like the per-server lint)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving_config")
    report.rules_run = 1
    report.extend(check_speculation(spec, draft_spec, speculate_k))
    report.seconds = _time.perf_counter() - t0
    return report


def analyze_fleet_config(replicas: int, max_slots: int,
                         p99_decode_step_ms: float, ttft_slo_ms: float,
                         arrival_rate_rps: float,
                         mean_new_tokens: float = 16.0
                         ) -> AnalysisReport:
    """Lint one fleet capacity plan (replica count + per-replica knobs
    + SLO + offered load) — the entry point
    ``serving.fleet_slo_unreachable`` runs under
    (``context="serving_config"``, like the per-server lint)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving_config")
    report.rules_run = 1
    report.extend(check_fleet_slo(replicas, max_slots,
                                  p99_decode_step_ms, ttft_slo_ms,
                                  arrival_rate_rps, mean_new_tokens))
    report.seconds = _time.perf_counter() - t0
    return report


def analyze_generative_config(spec, max_slots: int,
                              max_seq_len: Optional[int] = None,
                              headroom_bytes: Optional[int] = None
                              ) -> AnalysisReport:
    """Lint one generative serving capacity plan (spec + knobs) without
    constructing a server or touching a device — the entry point the
    serving rules run under (``context="serving_config"``)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving_config")
    report.rules_run = 1
    report.extend(check_dense_kv_headroom(
        spec, max_slots, max_seq_len, headroom_bytes))
    report.seconds = _time.perf_counter() - t0
    return report


__all__ = ["analyze_fleet_config", "analyze_generative_config",
           "analyze_speculation_config", "check_dense_kv_headroom",
           "check_fleet_slo", "check_speculation",
           "dense_kv_slab_bytes"]
