"""Serving-config lint: capacity checks a generative server would only
surface at construction time (or worse, as a backend OOM), run as pure
arithmetic over the spec + knobs — the serving analogue of
analyze/configpass.py.

One rule today: ``serving.dense_kv_exceeds_headroom`` — the dense
continuous-batching server preallocates ``2 x max_slots x max_seq``
rows of KV up front, so a capacity plan that looks innocuous
("max_slots=64, max_seq=8192") can exceed the chip's free HBM before a
single request arrives. ``GenerativeServer`` refuses such a config at
construction (monitor/memstats.check_headroom); this pass flags it at
LINT time instead, with the fix the refusal cannot suggest by itself:
the paged server (serving/paged) allocates the same budget as a block
pool, so capacity scales with tokens actually held rather than the
worst case — docs/serving.md "Paged KV & prefix caching".
"""
from __future__ import annotations

import time as _time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.analyze.findings import AnalysisReport, finding


def dense_kv_slab_bytes(spec, max_slots: int,
                        max_seq_len: Optional[int] = None) -> int:
    """Bytes of the dense server's two KV slabs for this spec + knobs
    (``kv_shape(max_slots, max_seq)`` twice, in ``spec.kv_dtype``)."""
    from deeplearning4j_tpu.ndarray.dtype import DataType
    msl = int(max_seq_len or spec.max_seq_len)
    shape = tuple(spec.kv_shape(int(max_slots), msl))
    itemsize = DataType.from_any(
        getattr(spec, "kv_dtype", "float32")).np.itemsize
    return 2 * int(np.prod(shape)) * itemsize


def check_dense_kv_headroom(spec, max_slots: int,
                            max_seq_len: Optional[int] = None,
                            headroom_bytes: Optional[int] = None):
    """Findings for one dense serving config. ``headroom_bytes`` is
    the capacity-plan budget; None reads the live device headroom
    (monitor/memstats.projected_headroom — None again on CPU, where the
    check is a no-op exactly like the construction-time guard)."""
    if headroom_bytes is None:
        from deeplearning4j_tpu.monitor import memstats
        headroom_bytes = memstats.projected_headroom()
    if headroom_bytes is None:
        return []
    need = dense_kv_slab_bytes(spec, max_slots, max_seq_len)
    if need <= int(headroom_bytes):
        return []
    msl = int(max_seq_len or spec.max_seq_len)
    return [finding(
        "serving.dense_kv_exceeds_headroom",
        f"kv_slab[{max_slots}x{msl}]",
        f"dense KV slabs need ~{need / 2**20:.1f} MiB "
        f"({max_slots} slots x {msl} positions preallocated) but the "
        f"headroom guard allows {int(headroom_bytes) / 2**20:.1f} MiB "
        f"— GenerativeServer would refuse this config at construction",
        fix_hint="serve paged: serving.paged.PagedGenerativeServer("
                 "spec, kv_hbm_bytes=<budget>) sizes the pool by "
                 "tokens actually held (+ prefix caching), or lower "
                 "max_slots/max_seq_len")]


def analyze_generative_config(spec, max_slots: int,
                              max_seq_len: Optional[int] = None,
                              headroom_bytes: Optional[int] = None
                              ) -> AnalysisReport:
    """Lint one generative serving capacity plan (spec + knobs) without
    constructing a server or touching a device — the entry point the
    serving rules run under (``context="serving_config"``)."""
    t0 = _time.perf_counter()
    report = AnalysisReport(context="serving_config")
    report.rules_run = 1
    report.extend(check_dense_kv_headroom(
        spec, max_slots, max_seq_len, headroom_bytes))
    report.seconds = _time.perf_counter() - t0
    return report


__all__ = ["analyze_generative_config", "check_dense_kv_headroom",
           "dense_kv_slab_bytes"]
