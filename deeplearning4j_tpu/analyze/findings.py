"""Findings, the rule catalog, and the analysis report.

Reference parity: the role of DL4J's ``OpValidation`` / SameDiff
shape-inference checks (L3 of the PAPER.md layer map) — user errors
surface as *named graph diagnostics* before anything native runs. Here
"native" is XLA: a wrong shape, dtype hazard or bad config otherwise
dies inside jit with a traceback that names none of the user's
variables. Every check the analyzer runs is a :class:`Rule` in
:data:`RULES`; every hit is a :class:`Finding` carrying the rule id,
severity, the offending variable/op and its producer chain, and a fix
hint. ``docs/static_analysis.md`` is the human-readable catalog
(tests/test_analyze.py asserts the two stay in sync, and that every
rule has a seeded-defect test the analyzer catches).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Sequence, Tuple

#: severity levels, most severe first. "error" findings make
#: ``strict`` mode raise :class:`GraphAnalysisError` BEFORE any XLA
#: compile; "warn" is a real hazard that may still be intended; "info"
#: is hygiene / a perf hint.
SEVERITIES = ("error", "warn", "info")


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    severity: str
    summary: str


def _catalog(*rules: Rule) -> Dict[str, Rule]:
    out: Dict[str, Rule] = {}
    for r in rules:
        if r.severity not in SEVERITIES:
            raise ValueError(f"{r.rule_id}: bad severity {r.severity!r}")
        if r.rule_id in out:
            raise ValueError(f"duplicate rule id {r.rule_id}")
        out[r.rule_id] = r
    return out


#: The rule catalog. Adding a rule here without a seeded-defect test in
#: tests/test_analyze.py (and a row in docs/static_analysis.md) fails
#: the suite — the catalog IS the contract.
RULES: Dict[str, Rule] = _catalog(
    # -- graph passes (analyze/graphpass.py) ----------------------------
    Rule("graph.shape_mismatch", "error",
         "an op's input shapes/dtypes cannot compose (abstract "
         "jax.eval_shape of the op body fails)"),
    Rule("graph.undefined_input", "error",
         "an op consumes a variable that does not exist or is an ARRAY "
         "with no producing op"),
    Rule("graph.invalid_loss", "error",
         "a loss variable is missing from the graph, is not an op "
         "output, or has a non-floating dtype"),
    Rule("graph.unused_placeholder", "warn",
         "a placeholder is declared but not consumed by any op "
         "contributing to the requested outputs"),
    Rule("graph.name_shadowing", "warn",
         "two placeholders share a base name (auto-suffixed _N) — data "
         "fed by name silently reaches only one of them"),
    Rule("graph.dead_op", "warn",
         "a recorded loss op contributes to none of the requested "
         "outputs — a forgotten loss_variables entry trains nothing, "
         "silently"),
    Rule("graph.state_alias", "error",
         "a state-var update source is missing or aliases the state "
         "var itself (the update would be a no-op or crash at trace)"),
    # -- numerics passes (analyze/numerics.py) --------------------------
    Rule("numerics.lowp_loss_accum", "warn",
         "a loss op reduces to its scalar in bf16/f16 under the "
         "compute-dtype policy — the accumulation loses the training "
         "signal (force an f32 accumulator)"),
    Rule("numerics.lowp_reduction", "warn",
         "a large reduction (>= 4096 elements) accumulates in "
         "bf16/f16 — rounding absorbs the tail of the sum"),
    Rule("numerics.unguarded_log", "warn",
         "log() over a value with no positivity guard (clip/maximum/"
         "+eps) — 0 or negative inputs produce -inf/NaN"),
    Rule("numerics.unguarded_div", "warn",
         "division by a value with no zero guard (+eps/maximum/"
         "nonzero constant) — a zero denominator produces inf/NaN"),
    Rule("numerics.ce_tail_f32", "info",
         "bf16 compute with the softmax-CE tail left in f32 — on a "
         "large vocab this is the single largest f32 tensor in the "
         "step (PROFILE.md; set MixedPrecision.softmax_dtype)"),
    # -- config/composition passes (analyze/configpass.py) --------------
    Rule("config.mapping_unknown", "error",
         "data_set_feature/label_mapping names a variable that does "
         "not exist or is not a placeholder"),
    Rule("config.mapping_incomplete", "warn",
         "a placeholder the loss depends on is in neither feature nor "
         "label mapping — tuple batches cannot feed it"),
    Rule("config.cadence_misalignment", "warn",
         "fused_steps is not a multiple of accum_steps — window "
         "boundaries land mid-accumulation-cycle "
         "(docs/training_performance.md)"),
    Rule("config.donation_conflict", "error",
         "a requested output (loss variable) is a parameter/state/"
         "constant — the donated buffer would be read after the step "
         "invalidates it, and it carries no gradient"),
    Rule("config.sharding_invalid", "error",
         "the ShardingSpec cannot bind: axis sizes don't divide the "
         "device count or a matched parameter dim "
         "(ShardingSpec.validate)"),
    Rule("config.sharding_unmatched_rule", "warn",
         "an explicit ShardingRule matches zero parameters — the "
         "intended layout silently degrades to the preset/replication"),
    Rule("config.chaos_armed", "warn",
         "a faults/chaos injection spec is still armed on the "
         "TrainingConfig — deterministic faults will fire in this fit"),
    Rule("config.tensorstats_unobserved", "warn",
         "tensorstats is configured but this fit has no listeners — "
         "stats are silently skipped, and attaching listeners later "
         "retraces the step program"),
    # -- serving/config passes (analyze/servingpass.py) -----------------
    Rule("serving.dense_kv_exceeds_headroom", "warn",
         "a generative serving config's dense KV slab estimate "
         "(max_slots x max_seq rows) exceeds the device headroom "
         "guard — construction would be refused; paged KV "
         "(serving/paged) sizes by tokens actually held"),
    Rule("serving.fleet_slo_unreachable", "warn",
         "a fleet capacity plan (replicas x slots x p99 decode-step "
         "estimate) cannot meet its TTFT SLO at the stated arrival "
         "rate — queues grow without bound under Little's law and "
         "every request is eventually shed or late"),
    Rule("serving.speculation_misconfig", "error",
         "a speculative-decoding draft/target pairing is broken "
         "(vocab or max_seq mismatch — the server would refuse it at "
         "construction) or pointless (draft at least as large as the "
         "target, demoted to a warning: verification still yields the "
         "target's exact tokens, just no speedup)"),
)


@dataclasses.dataclass
class Finding:
    """One diagnostic: which rule, how severe, what it names.

    ``subject`` is the user-facing variable/op/config-field name;
    ``provenance`` is the producer chain ("var <- op ... ") that turns
    "XLA failed" into "YOUR variable, defined here, fed this op".
    """
    rule_id: str
    severity: str
    subject: str
    message: str
    fix_hint: str = ""
    provenance: Tuple[str, ...] = ()

    def to_json(self) -> dict:
        return {"rule_id": self.rule_id, "severity": self.severity,
                "subject": self.subject, "message": self.message,
                "fix_hint": self.fix_hint,
                "provenance": list(self.provenance)}

    def render(self) -> str:
        lines = [f"[{self.severity:<5}] {self.rule_id}: {self.subject} — "
                 f"{self.message}"]
        for p in self.provenance:
            lines.append(f"    {p}")
        if self.fix_hint:
            lines.append(f"    fix: {self.fix_hint}")
        return "\n".join(lines)


def finding(rule_id: str, subject: str, message: str, fix_hint: str = "",
            provenance: Sequence[str] = (),
            severity: str = "") -> Finding:
    """Build a Finding for a cataloged rule. Severity comes from the
    catalog by default; a pass may pass ``severity=`` to DEMOTE a
    dual-severity rule's hit (e.g. ``serving.speculation_misconfig``:
    a broken pairing is an error, a merely-pointless one a warning) —
    never to escalate past the catalog, which states the worst case."""
    rule = RULES[rule_id]
    if severity and severity not in SEVERITIES:
        raise ValueError(f"{rule_id}: bad severity override {severity!r}")
    if severity and SEVERITIES.index(severity) < \
            SEVERITIES.index(rule.severity):
        raise ValueError(
            f"{rule_id}: override {severity!r} escalates past the "
            f"cataloged {rule.severity!r}")
    return Finding(rule_id=rule_id, severity=severity or rule.severity,
                   subject=subject, message=message, fix_hint=fix_hint,
                   provenance=tuple(provenance))


class GraphAnalysisError(RuntimeError):
    """Strict-mode verdict: error-severity findings exist, raised
    BEFORE any XLA compile is attempted. ``.report`` carries the full
    :class:`AnalysisReport`; the message renders the error findings."""

    def __init__(self, report: "AnalysisReport"):
        self.report = report
        errs = report.errors()
        msg = (f"static analysis found {len(errs)} error(s) "
               f"(strict mode; docs/static_analysis.md):\n"
               + "\n".join(f.render() for f in errs))
        super().__init__(msg)


class GraphAnalysisWarning(UserWarning):
    """Non-strict mode surfaces error-severity findings as this
    warning category and proceeds (the compile will usually fail with
    a better-located message than XLA's)."""


@dataclasses.dataclass
class AnalysisReport:
    """Everything one analyzer run produced, plus provenance of the
    run itself (context, wall seconds, graph size)."""
    findings: List[Finding] = dataclasses.field(default_factory=list)
    context: str = "fit"            # fit | precompile | serving | cli
    n_vars: int = 0
    n_ops: int = 0
    rules_run: int = 0
    seconds: float = 0.0

    def add(self, f: Finding) -> None:
        self.findings.append(f)

    def extend(self, fs: Sequence[Finding]) -> None:
        self.findings.extend(fs)

    def by_severity(self, severity: str) -> List[Finding]:
        return [f for f in self.findings if f.severity == severity]

    def errors(self) -> List[Finding]:
        return self.by_severity("error")

    def warnings(self) -> List[Finding]:
        return self.by_severity("warn")

    def counts(self) -> Dict[str, int]:
        return {s: len(self.by_severity(s)) for s in SEVERITIES}

    def raise_if_errors(self) -> None:
        if self.errors():
            raise GraphAnalysisError(self)

    def to_record(self, max_findings: int = 100) -> dict:
        """The ``{"type": "analysis"}`` ui/stats record (schema in the
        ui/stats.py module docstring; rendered by ui/report's "Static
        analysis" panel, folded by MetricsRegistry.fold_analysis)."""
        return {"type": "analysis", "t": time.time(),
                "context": self.context,
                "graph": {"vars": self.n_vars, "ops": self.n_ops},
                "rules_run": self.rules_run,
                "seconds": round(self.seconds, 4),
                "counts": self.counts(),
                "findings": [f.to_json()
                             for f in self.findings[:max_findings]],
                "truncated": max(0, len(self.findings) - max_findings)}

    def render(self) -> str:
        head = (f"static analysis ({self.context}): {self.n_ops} ops / "
                f"{self.n_vars} vars, {self.rules_run} rules in "
                f"{self.seconds:.3f}s — "
                + ", ".join(f"{n} {s}" for s, n in self.counts().items()))
        if not self.findings:
            return head + "\nclean — no findings."
        order = {s: i for i, s in enumerate(SEVERITIES)}
        ranked = sorted(self.findings, key=lambda f: order[f.severity])
        return head + "\n" + "\n".join(f.render() for f in ranked)


__all__ = ["SEVERITIES", "Rule", "RULES", "Finding", "finding",
           "AnalysisReport", "GraphAnalysisError", "GraphAnalysisWarning"]
