"""Graph passes: abstract shape/dtype inference + hygiene.

The core is :func:`infer_avals` — an abstract interpretation of the
recorded op order. Each op body runs under ``jax.eval_shape`` over the
inputs' ``ShapeDtypeStruct``s, so the walk costs microseconds per op,
never compiles, and never touches a device. Where the reference runs
per-op C++ ``calculateOutputShapes`` (NativeOps.h), here the op body
itself IS the shape function.

Unknowns are tracked honestly: placeholders with ``-1`` batch dims get
a substitute extent and TAINT everything downstream — an eval failure
on tainted inputs is an artifact of the fake dim, not a user bug, and
produces no finding (the same contract SameDiff.infer_shape keeps).
Ops whose attrs need concrete tensor values (tf_compat reshape et al.)
mark their outputs unknown and the walk continues.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.analyze.findings import Finding, finding
from deeplearning4j_tpu.ndarray.dtype import DataType
from deeplearning4j_tpu.ops import registry

#: substitute extents for unknown (-1) placeholder dims. Two walks with
#: DIFFERENT extents separate real shape errors from artifacts of the
#: substitution: a genuine mismatch (784-dim features into a 300-row
#: kernel) fails at both extents, while a failure that only exists at
#: one extent depended on the fake dim and is suppressed. Both are
#: highly composite so stride/pool/head-split ops divide cleanly.
FAKE_BATCH = 8
FAKE_BATCH_CONFIRM = 12

_LOWP = (jnp.bfloat16, jnp.float16)


@dataclasses.dataclass
class GraphFacts:
    """What the abstract walk learned — shared by the numerics and
    config passes so each graph is interpreted ONCE."""
    env: Dict[str, Optional[jax.ShapeDtypeStruct]]  # None = unknown
    tainted: Set[str]          # shapes involve substituted batch dims
    live_ops: List[str]        # pruned topo order for the outputs
    outputs: Tuple[str, ...]
    findings: List[Finding]
    #: tainted-failure candidates awaiting second-extent confirmation
    _deferred: Dict[str, Finding] = dataclasses.field(default_factory=dict)


def _aval_str(av) -> str:
    if av is None:
        return "?"
    return f"{tuple(av.shape)} {av.dtype}"


def provenance_chain(sd, names: Sequence[str], env, depth: int = 3
                     ) -> List[str]:
    """Producer chains for ``names``: each line walks var <- op(...)
    up to ``depth`` hops, with the inferred shape/dtype inline — the
    part of a diagnostic that names the USER's variables."""
    lines = []
    for name in names:
        hops = []
        cur = name
        for _ in range(depth):
            av = env.get(cur)
            v = sd._vars.get(cur)
            kind = v.var_type.value if v is not None else "?"
            hops.append(f"{cur} [{kind} {_aval_str(av)}]")
            prod = sd._producer.get(cur)
            if prod is None:
                break
            node = sd._ops[prod]
            hops.append(f"op {prod}({node.op})")
            cur = node.inputs[0] if node.inputs else None
            if cur is None:
                break
        lines.append("<- ".join(hops))
    return lines


def _aval(shape, dtype, weak_type=False):
    """ShapeDtypeStruct preserving ``weak_type`` — a weakly-typed
    stored constant (``sd.constant(0.17)`` under x64) promotes to its
    partner's dtype at runtime; dropping the flag would make the walk
    see a strong f64 and report promotion mismatches the real trace
    never has."""
    return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                weak_type=bool(weak_type))


def _cast_aval(av, dtype):
    wt = getattr(av, "weak_type", False)
    if dtype is not None and jnp.issubdtype(av.dtype, jnp.floating):
        return _aval(av.shape, jnp.dtype(dtype), wt)
    return _aval(av.shape, av.dtype, wt)


def infer_avals(sd, outputs: Sequence[str],
                compute_dtype=None, softmax_dtype=None,
                batch_size: Optional[int] = None) -> GraphFacts:
    """Walk the pruned subgraph for ``outputs`` abstractly.

    ``compute_dtype`` mirrors the MixedPrecision cast the train step
    applies at the top of its trace (params/constants/placeholders cast
    to the compute dtype, state vars stay f32) so the numerics pass
    sees the dtypes XLA will actually run. ``softmax_dtype`` activates
    the CE-tail scope the same way ``_build_step_parts`` does.

    With ``batch_size=None``, ``-1`` placeholder dims get a substitute
    extent; an eval failure downstream of one is only reported after a
    second walk at a DIFFERENT extent reproduces it (see FAKE_BATCH)."""
    facts = _walk(sd, outputs, compute_dtype, softmax_dtype,
                  FAKE_BATCH if batch_size is None else int(batch_size),
                  taint_fakes=batch_size is None)
    if batch_size is None and facts._deferred:
        confirm = _walk(sd, outputs, compute_dtype, softmax_dtype,
                        FAKE_BATCH_CONFIRM, taint_fakes=True)
        for opn, f in facts._deferred.items():
            if opn in confirm._deferred:
                facts.findings.append(f)
    return facts


def _walk(sd, outputs: Sequence[str], compute_dtype, softmax_dtype,
          bsz: int, taint_fakes: bool) -> GraphFacts:
    import contextlib

    findings: List[Finding] = []
    env: Dict[str, Optional[jax.ShapeDtypeStruct]] = {}
    tainted: Set[str] = set()
    deferred: Dict[str, Finding] = {}

    from deeplearning4j_tpu.autodiff.variable import VariableType
    for name, v in sd._vars.items():
        if name in sd._arrays:
            a = sd._arrays[name]
            av = _aval(a.shape, a.dtype, getattr(a, "weak_type", False))
            if compute_dtype is not None and \
                    name not in sd._state_var_names:
                av = _cast_aval(av, compute_dtype)
            env[name] = av
        elif v.var_type == VariableType.PLACEHOLDER:
            shp = v._shape
            if shp is None:
                env[name] = None
                continue
            if any(d == -1 for d in shp):
                if taint_fakes:
                    tainted.add(name)
                shp = tuple(bsz if d == -1 else d for d in shp)
            av = jax.ShapeDtypeStruct(
                tuple(shp), DataType.from_any(v.dtype).jnp)
            env[name] = _cast_aval(av, compute_dtype)

    if softmax_dtype is not None:
        from deeplearning4j_tpu.ops.loss import softmax_dtype_scope
        scope = lambda: softmax_dtype_scope(softmax_dtype)
    else:
        scope = contextlib.nullcontext

    key = jax.random.key(0)       # concrete; only its aval matters
    live = sd._prune(tuple(outputs))
    for idx, node in enumerate(live):
        try:
            o = registry.get_op(node.op)
        except KeyError as e:
            findings.append(finding(
                "graph.undefined_input", node.name, str(e),
                fix_hint="the op name is not in the registry — a "
                         "corrupted/hand-edited graph?"))
            for on in node.outputs:
                env[on] = None
            continue
        missing = [i for i in node.inputs if i not in env]
        if missing:
            findings.append(finding(
                "graph.undefined_input", node.name,
                f"op {node.name!r} ({node.op}) consumes "
                f"{missing} which no variable or op defines",
                fix_hint="declare the variable/placeholder, or fix the "
                         "op's input list",
                provenance=provenance_chain(
                    sd, [i for i in node.inputs if i in env], env)))
            for on in node.outputs:
                env[on] = None
            continue
        in_avals = [env[i] for i in node.inputs]
        node_taint = any(i in tainted for i in node.inputs)
        if node_taint:
            tainted.update(node.outputs)
        if any(a is None for a in in_avals):
            for on in node.outputs:
                env[on] = None
            continue
        attrs = dict(node.attrs)
        if node.random:
            attrs["key"] = jax.random.fold_in(key, idx)
        try:
            with scope():
                res = jax.eval_shape(
                    lambda *a, _fn=o.fn, _at=attrs: _fn(*a, **_at),
                    *in_avals)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.ConcretizationTypeError):
            # structural-tensor attrs need concrete values the abstract
            # tracer can't provide — genuinely uninferable, not a bug
            for on in node.outputs:
                env[on] = None
            continue
        except (TypeError, ValueError) as e:
            for on in node.outputs:
                env[on] = None
            ins = ", ".join(f"{n}={_aval_str(a)}"
                            for n, a in zip(node.inputs, in_avals))
            f = finding(
                "graph.shape_mismatch", node.name,
                f"op {node.name!r} ({node.op}) cannot compose its "
                f"inputs ({ins}): {e}",
                fix_hint="check the named producer shapes below — the "
                         "mismatch is in the graph, not in XLA",
                provenance=provenance_chain(sd, node.inputs, env))
            if node_taint:
                # downstream of a substituted batch extent: report only
                # if the failure reproduces at a second extent (the
                # caller's confirmation walk)
                deferred[node.name] = f
            else:
                findings.append(f)
            continue
        except Exception:
            # an op body that fails abstract eval for exotic reasons is
            # unknown, not a user-facing finding (no false positives)
            for on in node.outputs:
                env[on] = None
            continue
        results = list(res) if isinstance(res, (tuple, list)) else [res]
        for on, r in zip(node.outputs, results):
            env[on] = _aval(r.shape, r.dtype,
                            getattr(r, "weak_type", False)) \
                if hasattr(r, "shape") else None

    facts = GraphFacts(env=env, tainted=tainted,
                       live_ops=[n.name for n in live],
                       outputs=tuple(outputs), findings=findings)
    facts._deferred = deferred
    return facts


# ---------------------------------------------------------------------------
# hygiene passes over the same facts

def check_loss_variables(sd, facts: GraphFacts,
                         loss_names: Sequence[str]) -> List[Finding]:
    from deeplearning4j_tpu.autodiff.variable import VariableType
    out: List[Finding] = []
    for ln in loss_names:
        v = sd._vars.get(ln)
        if v is None:
            out.append(finding(
                "graph.invalid_loss", ln,
                f"loss variable {ln!r} does not exist in the graph",
                fix_hint="set_loss_variables() with an op output name"))
            continue
        if v.var_type != VariableType.ARRAY:
            rid = ("config.donation_conflict"
                   if v.var_type in (VariableType.VARIABLE,
                                     VariableType.CONSTANT)
                   else "graph.invalid_loss")
            out.append(finding(
                rid, ln,
                f"loss variable {ln!r} is a {v.var_type.value}, not an "
                f"op output — it carries no gradient"
                + (" and its donated buffer is read back after the "
                   "step invalidates it"
                   if v.var_type == VariableType.VARIABLE else ""),
                fix_hint="point the loss at the loss op's output"))
            continue
        av = facts.env.get(ln)
        if av is not None and not jnp.issubdtype(av.dtype, jnp.floating):
            out.append(finding(
                "graph.invalid_loss", ln,
                f"loss variable {ln!r} has dtype {av.dtype} — gradients "
                f"need a floating loss",
                fix_hint="cast the loss to float32 before reducing",
                provenance=provenance_chain(sd, [ln], facts.env)))
    return out


def check_placeholder_hygiene(sd, facts: GraphFacts,
                              restrict_to: Optional[Sequence[str]] = None
                              ) -> List[Finding]:
    """unused_placeholder + name_shadowing over the live subgraph.

    ``restrict_to`` scopes the unused check to a declared input set
    (the serving contract): a graph sliced out of a training graph
    legitimately carries the label placeholders of its training half,
    so only the inputs the caller SAYS it will feed are checked."""
    from deeplearning4j_tpu.autodiff.variable import VariableType
    out: List[Finding] = []
    consumed: Set[str] = set()
    for opn in facts.live_ops:
        consumed.update(sd._ops[opn].inputs)
    phs = [n for n, v in sd._vars.items()
           if v.var_type == VariableType.PLACEHOLDER]
    check = phs if restrict_to is None else \
        [p for p in phs if p in set(restrict_to)]
    for ph in check:
        if ph not in consumed and ph not in facts.outputs:
            out.append(finding(
                "graph.unused_placeholder", ph,
                f"placeholder {ph!r} is not consumed by any op "
                f"contributing to outputs {list(facts.outputs)}",
                fix_hint="remove it, or wire it into the graph — data "
                         "fed to it is silently dropped"))
    ph_set = set(phs)
    for ph in phs:
        base, _, suffix = ph.rpartition("_")
        if base and suffix.isdigit() and base in ph_set:
            out.append(finding(
                "graph.name_shadowing", ph,
                f"placeholder {ph!r} was auto-renamed from {base!r} "
                f"(both exist) — feeds keyed {base!r} reach only the "
                f"first",
                fix_hint="give each placeholder a distinct explicit "
                         "name"))
    return out


def check_dead_ops(sd, facts: GraphFacts) -> List[Finding]:
    """Dead subgraphs, scoped to the high-signal case: a recorded
    LOSS-category op contributing to none of the requested outputs is
    near-certainly a forgotten ``loss_variables`` entry — the penalty
    term trains nothing, silently. (Generic dead ops are usually the
    benign inference head — e.g. the softmax activation a training
    graph prunes but ``output(training=True)`` still fetches — so they
    are not reported.)"""
    live = set(facts.live_ops)
    out: List[Finding] = []
    for opn in sd._op_order:
        if opn in live:
            continue
        node = sd._ops[opn]
        try:
            o = registry.get_op(node.op)
        except KeyError:
            continue
        if o.category == "loss":
            out.append(finding(
                "graph.dead_op", opn,
                f"loss op {opn!r} ({node.op}) contributes to none of "
                f"the requested outputs {list(facts.outputs)} — the "
                f"penalty is computed nowhere and trains nothing",
                fix_hint="add its output to set_loss_variables(), or "
                         "remove the op"))
    return out


def check_state_updates(sd, facts: GraphFacts) -> List[Finding]:
    from deeplearning4j_tpu.autodiff.variable import VariableType
    out: List[Finding] = []
    for sv, src in sd._state_updates.items():
        if src not in sd._vars:
            out.append(finding(
                "graph.state_alias", sv,
                f"state var {sv!r} updates from {src!r}, which does "
                f"not exist",
                fix_hint="update_state() with an op output"))
        elif src == sv:
            out.append(finding(
                "graph.state_alias", sv,
                f"state var {sv!r} updates from itself — the update "
                f"is a no-op",
                fix_hint="point the update at the op computing the "
                         "new statistics"))
        elif sd._vars[src].var_type == VariableType.PLACEHOLDER:
            out.append(finding(
                "graph.state_alias", sv,
                f"state var {sv!r} updates from placeholder {src!r} — "
                f"raw fed data would overwrite the running statistics",
                fix_hint="update from the op output that folds the "
                         "batch statistics in"))
    return out


__all__ = ["GraphFacts", "infer_avals", "provenance_chain",
           "check_loss_variables", "check_placeholder_hygiene",
           "check_dead_ops", "check_state_updates", "FAKE_BATCH"]
