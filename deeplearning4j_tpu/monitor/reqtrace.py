"""Request-scoped distributed tracing + fleet SLO tracking.

The substrate traces host wall time per *process* (monitor/trace.py);
the serving tier is a *fleet* (serving/fleet/): one request crosses
router -> replica -> prefill -> N decode rounds -> stream delivery,
may be shed and retried, failed over to a survivor mid-stream, or
replayed from the journal after a router crash. Nothing tied those
segments together. This module is the Dapper-style rail that does:

- :class:`TraceContext` — ``trace_id`` (the fleet request id) plus a
  segment counter, minted by ``FleetRouter.generate()`` and carried
  through EVERY hop: retries, failovers, ``submit_continuation``
  resumes and ``recover()`` replays all reuse the SAME trace_id with a
  new segment. Down in the server the existing ``serving.*`` spans get
  tagged ``trace_id=/segment=``, and batch-level decode/verify spans
  record the slot->trace_id occupancy map (``slots=``) so per-request
  time inside a shared dispatch is attributable proportionally
  (``dur / n_occupied_slots`` — the Orca/vLLM iteration-level
  scheduling problem: one dispatch serves many requests).
- :func:`assemble` — host-side waterfall assembly from drained spans:
  queue_wait / admission / prefill / per-round decode / speculation
  verify / stream-delivery phases, with retry/failover segments
  (``fleet.attempt`` spans) linked in wall-clock order.
- :class:`RequestTracer` — the sampling collector: head-sample a
  configurable fraction (deterministic in trace_id), but ALWAYS keep
  traces that breach the SLO or end in retry/failover/shed (tail-based
  keep), into a bounded LRU of assembled waterfalls. Exported as a
  Perfetto lane-per-request view (:meth:`RequestTracer.to_chrome_trace`)
  and over ``GET /requesttrace?id=`` (monitor/server.py).
- :class:`SLOTracker` — per-request outcome records (TTFT, e2e, tokens,
  replica, retries, resumes, shed/ok/failed) in a rolling window ->
  SLO attainment + error-budget burn rate per objective. Rides the
  ``{"type": "fleet"}`` record as its ``"slo"`` sub-dict (no new record
  type), folds to ``dl4j_fleet_slo_*`` gauges, serves at ``GET /slo``
  and renders as the report's SLO panel.

Everything here is host-side accounting over spans that never touch
device state: the standing contract holds — clean serving runs are
bit-identical with request tracing on or off, and the whole rail is
inert (no span buffering, no assembly) while the shared tracer is
disabled. ``bench.py reqtrace_overhead`` guards <=3% on the fleet
loadgen loop. See docs/observability.md ("Request tracing & SLOs").
"""
from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from deeplearning4j_tpu.monitor.trace import TRACER, Span, Tracer


# ----------------------------------------------------------------------
# propagation

class TraceContext:
    """The per-request trace identity carried across every hop.

    ``trace_id`` is the fleet request id (also the journal key and the
    pinned sampling seed — one id names the request everywhere).
    ``segment`` is the ordinal of the CURRENT attempt: the router calls
    :meth:`next_segment` per attempt, so a retry, a failover resume and
    a recover() replay each tag their spans with a fresh segment while
    keeping the trace_id. Segment numbering restarts per context (a
    replay in a restarted process starts at 0 again); waterfall
    assembly orders segments by wall-clock, not by number.
    """

    __slots__ = ("trace_id", "segment", "sampled", "origin", "_n")

    def __init__(self, trace_id: int, sampled: bool = False,
                 origin: str = "live"):
        self.trace_id = int(trace_id)
        self.sampled = bool(sampled)
        self.origin = str(origin)       # "live" | "replay"
        self.segment = 0
        self._n = 0

    def next_segment(self) -> int:
        """Advance to (and return) the next segment ordinal — one call
        per placement attempt."""
        self.segment = self._n
        self._n += 1
        return self.segment

    @property
    def segments_minted(self) -> int:
        """How many attempts have taken a segment so far (0 before the
        first :meth:`next_segment` — a count, not an ordinal)."""
        return self._n

    def span_args(self) -> dict:
        """The args every span on this hop gets tagged with."""
        return {"trace_id": self.trace_id, "segment": self.segment}

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"TraceContext(trace_id={self.trace_id}, "
                f"segment={self.segment}, sampled={self.sampled}, "
                f"origin={self.origin!r})")


def head_sampled(trace_id: int, fraction: float) -> bool:
    """Deterministic head-sampling decision: a pure function of
    ``trace_id`` (NOT a random draw — the same request replays to the
    same decision on every router, which is what makes cross-process
    sampling coherent)."""
    if fraction >= 1.0:
        return True
    if fraction <= 0.0:
        return False
    h = hashlib.blake2b(str(int(trace_id)).encode("ascii"),
                        digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64 < float(fraction)


# ----------------------------------------------------------------------
# the ONE attainment definition (satellite: bench rows and the
# SLOTracker must not disagree about what "met the SLO" means)

def slo_attainment(records: Iterable[Tuple[str, Optional[float]]],
                   target_ms: float) -> float:
    """Fraction of requests that met the objective.

    ``records`` is ``(status, value_ms)`` pairs. A request attains iff
    ``status == "ok"`` AND its measured value is ``<= target_ms``; any
    non-ok outcome (shed, failed, timed out) is a miss — a request the
    fleet dropped did not meet its SLO. Ok records with no measurement
    (e.g. a zero-token generation has no TTFT) are excluded from the
    denominator. Empty input -> 1.0 (vacuous attainment)."""
    n = hit = 0
    for status, value in records:
        if status == "ok" and value is None:
            continue
        n += 1
        if status == "ok" and float(value) <= float(target_ms):
            hit += 1
    return (hit / n) if n else 1.0


def _pct(values: List[float], p: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    k = min(len(vs) - 1, max(0, int(round(p / 100.0 * (len(vs) - 1)))))
    return float(vs[k])


# ----------------------------------------------------------------------
# SLO tracking

class SLOTracker:
    """Rolling-window SLO attainment + error-budget burn rate.

    ``objectives`` maps an outcome field (``"ttft_ms"`` / ``"e2e_ms"``)
    to its target. ``error_budget`` is the allowed miss fraction (0.01
    = a 99% objective); ``burn_rate`` is the window's miss fraction
    over that budget — 1.0 means burning budget exactly as provisioned,
    >1 means the error budget is being spent faster than it accrues.
    Thread-safe (the router records from concurrent request threads).
    """

    def __init__(self, objectives: Optional[Dict[str, float]] = None,
                 window: int = 512, error_budget: float = 0.01,
                 worst_k: int = 5):
        self.objectives = dict(objectives if objectives is not None
                               else {"ttft_ms": 2000.0,
                                     "e2e_ms": 10000.0})
        self.error_budget = max(1e-9, float(error_budget))
        self.worst_k = int(worst_k)
        self._lock = threading.Lock()
        self._window: "collections.deque[dict]" = \
            collections.deque(maxlen=int(window))
        self.counts = {"ok": 0, "failed": 0, "timed_out": 0, "shed": 0}
        self.total = 0
        self._worst: List[dict] = []    # worst-TTFT sampled waterfalls

    # -- recording ------------------------------------------------------
    def record(self, status: str, *, ttft_ms: Optional[float] = None,
               e2e_ms: Optional[float] = None, tokens: int = 0,
               replica: Optional[str] = None, retries: int = 0,
               resumes: int = 0, trace_id: Optional[int] = None) -> dict:
        """Record one request outcome; returns the stored record."""
        rec = {"status": str(status), "ttft_ms": ttft_ms,
               "e2e_ms": e2e_ms, "tokens": int(tokens),
               "replica": replica, "retries": int(retries),
               "resumes": int(resumes), "trace_id": trace_id}
        with self._lock:
            self._window.append(rec)
            self.counts[status] = self.counts.get(status, 0) + 1
            self.total += 1
        return rec

    def breached(self, outcome: dict) -> bool:
        """True when this outcome missed ANY objective (the tail-keep
        trigger): every non-ok status breaches; an ok outcome breaches
        when a measured value exceeds its target."""
        if outcome.get("status") != "ok":
            return True
        for field, target in self.objectives.items():
            v = outcome.get(field)
            if v is not None and float(v) > float(target):
                return True
        return False

    def note_waterfall(self, waterfall: dict) -> None:
        """Keep the worst-TTFT sampled waterfalls' breakdowns (what the
        report's SLO panel shows next to the percentiles)."""
        entry = {"trace_id": waterfall.get("trace_id"),
                 "ttft_ms": waterfall.get("ttft_ms"),
                 "e2e_ms": waterfall.get("e2e_ms"),
                 "replica": waterfall.get("replica"),
                 "retries": waterfall.get("retries", 0),
                 "kept": waterfall.get("kept"),
                 "breakdown": ttft_breakdown(waterfall)}
        with self._lock:
            self._worst.append(entry)
            self._worst.sort(key=lambda e: -(e["ttft_ms"] or 0.0))
            del self._worst[self.worst_k:]

    # -- readout --------------------------------------------------------
    def attainment(self, field: str) -> float:
        target = self.objectives[field]
        with self._lock:
            recs = [(r["status"], r.get(field)) for r in self._window]
        return slo_attainment(recs, target)

    def burn_rate(self, field: str) -> float:
        return (1.0 - self.attainment(field)) / self.error_budget

    def to_dict(self) -> dict:
        """The ``"slo"`` sub-dict of the ``{"type": "fleet"}`` record."""
        with self._lock:
            win = list(self._window)
            counts = dict(self.counts)
            total = self.total
            worst = [dict(e) for e in self._worst]
        out = {"window": len(win), "total": total, "outcomes": counts,
               "error_budget": self.error_budget, "objectives": {},
               "worst_traces": worst}
        for field, target in self.objectives.items():
            vals = [float(r[field]) for r in win
                    if r.get(field) is not None]
            att = slo_attainment(
                [(r["status"], r.get(field)) for r in win], target)
            out["objectives"][field] = {
                "target_ms": float(target),
                "n": len(vals),
                "attainment": round(att, 6),
                "burn_rate": round((1.0 - att) / self.error_budget, 4),
                "p50_ms": round(_pct(vals, 50), 3),
                "p99_ms": round(_pct(vals, 99), 3)}
        return out


# ----------------------------------------------------------------------
# waterfall assembly

#: span names whose batch-level dispatch carries a slot->trace_id map
_SHARED_SPANS = ("serving.decode", "serving.draft", "serving.verify")


def _ms(seconds: float) -> float:
    return round(seconds * 1000.0, 4)


def assemble(spans: Iterable[Span], trace_id: int,
             outcome: Optional[dict] = None) -> dict:
    """Build one request's waterfall from a span set.

    Selects spans tagged ``trace_id=`` (enqueue/prefill/reply and the
    router's ``fleet.attempt`` segments) plus batch-level spans whose
    ``slots=`` occupancy map contains the trace — those contribute
    ``dur / n_occupied_slots`` (proportional attribution: the dispatch
    served that many requests at once). Returns a JSON-ready dict:
    ``segments`` (retry/failover/replay attempts in wall-clock order),
    ``phases`` (queue_wait/admission/prefill/decode/verify/reply
    totals + per-round counts), and a compact ``spans`` list for lane
    rendering. ``outcome`` (the router's measurement) is merged in as
    the authoritative ttft/e2e."""
    tid = int(trace_id)
    mine: List[Span] = []
    shared: List[Tuple[Span, int]] = []
    for s in spans:
        args = s.args
        if args.get("trace_id") == tid:
            mine.append(s)
        elif s.name in _SHARED_SPANS:
            slots = args.get("slots")
            if isinstance(slots, dict) and tid in slots.values():
                shared.append((s, max(1, len(slots))))
    all_spans = mine + [s for s, _ in shared]
    t0 = min((s.t0 for s in all_spans), default=0.0)

    def named(name):
        return sorted((s for s in mine if s.name == name),
                      key=lambda s: s.t0)

    segments = []
    for s in named("fleet.attempt"):
        segments.append({"segment": s.args.get("segment"),
                         "kind": s.args.get("kind"),
                         "replica": s.args.get("replica"),
                         "outcome": s.args.get("outcome"),
                         "error": s.args.get("error"),
                         "start_ms": _ms(s.t0 - t0),
                         "dur_ms": _ms(s.dur)})

    enq = named("serving.enqueue")
    pre = named("serving.prefill")
    rep = named("serving.reply")
    by_shared: Dict[str, List[Tuple[Span, int]]] = {}
    for s, n in sorted(shared, key=lambda sn: sn[0].t0):
        by_shared.setdefault(s.name, []).append((s, n))

    queue_wait = 0.0
    if enq and pre:
        queue_wait = max(0.0, pre[0].t0 - (enq[0].t0 + enq[0].dur))
    decodes = by_shared.get("serving.decode", [])
    phases = {
        "queue_wait_ms": _ms(queue_wait),
        "admission_ms": _ms(sum(s.dur for s in enq)),
        "prefill_ms": _ms(sum(s.dur for s in pre)),
        "decode_ms": _ms(sum(s.dur / n for s, n in decodes)),
        "decode_rounds": len(decodes),
        "first_decode_ms": _ms(decodes[0][0].dur / decodes[0][1])
        if decodes else 0.0,
        "draft_ms": _ms(sum(s.dur / n for s, n in
                            by_shared.get("serving.draft", []))),
        "verify_ms": _ms(sum(s.dur / n for s, n in
                             by_shared.get("serving.verify", []))),
        "verify_rounds": len(by_shared.get("serving.verify", [])),
        "reply_ms": _ms(sum(s.dur for s in rep)),
    }

    lanes = []
    for s in sorted(mine, key=lambda s: s.t0):
        lanes.append({"name": s.name, "cat": s.cat,
                      "start_ms": _ms(s.t0 - t0), "dur_ms": _ms(s.dur),
                      "segment": s.args.get("segment"), "share": 1.0})
    for s, n in sorted(shared, key=lambda sn: sn[0].t0):
        lanes.append({"name": s.name, "cat": s.cat,
                      "start_ms": _ms(s.t0 - t0), "dur_ms": _ms(s.dur),
                      "segment": s.args.get("segment"),
                      "share": round(1.0 / n, 4)})

    wf = {"trace_id": tid, "t0_s": t0, "n_spans": len(all_spans),
          "segments": segments, "phases": phases, "spans": lanes}
    if outcome:
        for k in ("status", "ttft_ms", "e2e_ms", "tokens", "replica",
                  "retries", "resumes", "origin"):
            if k in outcome:
                wf[k] = outcome[k]
    return wf


def ttft_breakdown(waterfall: dict) -> dict:
    """Where the time-to-first-token went (the loadgen row field)."""
    ph = waterfall.get("phases") or {}
    return {k: ph.get(k, 0.0)
            for k in ("queue_wait_ms", "prefill_ms", "first_decode_ms")}


# ----------------------------------------------------------------------
# the sampling collector

class RequestTracer:
    """Per-router collector: buffers a live tracer's spans per open
    trace, decides keep (head-sample OR tail-based: SLO breach /
    retry / failover / shed), assembles kept waterfalls into a bounded
    LRU.

    Inert while the tracer is disabled: :meth:`begin` returns an
    unsampled context and buffers nothing, so the disabled path costs
    one attribute check per request. ``max_spans_per_trace`` bounds the
    per-request buffer; overflow drops the OLDEST spans (the tail of a
    long generation matters more than its middle) and is counted in
    ``spans_dropped``.
    """

    def __init__(self, tracer: Optional[Tracer] = None,
                 sample: float = 1.0, capacity: int = 64,
                 max_spans_per_trace: int = 2048,
                 slo: Optional[SLOTracker] = None):
        self.tracer = tracer if tracer is not None else TRACER
        self.sample = float(sample)
        self.capacity = int(capacity)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.slo = slo
        self._lock = threading.Lock()
        self._cursor = self.tracer.mark()
        self._open: Dict[int, "collections.deque[Span]"] = {}
        self._kept: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self.spans_dropped = 0          # per-trace buffer overflow
        self.ring_dropped = 0           # evicted from the ring unseen

    @property
    def active(self) -> bool:
        return self.tracer.enabled

    # -- lifecycle ------------------------------------------------------
    def begin(self, trace_id: int, origin: str = "live") -> TraceContext:
        """Mint the context for one request; opens a span buffer when
        the tracer is recording."""
        ctx = TraceContext(trace_id,
                           sampled=head_sampled(trace_id, self.sample),
                           origin=origin)
        if self.tracer.enabled:
            with self._lock:
                self._open[ctx.trace_id] = collections.deque(
                    maxlen=self.max_spans_per_trace)
        return ctx

    def _collect_locked(self) -> None:
        spans, self._cursor, dropped = self.tracer.drain(self._cursor)
        self.ring_dropped += dropped
        if not self._open:
            return
        for s in spans:
            args = s.args
            tid = args.get("trace_id")
            buf = self._open.get(tid) if isinstance(tid, int) else None
            if buf is not None:
                if len(buf) == buf.maxlen:
                    self.spans_dropped += 1
                buf.append(s)
                continue
            if s.name in _SHARED_SPANS:
                slots = args.get("slots")
                if isinstance(slots, dict):
                    for occupant in set(slots.values()):
                        buf = self._open.get(occupant)
                        if buf is not None:
                            if len(buf) == buf.maxlen:
                                self.spans_dropped += 1
                            buf.append(s)

    def collect(self) -> None:
        """Drain new spans from the tracer into the open-trace buffers
        (also called implicitly by :meth:`finish`)."""
        with self._lock:
            self._collect_locked()

    def finish(self, ctx: TraceContext,
               outcome: dict) -> Optional[dict]:
        """Close one request's trace: collect its spans, decide keep
        (head sample OR tail-based), assemble and retain the waterfall.
        Returns the waterfall when kept, else None."""
        with self._lock:
            self._collect_locked()
            buf = self._open.pop(ctx.trace_id, None)
        if buf is None:                 # tracing was off at begin()
            return None
        keep = ctx.sampled
        why = "head"
        if not keep:
            tail = (outcome.get("status") != "ok"
                    or int(outcome.get("retries") or 0) > 0
                    or int(outcome.get("resumes") or 0) > 0
                    or (self.slo is not None
                        and self.slo.breached(outcome)))
            if tail:
                keep, why = True, "tail"
        if not keep:
            return None
        wf = assemble(buf, ctx.trace_id, outcome)
        wf["kept"] = why
        with self._lock:
            self._kept[ctx.trace_id] = wf
            self._kept.move_to_end(ctx.trace_id)
            while len(self._kept) > self.capacity:
                self._kept.popitem(last=False)
        if self.slo is not None:
            self.slo.note_waterfall(wf)
        return wf

    # -- readout --------------------------------------------------------
    def get(self, trace_id: int) -> Optional[dict]:
        with self._lock:
            return self._kept.get(int(trace_id))

    def waterfalls(self) -> List[dict]:
        """Kept waterfalls, oldest first."""
        with self._lock:
            return list(self._kept.values())

    def summaries(self) -> List[dict]:
        """One index row per kept waterfall (the /requesttrace list)."""
        out = []
        for wf in self.waterfalls():
            out.append({"trace_id": wf["trace_id"],
                        "status": wf.get("status"),
                        "kept": wf.get("kept"),
                        "ttft_ms": wf.get("ttft_ms"),
                        "e2e_ms": wf.get("e2e_ms"),
                        "replica": wf.get("replica"),
                        "retries": wf.get("retries", 0),
                        "segments": len(wf.get("segments") or ()),
                        "n_spans": wf.get("n_spans", 0)})
        return out

    def to_chrome_trace(self,
                        trace_id: Optional[int] = None) -> dict:
        """Perfetto lane-per-REQUEST view (the process tracer's export
        is lane-per-thread): each kept waterfall renders on its own
        ``tid`` lane named after the trace, on a shared timeline, so
        retries and failovers line up across requests."""
        wfs = ([self.get(trace_id)] if trace_id is not None
               else self.waterfalls())
        wfs = [wf for wf in wfs if wf]
        events: List[dict] = []
        for wf in wfs:
            tid = wf["trace_id"]
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": tid,
                           "args": {"name": f"request {tid}"}})
            base_us = wf.get("t0_s", 0.0) * 1e6
            for lane in wf.get("spans") or ():
                ev = {"name": lane["name"], "ph": "X",
                      "ts": round(base_us + lane["start_ms"] * 1000.0, 3),
                      "dur": round(lane["dur_ms"] * 1000.0, 3),
                      "pid": 0, "tid": tid,
                      "args": {"segment": lane.get("segment"),
                               "share": lane.get("share", 1.0)}}
                if lane.get("cat"):
                    ev["cat"] = lane["cat"]
                events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"requests": len(wfs),
                              "spans_dropped": self.spans_dropped,
                              "ring_dropped": self.ring_dropped}}


__all__ = ["TraceContext", "RequestTracer", "SLOTracker", "assemble",
           "ttft_breakdown", "slo_attainment", "head_sampled"]
