"""monitor/ — the observability spine: trace spans, a unified metrics
registry, and per-window step-time attribution.

Reference parity: the deeplearning4j-ui stats pipeline answers "how is
training going"; this subsystem answers "where did the time go" —
always-on, cheap, and unified across training (fused windows and the
per-step tier), serving, checkpointing and the fault rail:

- :mod:`monitor.trace` — a thread-safe ring-buffered span tracer with
  a near-zero-cost disabled path and Chrome/Perfetto trace export; the
  hot paths are permanently instrumented (window executor stages,
  serving request lifecycle, checkpoint commits, rollback/retry).
- :mod:`monitor.registry` — labeled counters/gauges/histograms folding
  every subsystem's counters into one namespace, with Prometheus text
  export and ``{"type": "metrics"}`` StatsStorage records.
- :mod:`monitor.steptime` — per-window data-wait/dispatch/flush
  breakdowns computed from spans at existing flush boundaries (no
  extra device syncs; clean runs stay bit-identical), rolling
  percentiles, and a straggler watcher.
- :mod:`monitor.tensorstats` — in-graph per-layer gradient/update/
  param summaries (norms, nonfinite counts, log2-magnitude histograms)
  sampled inside the compiled step, folded into the scan carry like
  the divergence sentinel; plus the dead/exploding-layer watcher.
- :mod:`monitor.server` — the live telemetry HTTP endpoint
  (``monitor.serve(port=0)``): /metrics, /healthz, /readyz, /report,
  /trace, /stats over a stdlib ThreadingHTTPServer, loopback-bound.

See docs/observability.md.
"""
from deeplearning4j_tpu.monitor import memstats
from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.monitor.server import (TelemetryServer,
                                               health_snapshot, serve)
from deeplearning4j_tpu.monitor.steptime import (MonitorListener,
                                                 RollingPercentiles,
                                                 StragglerWatcher,
                                                 window_rows)
from deeplearning4j_tpu.monitor.tensorstats import (LayerHealthWatcher,
                                                    TensorStatsConfig)
from deeplearning4j_tpu.monitor.trace import (TRACER, Span, Tracer,
                                              disable_tracing,
                                              enable_tracing, get_tracer)

__all__ = ["TRACER", "Span", "Tracer", "get_tracer", "enable_tracing",
           "disable_tracing", "MetricsRegistry", "MonitorListener",
           "RollingPercentiles", "StragglerWatcher", "window_rows",
           "TensorStatsConfig", "LayerHealthWatcher", "TelemetryServer",
           "serve", "health_snapshot"]
