"""Per-window step-time attribution: data-wait vs dispatch vs flush.

BENCH_r05 found the per-step fit tier dispatch-bound (~1.8 % MFU on
lenet) by HAND-instrumenting the loop; this module makes that breakdown
a standing observable. The window executor (autodiff/window.py) and the
per-step tier (samediff.fit) already emit ``window``/``step`` spans
with ``data_wait`` / ``dispatch`` / ``flush`` children into
``monitor.trace.TRACER``; :class:`MonitorListener` drains those spans
at the flush boundaries the host ALREADY syncs on — no extra device
syncs, so a clean run's losses stay bit-identical with monitoring on
or off (asserted in tests/test_monitor.py) — and publishes:

- ``{"type": "steptime"}`` breakdown records (per listener flush:
  wall seconds attributed to data-wait / dispatch / flush / other,
  rolling step-time percentiles) into the run's StatsStorage, rendered
  by ui/report.py as a stacked chart;
- ``{"type": "metrics"}`` registry snapshots at epoch boundaries;
- ``{"type": "trace"}`` span dumps (bounded) at training end, rendered
  as the report's swimlane timeline;
- straggler flags: :class:`StragglerWatcher` keeps an EMA of step time
  and records a ``{"type": "steptime", "event": "straggler"}`` record
  when a window's per-step time spikes past ``threshold ×`` the EMA —
  the step-time rail analogous to the faults rail's LossSpikeWatcher.

Semantics of the stages (host wall time, per window):

- ``data_wait`` — the consumer blocked on the stager queue / iterator
  (a data-bound run shows this dominating);
- ``dispatch``  — enqueueing the compiled window program (async; this
  is HOST dispatch overhead, not device compute — a dispatch-bound run
  shows many short windows with high dispatch share);
- ``flush``     — the device→host loss-burst sync at listener
  boundaries (the only place a healthy fused run actually waits on the
  device, so device-bound time surfaces here);
- ``other``     — window wall time not inside any child span
  (listener callbacks, checkpoint capture on the training thread, …).
"""
from __future__ import annotations

import bisect
import time
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.monitor.registry import MetricsRegistry
from deeplearning4j_tpu.monitor.trace import TRACER, Span, Tracer

#: span names treated as one attributed training step unit
_WINDOW_NAMES = ("window", "step")
_STAGE_NAMES = ("data_wait", "dispatch", "flush")


def window_rows(spans: Sequence[Span]) -> List[dict]:
    """Group a span batch into per-window rows: each ``window``/``step``
    span plus the stage children recorded under it. Returns dicts with
    ``k`` (steps in the window), ``dur_s``, per-stage seconds and the
    derived ``other_s``."""
    rows: Dict[int, dict] = {}
    for sp in spans:
        if sp.name in _WINDOW_NAMES:
            rows[sp.sid] = {
                "name": sp.name, "sid": sp.sid, "t0": sp.t0,
                "dur_s": sp.dur, "k": int(sp.args.get("k", 1)),
                "iteration": sp.args.get("iteration"),
                **{f"{s}_s": 0.0 for s in _STAGE_NAMES}}
    for sp in spans:
        if sp.name in _STAGE_NAMES and sp.parent in rows:
            rows[sp.parent][f"{sp.name}_s"] += sp.dur
    out = []
    for row in sorted(rows.values(), key=lambda r: r["t0"]):
        row["other_s"] = max(0.0, row["dur_s"] - sum(
            row[f"{s}_s"] for s in _STAGE_NAMES))
        out.append(row)
    return out


class RollingPercentiles:
    """Rolling-window order statistics over the last ``window`` values
    (bisect-maintained sorted list: O(log n) insert, O(1) percentile)."""

    def __init__(self, window: int = 512):
        self.window = int(window)
        self._ring: List[float] = []
        self._sorted: List[float] = []
        self._next = 0

    def add(self, value: float) -> None:
        v = float(value)
        if len(self._ring) < self.window:
            self._ring.append(v)
        else:
            old = self._ring[self._next]
            del self._sorted[bisect.bisect_left(self._sorted, old)]
            self._ring[self._next] = v
            self._next = (self._next + 1) % self.window
        bisect.insort(self._sorted, v)

    def __len__(self) -> int:
        return len(self._sorted)

    def percentile(self, p: float) -> float:
        if not self._sorted:
            return 0.0
        idx = min(len(self._sorted) - 1,
                  max(0, int(round(p / 100.0 * (len(self._sorted) - 1)))))
        return self._sorted[idx]


class StragglerWatcher:
    """EMA step-time spike detector.

    ``observe(step_s, ...)`` returns a straggler event dict (and
    optionally records it) when a step time exceeds ``threshold ×`` the
    exponential moving average, after ``warmup`` observations. State
    resets via ``reset()`` — FaultTolerantFit calls it on rollback so
    replayed timelines are judged fresh (same contract as the faults
    watchers)."""

    def __init__(self, threshold: float = 3.0, alpha: float = 0.1,
                 warmup: int = 8, storage=None):
        if threshold <= 1.0:
            raise ValueError("threshold must be > 1 (it multiplies the "
                             "EMA)")
        self.threshold = float(threshold)
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.storage = storage
        self.events: List[dict] = []
        self.reset()

    def reset(self) -> None:
        self._ema: Optional[float] = None
        self._seen = 0

    def observe(self, step_s: float, iteration=None,
                k: int = 1) -> Optional[dict]:
        step_s = float(step_s)
        self._seen += 1
        ema = self._ema
        if ema is not None and self._seen > self.warmup and \
                step_s > self.threshold * ema:
            ev = {"type": "steptime", "event": "straggler",
                  "t": time.time(), "step_s": round(step_s, 6),
                  "ema_s": round(ema, 6),
                  "ratio": round(step_s / ema, 3), "k": int(k)}
            if iteration is not None:
                ev["iteration"] = int(iteration)
            self.events.append(ev)
            if self.storage is not None:
                self.storage.put(ev)
            # the spike does NOT feed the EMA: one straggler must not
            # raise the bar for detecting the next one
            return ev
        self._ema = step_s if ema is None else \
            (1.0 - self.alpha) * ema + self.alpha * step_s
        return None


class MonitorListener:
    """The observability listener: span-fed step-time breakdowns,
    straggler flags, and metrics-registry snapshots, all riding the
    flush boundaries fit() already syncs on.

    ::

        enable_tracing()
        mon = MonitorListener(storage)
        sd.fit(it, epochs=3, listeners=[mon, ...])
        write_report(storage, "report.html")   # timeline + breakdown

    Works on every fit tier that delivers listener bursts (fused
    windows and per-step; the scanned tier has no listeners by
    definition). With tracing disabled it degrades to publishing
    dispatch-derived metrics only — it never forces a device sync
    either way.
    """

    needs_params = False

    def __init__(self, storage, registry: Optional[MetricsRegistry] = None,
                 tracer: Optional[Tracer] = None, frequency: int = 10,
                 straggler: Optional[StragglerWatcher] = None,
                 rolling_window: int = 512, trace_record_spans: int = 400,
                 serve_port: Optional[int] = None,
                 serve_host: str = "127.0.0.1",
                 memory: bool = True):
        self.storage = storage
        self.registry = registry if registry is not None else \
            MetricsRegistry()
        self.tracer = tracer if tracer is not None else TRACER
        self.frequency = max(1, int(frequency))
        self.straggler = straggler
        if self.straggler is not None and self.straggler.storage is None:
            self.straggler.storage = storage
        self.rolling = RollingPercentiles(rolling_window)
        self.trace_record_spans = int(trace_record_spans)
        self._mark = 0
        self._dropped = 0
        self._compile_snap: Optional[dict] = None
        # live telemetry endpoint (monitor/server.py): serve_port=0
        # picks a free port; the server shares this listener's storage,
        # registry and tracer, and stays up after training ends (a
        # dashboard scraping between fits must not 404). None = off.
        self._serve_port = serve_port
        self._serve_host = serve_host
        self.server = None
        self._last_flush_t: Optional[float] = None
        self._last_iteration: Optional[int] = None
        # HBM telemetry (monitor/memstats.py): one {"type": "memory"}
        # record per listener flush — pure host reads at boundaries the
        # host ALREADY syncs on, so clean runs stay bit-identical —
        # plus plan capture for lazily-compiled programs and the live
        # MFU-estimate gauge. memory=False turns the whole rail off.
        self.memory = bool(memory)
        # streaming-pipeline telemetry (datapipe/): (pipeline id,
        # cumulative-counter snapshot) for per-flush deltas; None until
        # the first flush sees a registered pipeline
        self._datapipe_snap: Optional[tuple] = None
        self._published_plans: set = set()
        # id -> report (the ref pins the object so a recycled id can't
        # suppress a fresh report's publish); bounded FIFO — a
        # long-lived listener over many graph versions must not pin
        # every report forever
        self._published_analyses: dict = {}
        self._published_analyses_cap = 32

    def reset(self) -> None:
        """Rollback hook (faults/recovery.py resets stateful listeners):
        discard EMA/rolling state from the abandoned timeline."""
        self.rolling = RollingPercentiles(self.rolling.window)
        if self.straggler is not None:
            self.straggler.reset()

    # -- listener protocol ----------------------------------------------
    def on_training_start(self, sd) -> None:
        self._mark = self.tracer.mark()
        # static-analysis findings (analyze/): fit() stores its report
        # on the graph before listeners start — publish each report
        # ONCE (repeat fits of the same graph version reuse the cached
        # report object) and fold through the storage's incremental
        # fold mark like every other record
        report = getattr(sd, "last_analysis", None)
        if report is not None and id(report) not in self._published_analyses:
            self._published_analyses[id(report)] = report
            while len(self._published_analyses) > \
                    self._published_analyses_cap:
                self._published_analyses.pop(
                    next(iter(self._published_analyses)))
            self.storage.put(report.to_record())
            self.registry.fold_storage(self.storage)
        if self.memory:
            # arm lazy-compile plan capture: a monitored fit's first
            # dispatch per shape compiles through the AOT path (same
            # lowering, one compile either way) so its memory plan —
            # and the MFU numerator — is inspectable
            from deeplearning4j_tpu.monitor import memstats
            memstats.enable_plan_capture()
        if self._serve_port is not None and self.server is None:
            from deeplearning4j_tpu.monitor.server import TelemetryServer
            self.server = TelemetryServer(
                storage=self.storage, registry=self.registry,
                tracer=self.tracer, host=self._serve_host,
                port=self._serve_port)
            self.server.add_health_provider("training", self._heartbeat)

    def on_epoch_start(self, sd, epoch: int) -> None:
        pass

    def _heartbeat(self) -> dict:
        """Health-provider payload for the telemetry server: the wall
        time and iteration of the last listener flush — /healthz's
        last-step-age source that works even before any record with a
        wall timestamp lands in the storage."""
        out = {}
        if self._last_flush_t is not None:
            out["last_step_t"] = self._last_flush_t
        if self._last_iteration is not None:
            out["last_iteration"] = self._last_iteration
        return out

    def tensorstats_done(self, sd, epoch: int, records) -> None:
        """The tensorstats rail (monitor/tensorstats.py): persist every
        fetched per-layer record and fold it into ``dl4j_layer_*`` —
        through the storage's incremental fold mark (see
        ``iterations_done``), never per-record."""
        for rec in records:
            self.storage.put(rec)
        self.registry.fold_storage(self.storage)

    def _publish_memory(self, epoch: int, iterations,
                        prev_flush_t: Optional[float],
                        now: float) -> None:
        """The memory half of a flush: one ``{"type": "memory"}``
        record (pure host reads — no device sync) plus, when an active
        program plan is known, the live MFU-estimate gauge (plan flops
        per step ÷ measured step time ÷ device peak)."""
        from deeplearning4j_tpu.monitor import memstats
        rec = memstats.memory_record(
            epoch=epoch,
            iteration=int(iterations[-1]) if iterations else None)
        self.storage.put(rec)
        step_s = self.rolling.percentile(50) if len(self.rolling) else 0.0
        if not step_s and prev_flush_t is not None and iterations:
            # tracing disabled: no span-derived step times — fall back
            # to flush wall time over the burst's step count
            step_s = max(0.0, now - prev_flush_t) / max(1, len(iterations))
        if step_s:
            est = memstats.mfu_estimate(step_s)
            if est is not None:
                mfu, fps = est
                self.registry.set_gauge(
                    "mfu_estimate", round(mfu, 6),
                    help="live MFU estimate: active-plan flops/step / "
                         "measured step time / device peak flops")
                self.registry.set_gauge(
                    "plan_flops_per_step", fps,
                    help="active compiled program's flops per train "
                         "step (cost_analysis)")

    def _publish_datapipe(self, sd, epoch: int,
                          steptime_rec: Optional[dict],
                          prev_flush_t: Optional[float],
                          now: float) -> None:
        """The data-plane half of a flush: one ``{"type": "datapipe"}``
        record of per-flush DELTAS of the registered streaming
        pipeline's cumulative counters (records/sec, retries,
        quarantines, supervision decisions, per-worker utilization) —
        pure host reads, published only when a pipeline is active."""
        dp = getattr(sd, "_active_datapipe", None)
        if dp is None or not hasattr(dp, "stats"):
            return
        snap = dp.stats()
        # snapshot keyed by pipeline IDENTITY — the OBJECT, pinned, not
        # id(): a listener reused across fits with different pipelines
        # must not delta the new pipeline's counters against the old
        # one's, and a recycled CPython id would alias them (the same
        # recycled-id class the analysis-report pin set guards against)
        prev_dp, prev = self._datapipe_snap or (None, {})
        if prev_dp is not None and prev_dp is not dp:
            prev = {}
        self._datapipe_snap = (dp, snap)
        rec = {"type": "datapipe", "t": now, "epoch": int(epoch)}
        for key in ("records", "batches", "read_retries", "shard_reads",
                    "bytes_read", "rows_quarantined", "records_withheld",
                    "worker_restarts", "requeues", "slow_reads"):
            rec[key] = max(0, snap.get(key, 0) - prev.get(key, 0))
        for key in ("quarantined_shards", "passes_started", "workers"):
            if snap.get(key) is not None:
                rec[key] = snap[key]
        dt = max(1e-9, now - prev_flush_t) if prev_flush_t else None
        if dt is not None:
            rec["records_per_sec"] = round(rec["records"] / dt, 2)
        if steptime_rec:
            wall = steptime_rec.get("wall_s") or 0.0
            if wall:
                rec["data_wait_frac"] = round(
                    steptime_rec.get("data_wait_s", 0.0) / wall, 4)
        busy = snap.get("worker_busy_s") or {}
        prev_busy = prev.get("worker_busy_s") or {}
        if dt is not None and busy:
            rec["worker_utilization"] = {
                str(w): round(min(1.0, max(
                    0.0, busy.get(w, 0.0)
                    - prev_busy.get(w, 0.0)) / dt), 4)
                for w in busy}
        self.storage.put(rec)

    def iterations_done(self, sd, epoch: int, iterations, losses) -> None:
        now = time.time()
        prev_flush_t = self._last_flush_t
        self._last_flush_t = now
        if iterations:
            self._last_iteration = int(iterations[-1])
        spans, self._mark, dropped = self.tracer.drain(self._mark)
        self._dropped += dropped
        rows = window_rows(spans)
        if self.memory:
            self._publish_memory(epoch, iterations, prev_flush_t, now)
        if not rows:
            self._publish_datapipe(sd, epoch, None, prev_flush_t, now)
            self.registry.fold_storage(self.storage)
            return
        rec = {"type": "steptime", "epoch": int(epoch), "t": time.time(),
               "windows": len(rows), "steps": sum(r["k"] for r in rows),
               "wall_s": round(sum(r["dur_s"] for r in rows), 6)}
        # stage spans OUTSIDE any drained window (the epoch-end flush,
        # and the flush fired between a window's close and this
        # delivery) still belong to this burst's wall time — count them
        # into the totals so flush time is never silently dropped
        window_sids = {r["sid"] for r in rows}
        orphans = {s: 0.0 for s in _STAGE_NAMES}
        for sp in spans:
            if sp.name in _STAGE_NAMES and sp.parent not in window_sids:
                orphans[sp.name] += sp.dur
        for stage in ("data_wait", "dispatch", "flush"):
            rec[f"{stage}_s"] = round(
                sum(r[f"{stage}_s"] for r in rows) + orphans[stage], 6)
        rec["other_s"] = round(sum(r["other_s"] for r in rows), 6)
        for r in rows:
            # per-step time EXCLUDES the flush child: the flush is a
            # burst sync amortized over the whole cadence, carried by
            # whichever window crossed the boundary — folding it in
            # would make the straggler watcher flag every flush-carrying
            # window of a healthy sparse-cadence run (flush cost is
            # reported separately in flush_s)
            step_s = max(0.0, r["dur_s"] - r["flush_s"]) / max(1, r["k"])
            self.rolling.add(step_s)
            if self.straggler is not None:
                self.straggler.observe(step_s, iteration=r.get("iteration"),
                                       k=r["k"])
        rec["step_ms_p50"] = round(1e3 * self.rolling.percentile(50), 4)
        rec["step_ms_p95"] = round(1e3 * self.rolling.percentile(95), 4)
        rec["step_ms_max"] = round(1e3 * self.rolling.percentile(100), 4)
        if iterations:
            rec["iteration"] = int(iterations[-1])
        if self._dropped:
            rec["spans_dropped"] = self._dropped
        self.storage.put(rec)
        self._publish_datapipe(sd, epoch, rec, prev_flush_t, now)
        # fold through the storage's incremental per-(registry, storage)
        # high-water mark, NOT per-record: a TelemetryServer sharing
        # this registry folds the same storage on every /metrics scrape,
        # and the shared mark is what keeps counter-typed series (the
        # fold adapters are not idempotent) from reading 2x. This also
        # picks up records other writers (checkpoint manager, fault
        # rail, serving) put into the same storage between flushes.
        self.registry.fold_storage(self.storage)

    def on_epoch_end(self, sd, epoch: int, mean_loss) -> None:
        self.registry.fold_dispatch(getattr(sd, "last_fit_stats", None),
                                    epoch=epoch)
        # compile accounting rides the same cadence: whenever the
        # process-wide counters moved since the last publish (first
        # epoch covers compiles that predate the fit, e.g. precompile),
        # fold them and emit the {"type": "compile"} record — without
        # this a monitored run never surfaces the cache-hit/miss split
        # and ui/report's Compilation section only exists for callers
        # that publish COMPILE_STATS by hand
        from deeplearning4j_tpu.compilecache import COMPILE_STATS
        snap = COMPILE_STATS.snapshot()
        if any(snap.values()) and snap != self._compile_snap:
            self._compile_snap = snap
            self.registry.fold_compile(COMPILE_STATS)
            COMPILE_STATS.publish(self.storage)
        if self.memory:
            # plans captured for THIS graph (precompile, serving
            # warmup, lazy-compile promotion) become {"type":
            # "memory_plan"} records — the per-executable footprint
            # ui/report's Memory panel charts. Filtered by graph
            # identity: the registry is process-global, and a second
            # model's listener must not republish the first model's
            # plans into its own storage as if they were its run's.
            from deeplearning4j_tpu.monitor import memstats
            gid = memstats.graph_key(sd)
            for plan in memstats.PLANS.plans():
                if plan.graph is not None and plan.graph != gid:
                    continue
                key = (plan.label, plan.sig)
                if key in self._published_plans:
                    continue
                self._published_plans.add(key)
                self.storage.put(plan.to_record())
            self.registry.fold_storage(self.storage)
        self.registry.publish(self.storage)

    def on_training_end(self, sd) -> None:
        spans = self.tracer.spans()
        if spans:
            t0 = self.tracer.epoch
            tail = spans[-self.trace_record_spans:]
            self.storage.put({
                "type": "trace", "t": time.time(),
                "spans_total": len(spans), "spans": [
                    s.to_dict(t0) for s in tail]})


__all__ = ["MonitorListener", "RollingPercentiles", "StragglerWatcher",
           "window_rows"]
