"""Span tracer: where did the wall-clock time go?

The reference answers this with the deeplearning4j-ui stats pipeline
(BaseStatsListener's timing families) plus ad-hoc PerformanceListener
prints; neither can say that a slow epoch was data-wait vs dispatch vs
device. This tracer records host-side WALL-TIME SPANS — named, nested,
per-thread — into a fixed ring buffer, exportable as a Chrome/Perfetto
trace (``chrome://tracing`` / https://ui.perfetto.dev loads the JSON
directly).

Design constraints, in order:

1. **Near-zero cost disabled.** Instrumentation is compiled into the
   hot paths permanently (window executor, serving lifecycle,
   checkpoint commits, fault recovery); the disabled path is one
   attribute check returning a shared no-op span — no allocation, no
   lock, no clock read. Always-on instrumentation with an off switch,
   not an opt-in build.
2. **Thread-safe, per-thread lanes.** The window stager, serving
   workers and the checkpoint writer all trace concurrently; spans
   carry their thread id (a chrome-trace "tid" lane) and nest via a
   thread-local stack, so lanes never interleave.
3. **Bounded memory.** A ring buffer (default 65536 completed spans)
   with a monotonically increasing sequence number; consumers
   (monitor/steptime.py) incrementally drain "spans since mark"
   without copying the whole buffer, and eviction is explicit in the
   drain result (``dropped``).
4. **No device syncs.** Spans time the HOST: a ``dispatch`` span is
   enqueue cost, not device compute (jax dispatch is async). Device
   time comes from profiler/ xplane captures, correlated onto window
   spans by ``ProfilerSession.correlate_spans``.

Usage::

    from deeplearning4j_tpu.monitor import TRACER, enable_tracing
    enable_tracing()
    with TRACER.span("window", cat="train", k=8) as sp:
        ...
        sp.set(iteration=it)
    TRACER.write_chrome_trace("trace.json")

Spans measure ``time.perf_counter`` and are recorded on ``__exit__``
(a crashed span still records, with the exception type in its args).
"""
from __future__ import annotations

import collections
import functools
import itertools
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple


class _NullSpan:
    """The disabled path: a shared, stateless, no-op span."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def set(self, **args) -> "_NullSpan":
        return self

    def discard(self) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """One live (then completed) span. Create via :meth:`Tracer.span`."""

    __slots__ = ("tracer", "name", "cat", "args", "t0", "dur", "tid",
                 "thread_name", "seq", "sid", "parent", "_discarded")

    #: process-wide id source — `next()` is atomic under the GIL
    _ids = itertools.count(1)

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0.0
        self.dur = 0.0
        self.tid = 0
        self.thread_name = ""
        self.seq = -1          # assigned when recorded
        self.sid = 0           # assigned when entered
        self.parent = 0        # sid of the enclosing span on this thread
        self._discarded = False

    def __enter__(self) -> "Span":
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread_name = t.name
        self.sid = next(Span._ids)
        stack = self.tracer._stack()
        if stack:
            self.parent = stack[-1].sid
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.dur = time.perf_counter() - self.t0
        stack = self.tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:               # unbalanced nesting: repair
            stack.remove(self)
        if exc_type is not None:
            self.args = dict(self.args, error=exc_type.__name__)
        if not self._discarded:
            self.tracer._record(self)
        return False

    def set(self, **args) -> "Span":
        """Attach/overwrite span args (shows up in the chrome trace)."""
        self.args.update(args)
        return self

    def discard(self) -> None:
        """Drop this span on exit (e.g. a data_wait that found
        end-of-stream instead of data)."""
        self._discarded = True

    def to_dict(self, t0: float) -> dict:
        """Compact dict form (seconds relative to the tracer epoch)."""
        return {"name": self.name, "cat": self.cat,
                "ts": round(self.t0 - t0, 9), "dur": round(self.dur, 9),
                "tid": self.tid, "thread": self.thread_name,
                "sid": self.sid, "parent": self.parent,
                "args": dict(self.args)}


class Tracer:
    """Thread-safe ring-buffered span tracer (see module docstring).

    One module-level instance (:data:`TRACER`) is shared by all
    instrumented subsystems; ``enabled`` flips instrumentation from
    no-op to recording in place, so call sites can hold the reference
    forever.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = False):
        self.enabled = bool(enabled)
        self._capacity = int(capacity)
        self._lock = threading.Lock()
        self._buf: "collections.deque[Span]" = \
            collections.deque(maxlen=self._capacity)
        self._seq = 0                     # completed spans ever recorded
        self._tls = threading.local()
        self._t0 = time.perf_counter()    # trace epoch
        self._meta_t0 = time.time()       # wall-clock anchor for humans

    # -- recording ------------------------------------------------------
    def span(self, name: str, cat: str = "", **args):
        """Open a span context manager. THE hot call: when disabled it
        returns a shared no-op singleton (no allocation, no clock)."""
        if not self.enabled:
            return _NULL_SPAN
        return Span(self, name, cat, args)

    def traced(self, name: Optional[str] = None, cat: str = ""):
        """Decorator form: ``@TRACER.traced()`` spans every call."""
        def deco(fn: Callable):
            span_name = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                with self.span(span_name, cat=cat):
                    return fn(*a, **kw)
            return wrapper
        return deco

    def record_completed(self, name: str, cat: str = "", dur: float = 0.0,
                         **args) -> None:
        """Record an already-measured span — a duration reported by a
        callback (e.g. a ``jax.monitoring`` compile event) that was
        never entered as a context manager. The span ends NOW and
        started ``dur`` seconds ago, lands in the current thread's lane,
        and nests under whatever span is open on this thread."""
        if not self.enabled:
            return
        sp = Span(self, name, cat, args)
        t = threading.current_thread()
        sp.tid = t.ident or 0
        sp.thread_name = t.name
        sp.sid = next(Span._ids)
        stack = self._stack()
        if stack:
            sp.parent = stack[-1].sid
        sp.dur = float(dur)
        sp.t0 = time.perf_counter() - sp.dur
        self._record(sp)

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _record(self, span: Span) -> None:
        with self._lock:
            span.seq = self._seq
            self._seq += 1
            self._buf.append(span)

    # -- lifecycle ------------------------------------------------------
    def enable(self) -> "Tracer":
        self.enabled = True
        return self

    def disable(self) -> "Tracer":
        self.enabled = False
        return self

    def reset(self, capacity: Optional[int] = None) -> "Tracer":
        """Clear the buffer (and optionally resize) in place."""
        with self._lock:
            if capacity is not None:
                self._capacity = int(capacity)
            self._buf = collections.deque(maxlen=self._capacity)
            self._seq = 0
            self._t0 = time.perf_counter()
            self._meta_t0 = time.time()
        return self

    # -- readout --------------------------------------------------------
    @property
    def epoch(self) -> float:
        """perf_counter value all exported timestamps are relative to."""
        return self._t0

    def mark(self) -> int:
        """Current sequence high-water mark (pass to :meth:`drain`)."""
        with self._lock:
            return self._seq

    def drain(self, since: int = 0) -> Tuple[List[Span], int, int]:
        """Spans recorded after sequence mark ``since`` →
        ``(spans, new_mark, dropped)``. ``dropped`` counts spans that
        were evicted from the ring before this drain saw them."""
        with self._lock:
            n_new = self._seq - since
            if n_new <= 0:
                return [], self._seq, 0
            take = min(n_new, len(self._buf))
            spans = list(itertools.islice(
                self._buf, len(self._buf) - take, len(self._buf)))
            return spans, self._seq, n_new - take

    def spans(self) -> List[Span]:
        """Snapshot of the whole ring (oldest first)."""
        with self._lock:
            return list(self._buf)

    # -- export ---------------------------------------------------------
    def to_chrome_trace(self, since: Optional[int] = None) -> dict:
        """Chrome Trace Event JSON (the ``{"traceEvents": [...]}``
        object form). Loadable by chrome://tracing and Perfetto.
        Timestamps are microseconds from the tracer epoch; each thread
        is one lane, named via metadata events.

        With ``since`` (a sequence mark from a previous export's
        ``otherData["next"]`` or :meth:`mark`), only spans recorded
        after that mark are exported — the incremental form a polling
        collector uses instead of re-downloading the whole ring;
        ``otherData`` then carries the ``next`` cursor and the
        ``dropped`` eviction count."""
        if since is None:
            spans, next_mark, dropped = self.spans(), self.mark(), None
        else:
            spans, next_mark, dropped = self.drain(int(since))
        events: List[dict] = []
        threads: Dict[int, str] = {}
        for sp in spans:
            threads.setdefault(sp.tid, sp.thread_name)
        for tid, tname in sorted(threads.items()):
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": tid, "args": {"name": tname}})
        for sp in sorted(spans, key=lambda s: s.t0):
            ev = {"name": sp.name, "ph": "X",
                  "ts": round((sp.t0 - self._t0) * 1e6, 3),
                  "dur": round(sp.dur * 1e6, 3),
                  "pid": 0, "tid": sp.tid}
            if sp.cat:
                ev["cat"] = sp.cat
            if sp.args:
                ev["args"] = {k: (v if isinstance(v, (int, float, str,
                                                      bool, type(None)))
                                  else repr(v))
                              for k, v in sp.args.items()}
            events.append(ev)
        other = {"tracer_epoch_unix_s": self._meta_t0,
                 "spans": len(spans), "recorded_total": next_mark,
                 "next": next_mark}
        if dropped is not None:
            other["dropped"] = dropped
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": other}

    def write_chrome_trace(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.to_chrome_trace(), fh)
        return path


#: The process-wide tracer every instrumented subsystem records into.
TRACER = Tracer()


def get_tracer() -> Tracer:
    return TRACER


def enable_tracing(capacity: Optional[int] = None,
                   reset: bool = False) -> Tracer:
    """Turn span recording on (optionally resetting/resizing the ring)."""
    if reset or capacity is not None:
        TRACER.reset(capacity=capacity)
    return TRACER.enable()


def disable_tracing() -> Tracer:
    return TRACER.disable()


#: The canonical span registry: every span NAME the package records,
#: mapped to ``(category, well-known arg keys)``. Downstream consumers
#: key on these literals — waterfall assembly (monitor/reqtrace.py)
#: selects ``serving.*``/``fleet.attempt`` by name, steptime attribution
#: selects the train-tier stages, report lanes color by name — so a
#: rename is a silent data loss everywhere at once. The span-name lint
#: (tests/test_static_lint.py) walks every ``span("...")`` /
#: ``record_completed("...")`` / ``_dispatch(..., "...")`` literal in
#: the package and asserts BOTH directions: every recorded name is
#: cataloged, and every cataloged name is still recorded somewhere.
#: Arg keys are the documented contract (e.g. ``trace_id``/``segment``
#: land on any serving span once request tracing propagates a
#: TraceContext; ``slots`` is the batch-level occupancy map).
SPAN_CATALOG: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    # train tier (autodiff/samediff.py, autodiff/window.py)
    "window": ("train", ("k", "iteration")),
    "step": ("train", ("k",)),
    "data_wait": ("train", ()),
    "dispatch": ("train", ("k",)),
    "flush": ("train", ("steps",)),
    "h2d_stage": ("train", ("k",)),
    "integrity.replay_probe": ("integrity", ("k",)),
    # compile pipeline (compilecache/, samediff precompile, memstats)
    "compile.precompile": ("compile", ("target",)),
    "compile.plan_capture": ("compile", ("target",)),
    "compile.backend": ("compile", ("cache_hit",)),
    "compile.trace": ("compile", ()),
    "compile.lower": ("compile", ()),
    # checkpoint rail (checkpoint/, parallel/trainer.py)
    "checkpoint.capture": ("checkpoint", ("step",)),
    "checkpoint.commit": ("checkpoint", ("step", "asynchronous",
                                         "queue_s")),
    "checkpoint.serialize": ("checkpoint", ("step",)),
    "checkpoint.reshard": ("checkpoint", ("step",)),
    # fault rail (faults/)
    "faults.rollback": ("faults", ("cause",)),
    "faults.backoff": ("faults", ("attempt", "backoff_s")),
    "data.loader_seek": ("data", ("skip",)),
    "data.loader_retry": ("data", ("skip",)),
    # serving lifecycle (serving/) — request-traced spans additionally
    # carry trace_id/segment; batch-level dispatches carry slots
    "serving.enqueue": ("serving", ("id", "trace_id", "segment")),
    "serving.batch": ("serving", ("rows", "requests")),
    "serving.pad": ("serving", ("rows", "bucket")),
    "serving.exec": ("serving", ("rows", "padding")),
    "serving.reply": ("serving", ("id", "requests", "trace_id",
                                  "segment")),
    "serving.warmup": ("serving", ("bucket",)),
    "serving.reload": ("serving", ("step", "arrays")),
    "serving.prefill": ("serving", ("bucket", "slot", "trace_id",
                                    "segment")),
    "serving.decode": ("serving", ("active", "slots")),
    "serving.draft": ("serving", ("active", "step", "slots")),
    "serving.verify": ("serving", ("active", "window", "slots")),
    # fleet tier (serving/fleet/router.py) — one span per placement
    # attempt, the segment boundary request waterfalls link on
    "fleet.attempt": ("fleet", ("trace_id", "segment", "kind",
                                "replica", "outcome")),
}


__all__ = ["Span", "Tracer", "TRACER", "SPAN_CATALOG", "get_tracer",
           "enable_tracing", "disable_tracing"]
