"""Live telemetry HTTP endpoint — the runtime half of the reference's
``deeplearning4j-ui-parent`` web dashboard.

Until now every observable this codebase produces (metrics registry,
steptime/tensorstats records, trace spans, the HTML report) was only
reachable by reading files after the run. This module serves them LIVE
from a stdlib :class:`ThreadingHTTPServer` — no new dependencies, safe
to run inside a training job or an inference server:

====================  =====================================================
route                 payload
====================  =====================================================
``GET /metrics``      Prometheus text exposition from the
                      :class:`~deeplearning4j_tpu.monitor.registry.
                      MetricsRegistry` (the attached storage is folded
                      incrementally on every scrape, so ``dl4j_*`` series
                      track the run without a publisher thread)
``GET /healthz``      liveness: 200 while the fault rail is clean, 503
                      from the first ``fault``/``rollback`` record until
                      the run publishes ``recovered`` (sticky 503 on
                      ``retry_exhausted``) — JSON body with the fault
                      state, last-step age and provider snapshots
``GET /readyz``       readiness: 200 while healthy AND fresh (last-step
                      age within ``stale_after_s`` when set) AND no
                      provider reports ``ready: False`` (the serving
                      queue-depth hook — the SLO shed-load signal)
``GET /report``       the self-contained ui/report HTML, rendered from
                      the live storage
``GET /memory``       live HBM state: a fresh per-device snapshot,
                      AllocationsTracker transfer totals, and every
                      captured compiled-program memory plan
                      (monitor/memstats.py)
``GET /trace``        Chrome/Perfetto trace JSON from the shared tracer
                      (load at ui.perfetto.dev); ``?since=<seq>`` drains
                      incrementally from that cursor — the next cursor
                      comes back in ``otherData.next``, so a polling
                      collector never re-downloads old spans
``GET /requesttrace`` per-request waterfalls from an attached
                      :class:`~deeplearning4j_tpu.monitor.reqtrace.
                      RequestTracer` — no args lists kept traces,
                      ``?id=<trace_id>`` returns one assembled
                      waterfall, ``&chrome=1`` renders it as a Perfetto
                      lane-per-request timeline
``GET /slo``          fleet SLO attainment + error-budget burn rate:
                      the attached SLOTracker live, else the latest
                      fleet record's ``slo`` sub-dict from storage
``GET /stacks``       all-thread Python stack dump (integrity/
                      watchdog.py) — look at a run that seems wedged;
                      the stall watchdog's forensics reuse it
``GET /stats``        recent storage records as JSON lines
                      (``?n=500&type=tensorstats``)
``GET /``             a minimal index linking the routes
====================  =====================================================

**Security note**: the server binds loopback (``127.0.0.1``) by default
and serves everything unauthenticated — training internals, parameter
statistics, trace timelines. Bind a routable interface only behind
infrastructure you trust (a pod-local sidecar, an authenticated proxy).

Start it three ways:

- ``monitor.serve(port=0, storage=st)`` — standalone, port 0 picks a
  free port;
- ``MonitorListener(storage, serve_port=0)`` — the training listener
  brings the endpoint up at ``on_training_start`` sharing its storage/
  registry/tracer (and a last-flush heartbeat provider);
- ``ParallelInference(model, telemetry_port=0)`` — the inference server
  exposes its ``ServingMetrics`` and queue depth.

See docs/observability.md ("The live telemetry endpoint").
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.monitor.registry import MetricsRegistry

#: fault-rail events that flip /healthz to 503 (a recovery in progress;
#: "stall" is the watchdog's wedged-boundary verdict — the run may
#: never raise, but the probe must go red immediately)
_DEGRADING_EVENTS = frozenset({"fault", "rollback", "retry",
                               "topology_changed", "stall",
                               "corrupt_checkpoint"})
#: ... and the event that clears it
_RECOVERED_EVENTS = frozenset({"recovered"})
#: sticky failure: the retry budget is spent and the job is aborting,
#: or device memory is exhausted (a rollback cannot shrink the program
#: — the run/bucket will not heal without intervention)
_FATAL_EVENTS = frozenset({"retry_exhausted", "oom"})

#: record types whose ``t`` field is wall-clock (time.time()) — the
#: last-step-age fallback when no heartbeat provider is registered
#: ("score"/"perf" use perf_counter timestamps and must NOT mix in)
_WALL_T_TYPES = ("steptime", "tensorstats", "metrics", "checkpoint",
                 "faults", "serving", "memory", "datapipe", "integrity")


def health_snapshot(storage=None, providers: Dict[str, Callable] = None,
                    stale_after_s: Optional[float] = None,
                    now: Optional[float] = None,
                    cache: Optional[dict] = None) -> dict:
    """Pure health evaluation over a StatsStorage + provider callbacks
    (separated from the HTTP layer so tests and supervisors can call it
    directly).

    Returns ``{"healthy", "ready", "fault_state", "last_step_age_s",
    "rollbacks", "providers", ...}``. Fault state walks the storage's
    ``{"type": "faults"}`` records in order: any degrading event flips
    to ``recovering`` until a ``recovered`` lands; ``retry_exhausted``
    is sticky ``failed``. Providers are ``name -> fn()`` returning a
    dict; a provider raising is reported (and makes the snapshot
    unhealthy — a dead introspection hook is itself a symptom);
    ``healthy: False`` / ``ready: False`` keys gate the aggregate.
    A provider's ``load`` sub-dict (queue depth, slot/pool occupancy,
    rolling p99 decode-step ms — see ``GenerativeServer``'s provider)
    is merged into a top-level ``load`` key, so a fleet router reads
    readiness AND load in ONE ``/readyz`` scrape.

    ``cache``: an opaque dict the caller keeps between calls — only
    records appended since the last call are walked, so a per-second
    kubernetes probe stays O(new records) instead of re-scanning a
    long run's whole history per probe (the TelemetryServer passes a
    persistent cache; the sticky-``failed`` semantics make the fold
    order-safe). Omit it for the stateless full walk.
    """
    now = time.time() if now is None else now
    if cache is None:
        cache = {}
    state = cache.get("state", "ok")
    rollbacks = cache.get("rollbacks", 0)
    last_event = cache.get("last_event")
    rec_last_t = cache.get("last_wall_t")
    if storage is not None:
        records = storage.records        # append-only; slicing is safe
        n = len(records)
        for rec in list(records[cache.get("mark", 0):n]):
            t = rec.get("type")
            if t == "faults":
                ev = rec.get("event")
                if ev == "rollback":
                    rollbacks += 1
                if ev in _FATAL_EVENTS:
                    state = "failed"
                    last_event = ev
                elif state != "failed" and ev in _DEGRADING_EVENTS:
                    state = "recovering"
                    last_event = ev
                elif state != "failed" and ev in _RECOVERED_EVENTS:
                    state = "ok"
                    last_event = ev
            if t in _WALL_T_TYPES:
                tv = rec.get("t")
                if tv is not None and (rec_last_t is None
                                       or tv > rec_last_t):
                    rec_last_t = float(tv)
        cache.update(mark=n, state=state, rollbacks=rollbacks,
                     last_event=last_event, last_wall_t=rec_last_t)
    prov_out: Dict[str, dict] = {}
    healthy = state == "ok"
    ready = True
    load: Dict[str, object] = {}
    last_step_t: Optional[float] = None
    for name, fn in (providers or {}).items():
        try:
            p = dict(fn() or {})
        except Exception as e:           # noqa: BLE001 — reported, not fatal
            p = {"error": f"{type(e).__name__}: {e}", "healthy": False}
        prov_out[name] = p
        if p.get("healthy") is False:
            healthy = False
        if p.get("ready") is False:
            ready = False
        if isinstance(p.get("load"), dict):
            load.update(p["load"])
        t = p.get("last_step_t")
        if t is not None and (last_step_t is None or t > last_step_t):
            last_step_t = float(t)
    if last_step_t is None:
        last_step_t = rec_last_t
    age = None if last_step_t is None else max(0.0, now - last_step_t)
    if stale_after_s is not None and age is not None \
            and age > stale_after_s:
        ready = False
    snap = {"t": now, "fault_state": state, "healthy": healthy,
            "ready": healthy and ready, "rollbacks": rollbacks,
            "last_step_age_s": None if age is None else round(age, 3),
            "providers": prov_out}
    if load:
        snap["load"] = load
    if last_event is not None:
        snap["last_fault_event"] = last_event
    if stale_after_s is not None:
        snap["stale_after_s"] = stale_after_s
    return snap


class TelemetryServer:
    """The live telemetry endpoint (module docstring). Thread-per-
    request (``ThreadingHTTPServer`` with daemon threads) over shared
    thread-safe state: the registry locks internally, the storage locks
    ``put``/``of_type``/``tail``, the tracer locks its ring — a scrape
    never blocks training for more than one lock hold."""

    def __init__(self, storage=None,
                 registry: Optional[MetricsRegistry] = None,
                 tracer=None, host: str = "127.0.0.1", port: int = 0,
                 stale_after_s: Optional[float] = None,
                 title: str = "deeplearning4j_tpu telemetry"):
        if tracer is None:
            from deeplearning4j_tpu.monitor.trace import TRACER
            tracer = TRACER
        self.storage = storage
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.tracer = tracer
        self.stale_after_s = stale_after_s
        self.title = title
        # request-tracing rail (monitor/reqtrace.py): attach via
        # attach_reqtrace()/attach_slo() — typically a FleetRouter's
        # .reqtrace and .slo — to light up /requesttrace and /slo
        self.reqtrace = None
        self.slo = None
        self._providers: Dict[str, Callable] = {}
        self._scrape_hooks: List[Callable] = []
        # incremental health-state fold (health_snapshot cache=): one
        # persistent cache + a lock so concurrent probes don't race the
        # mark and double-count rollbacks
        self._health_cache: dict = {}
        self._health_lock = threading.Lock()
        self._closed = False
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            # quiet: request logging through the monitor rail, not stderr
            def log_message(self, fmt, *args):  # noqa: D102
                pass

            def do_GET(self):                   # noqa: N802 (http.server)
                try:
                    status, ctype, body = outer._route(self.path)
                except Exception as e:          # noqa: BLE001
                    status, ctype = 500, "application/json"
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="TelemetryServer",
            daemon=True)
        self._thread.start()

    # -- addressing -----------------------------------------------------
    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- wiring ---------------------------------------------------------
    def add_health_provider(self, name: str, fn: Callable) -> None:
        """Register a ``fn() -> dict`` merged into /healthz and
        /readyz. Recognized keys: ``healthy``/``ready`` (False gates
        the aggregate), ``last_step_t`` (wall clock of the last unit of
        progress — feeds last-step age), ``load`` (a sub-dict of load
        signals — queue depth, occupancy, rolling p99 decode-step ms —
        merged into the snapshot's top-level ``load`` key for one-scrape
        fleet routing); everything else is reported verbatim (queue
        depths, iteration counters, ...)."""
        self._providers[str(name)] = fn

    def attach_reqtrace(self, reqtrace) -> None:
        """Attach a :class:`~deeplearning4j_tpu.monitor.reqtrace.
        RequestTracer` (e.g. ``router.reqtrace``) — serves its kept
        waterfalls at ``/requesttrace``."""
        self.reqtrace = reqtrace

    def attach_slo(self, slo) -> None:
        """Attach a :class:`~deeplearning4j_tpu.monitor.reqtrace.
        SLOTracker` (e.g. ``router.slo``) — serves its live attainment/
        burn-rate readout at ``/slo``."""
        self.slo = slo

    def add_scrape_hook(self, fn: Callable) -> None:
        """Register ``fn(registry)`` run at the top of every /metrics
        scrape — the pull-model adapter for sources without records
        (e.g. ``reg.fold_serving(pi.metrics)``)."""
        self._scrape_hooks.append(fn)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    # -- routes ---------------------------------------------------------
    def _route(self, path: str):
        url = urlparse(path)
        route = url.path.rstrip("/") or "/"
        qs = parse_qs(url.query)
        if route == "/metrics":
            return self._metrics()
        if route == "/healthz":
            return self._health(ready_probe=False)
        if route == "/readyz":
            return self._health(ready_probe=True)
        if route == "/report":
            return self._report()
        if route == "/memory":
            return self._memory()
        if route == "/trace":
            return self._trace(qs)
        if route == "/requesttrace":
            return self._requesttrace(qs)
        if route == "/slo":
            return self._slo()
        if route == "/stacks":
            return self._stacks()
        if route == "/stats":
            return self._stats(qs)
        if route == "/":
            return self._index()
        return 404, "application/json", \
            json.dumps({"error": f"no route {route!r}"}).encode()

    def _metrics(self):
        for hook in self._scrape_hooks:
            hook(self.registry)
        if self.storage is not None:
            # incremental: fold_storage keeps a per-storage high-water
            # mark, so scraping in a loop never double-counts
            self.registry.fold_storage(self.storage)
        text = self.registry.to_prometheus_text()
        return 200, "text/plain; version=0.0.4; charset=utf-8", \
            text.encode("utf-8")

    def _health(self, ready_probe: bool):
        with self._health_lock:
            snap = health_snapshot(self.storage, self._providers,
                                   stale_after_s=self.stale_after_s,
                                   cache=self._health_cache)
        ok = snap["ready"] if ready_probe else snap["healthy"]
        return (200 if ok else 503), "application/json", \
            json.dumps(snap, default=str).encode("utf-8")

    def _report(self):
        if self.storage is None:
            return 404, "application/json", \
                json.dumps({"error": "no storage attached"}).encode()
        from deeplearning4j_tpu.ui.report import render_report
        html = render_report(self.storage, title=self.title)
        return 200, "text/html; charset=utf-8", html.encode("utf-8")

    def _memory(self):
        """Live HBM state (monitor/memstats.py): a fresh per-device
        snapshot + tracked transfer totals + every captured compiled-
        program memory plan + the last stored memory record (so the
        flush-cadence history and the instantaneous view sit side by
        side)."""
        from deeplearning4j_tpu.monitor import memstats
        body = memstats.memory_record(source="probe")
        body["plans"] = [p.to_record() for p in memstats.PLANS.plans()]
        active = memstats.PLANS.active_plan()
        body["active_program"] = active.label if active is not None \
            else None
        if self.storage is not None:
            last = self.storage.tail(1, "memory")
            if last:
                body["last_record"] = last[-1]
        return 200, "application/json", \
            json.dumps(body, default=str).encode("utf-8")

    def _trace(self, qs):
        since = None
        raw = qs.get("since", [None])[0]
        if raw is not None:
            try:
                since = int(raw)
            except ValueError:
                return 400, "application/json", json.dumps(
                    {"error": f"since must be an integer, got {raw!r}"}
                ).encode("utf-8")
        body = self.tracer.to_chrome_trace(since=since)
        return 200, "application/json", \
            json.dumps(body).encode("utf-8")

    def _requesttrace(self, qs):
        """Per-request waterfalls (monitor/reqtrace.py): the list of
        kept traces, one assembled waterfall by id, or its Perfetto
        lane-per-request rendering with ``chrome=1``."""
        if self.reqtrace is None:
            return 404, "application/json", json.dumps(
                {"error": "no RequestTracer attached "
                          "(TelemetryServer.attach_reqtrace)"}).encode()
        # fold any spans still sitting in the ring into open buffers
        self.reqtrace.collect()
        raw = qs.get("id", [None])[0]
        chrome = qs.get("chrome", ["0"])[0] not in ("0", "", "false")
        if raw is None:
            if chrome:
                body = self.reqtrace.to_chrome_trace()
            else:
                body = {"traces": self.reqtrace.summaries()}
            return 200, "application/json", \
                json.dumps(body, default=str).encode("utf-8")
        try:
            tid = int(raw)
        except ValueError:
            return 400, "application/json", json.dumps(
                {"error": f"id must be an integer, got {raw!r}"}
            ).encode("utf-8")
        if chrome:
            body = self.reqtrace.to_chrome_trace(trace_id=tid)
            if not body.get("traceEvents"):
                return 404, "application/json", json.dumps(
                    {"error": f"no kept trace {tid}"}).encode()
        else:
            body = self.reqtrace.get(tid)
            if body is None:
                return 404, "application/json", json.dumps(
                    {"error": f"no kept trace {tid}"}).encode()
        return 200, "application/json", \
            json.dumps(body, default=str).encode("utf-8")

    def _slo(self):
        """SLO attainment/burn-rate readout: the attached tracker live,
        else the newest fleet record's ``slo`` sub-dict from storage."""
        if self.slo is not None:
            body = {"t": time.time(), "source": "live",
                    "slo": self.slo.to_dict()}
        else:
            sub = None
            if self.storage is not None:
                for rec in reversed(self.storage.tail(200, "fleet")):
                    if rec.get("slo") is not None:
                        sub = rec.get("slo")
                        break
            if sub is None:
                return 404, "application/json", json.dumps(
                    {"error": "no SLOTracker attached and no fleet "
                              "record carries an 'slo' sub-dict"}
                ).encode()
            body = {"t": time.time(), "source": "storage", "slo": sub}
        return 200, "application/json", \
            json.dumps(body, default=str).encode("utf-8")

    def _stacks(self):
        """All-thread Python stack dump (integrity/watchdog.py) — the
        standalone look-at-a-wedged-run debug route; the stall
        watchdog's forensics reuse the same dump. Same security note as
        every other route: loopback-only by default, serves process
        internals unauthenticated."""
        from deeplearning4j_tpu.integrity.watchdog import dump_all_stacks
        body = {"t": time.time(), "threads": dump_all_stacks()}
        return 200, "application/json", \
            json.dumps(body, default=str).encode("utf-8")

    def _stats(self, qs):
        if self.storage is None:
            return 404, "application/json", \
                json.dumps({"error": "no storage attached"}).encode()
        try:
            n = int(qs.get("n", ["200"])[0])
        except ValueError:
            n = 200
        rtype = qs.get("type", [None])[0]
        recs = self.storage.tail(n, rtype)
        body = "\n".join(json.dumps(r, default=str) for r in recs)
        return 200, "application/x-ndjson; charset=utf-8", \
            body.encode("utf-8")

    def _index(self):
        import html as _html
        rows = "".join(
            f'<li><a href="{r}">{r}</a> — {_html.escape(d)}</li>'
            for r, d in (
                ("/metrics", "Prometheus exposition"),
                ("/healthz", "liveness (fault/rollback state)"),
                ("/readyz", "readiness (staleness + queue depth)"),
                ("/report", "training report HTML"),
                ("/memory", "live HBM snapshot + program memory plans"),
                ("/trace", "Chrome/Perfetto trace JSON "
                           "(?since=<seq> drains incrementally)"),
                ("/requesttrace", "per-request waterfalls "
                                  "(?id=<trace_id>, &chrome=1)"),
                ("/slo", "fleet SLO attainment + error-budget burn"),
                ("/stacks", "all-thread stack dump (wedged-run "
                            "debugging)"),
                ("/stats", "recent records (?n=500&type=...)")))
        body = (f"<!doctype html><html><head><meta charset='utf-8'>"
                f"<title>{_html.escape(self.title)}</title></head>"
                f"<body><h1>{_html.escape(self.title)}</h1>"
                f"<ul>{rows}</ul></body></html>")
        return 200, "text/html; charset=utf-8", body.encode("utf-8")


def serve(port: int = 0, host: str = "127.0.0.1", storage=None,
          registry: Optional[MetricsRegistry] = None, tracer=None,
          stale_after_s: Optional[float] = None) -> TelemetryServer:
    """Start a :class:`TelemetryServer` (module docstring). ``port=0``
    binds a free loopback port; read it back from ``server.port`` /
    ``server.url``. The server runs on daemon threads — it dies with
    the process, or earlier via ``server.close()``."""
    return TelemetryServer(storage=storage, registry=registry,
                           tracer=tracer, host=host, port=port,
                           stale_after_s=stale_after_s)


__all__ = ["TelemetryServer", "serve", "health_snapshot"]
