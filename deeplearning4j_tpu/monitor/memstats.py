"""HBM memory observability: live telemetry, compiled-program memory
plans, and OOM forensics.

PROFILE.md rounds 5–6 did the 16 GB HBM budget math for gpt_medium **by
hand** ("f32 masters + Adam m/v 6 GB + grads 2 GB + … logits 2.1 GB"),
and an OOM surfaced as a raw ``RESOURCE_EXHAUSTED`` with no breakdown.
This module makes memory a first-class observable on the same "ride
existing flush boundaries, bit-identical when on" discipline as the
rest of monitor/:

- **live telemetry** — :func:`memory_record` samples
  :func:`deeplearning4j_tpu.memory.snapshot` into a ``{"type":
  "memory"}`` record (ui/stats schema). ``MonitorListener`` publishes
  one per listener flush (the host already syncs there — no extra
  device round-trips, clean runs stay bit-identical),
  ``ParallelInference`` at serving batch boundaries,
  ``MetricsRegistry.fold_memory`` exports ``dl4j_hbm_*`` gauges, and
  ``TelemetryServer`` serves it all live at ``GET /memory``.
- **static memory & compute plans** — :func:`capture_plan` reads
  ``compiled.memory_analysis()`` (temp/argument/output/generated-code
  bytes) and ``cost_analysis()`` (flops, bytes accessed) off every
  executable built by ``SameDiff.precompile()`` /
  ``precompile_output()`` (serving warmup buckets) into the
  process-wide :data:`PLANS` registry. With plan capture **enabled**
  (:func:`enable_plan_capture` — ``MonitorListener`` arms it), lazily
  jitted train programs are promoted to AOT executables at their first
  dispatch (``lower().compile()`` instead of the jit call's internal
  compile — the SAME lowering, one compile either way, bit-identical
  outputs) so their plans are captured too. The fit tiers report the
  active program via :func:`note_dispatch`, which is what lets
  ``MonitorListener`` export a live MFU-estimate gauge mid-fit:
  plan flops-per-step ÷ measured step time ÷ :func:`peak_flops`.
- **OOM forensics** — :func:`reraise_oom` converts a backend
  ``RESOURCE_EXHAUSTED`` caught at the fit / serving exec paths into a
  structured :class:`~deeplearning4j_tpu.memory.MemoryExhaustedError`
  carrying the last device snapshot, a live-array census, and the
  active program's plan. ``FaultTolerantFit`` publishes it as a
  ``{"type": "faults", "event": "oom"}`` record and aborts — a
  rollback cannot shrink the program, so OOM is
  non-retryable-with-diagnosis (docs/fault_tolerance.md).
- **headroom guards** — :func:`projected_headroom` (bytes_limit −
  bytes_in_use, min across devices that report a limit) backs the
  serving-side refusals: ``reload_from()`` and ``warmup()`` raise
  :class:`~deeplearning4j_tpu.memory.MemoryHeadroomError` instead of
  letting a too-big swap/bucket OOM a live server.

See docs/observability.md ("Memory observability").
"""
from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from deeplearning4j_tpu import memory
from deeplearning4j_tpu.memory import (MemoryExhaustedError,
                                       MemoryHeadroomError)
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer

#: memory-plan byte components, in stacked-budget-chart order
PLAN_BYTE_FIELDS = ("argument_bytes", "temp_bytes", "output_bytes",
                    "generated_code_bytes")


_graph_counter = itertools.count(1)


def graph_key(graph) -> Optional[int]:
    """Stable per-graph identity for plan attribution (assigned on
    first use, stored on the graph). The registry is process-global;
    this is what lets a listener publish only ITS model's plans when
    several models train/serve in one process."""
    if graph is None:
        return None
    gid = graph.__dict__.get("_memstats_gid")
    if gid is None:
        gid = graph.__dict__["_memstats_gid"] = next(_graph_counter)
    return gid


@dataclasses.dataclass
class MemoryPlan:
    """One compiled executable's static memory & compute plan."""
    label: str                       # "window_k8", "train_step", "output_b32"
    sig: str                         # placeholder shape signature (repr)
    steps: int = 1                   # train steps per dispatch (k)
    graph: Optional[int] = None      # graph_key() of the owning graph
    argument_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    alias_bytes: Optional[int] = None
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    t: float = 0.0

    @property
    def total_bytes(self) -> int:
        """Predicted peak footprint of one dispatch: arguments + temps
        + outputs + generated code (aliased/donated bytes excluded —
        they reuse argument space)."""
        return sum(int(getattr(self, f) or 0) for f in PLAN_BYTE_FIELDS) \
            - int(self.alias_bytes or 0)

    @property
    def flops_per_step(self) -> Optional[float]:
        if self.flops is None:
            return None
        return float(self.flops) / max(1, int(self.steps))

    def to_record(self) -> dict:
        """One ``{"type": "memory_plan"}`` record (ui/stats schema)."""
        rec = {"type": "memory_plan", "t": self.t or time.time(),
               "program": self.label, "sig": self.sig,
               "steps": int(self.steps),
               "total_bytes": int(self.total_bytes)}
        for f in PLAN_BYTE_FIELDS + ("alias_bytes",):
            v = getattr(self, f)
            if v is not None:
                rec[f] = int(v)
        if self.flops is not None:
            rec["flops"] = float(self.flops)
            rec["flops_per_step"] = float(self.flops_per_step)
        if self.bytes_accessed is not None:
            rec["bytes_accessed"] = float(self.bytes_accessed)
        return rec


def _analyze(compiled=None, lowered=None) -> Dict[str, Any]:
    """Read whatever analyses the stage object supports — memory from a
    ``Compiled``, cost from either — defensively: a backend without an
    analysis returns a partial plan, never an error."""
    out: Dict[str, Any] = {}
    if compiled is not None:
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                out["argument_bytes"] = int(ma.argument_size_in_bytes)
                out["temp_bytes"] = int(ma.temp_size_in_bytes)
                out["output_bytes"] = int(ma.output_size_in_bytes)
                out["generated_code_bytes"] = \
                    int(ma.generated_code_size_in_bytes)
                out["alias_bytes"] = int(ma.alias_size_in_bytes)
        except Exception:
            pass
    for stage in (compiled, lowered):
        if stage is None or "flops" in out:
            continue
        try:
            ca = stage.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if ca:
                if ca.get("flops") is not None:
                    out["flops"] = float(ca["flops"])
                if ca.get("bytes accessed") is not None:
                    out["bytes_accessed"] = float(ca["bytes accessed"])
        except Exception:
            pass
    return out


class MemoryPlans:
    """Process-wide registry of captured memory plans (the static half
    of the memory story), keyed by placeholder shape signature.

    ``note_dispatch`` is on the fit hot path: its fast path is one
    attribute check when no plans exist, one dict lookup + attribute
    store when they do — no locks, no allocation."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_sig: Dict[str, MemoryPlan] = {}
        self._order: List[str] = []          # capture order (publishing)
        self._active_sig: Optional[str] = None

    @staticmethod
    def _sig_key(sig) -> str:
        return sig if isinstance(sig, str) else repr(sig)

    def capture(self, label: str, sig, compiled=None, lowered=None,
                steps: int = 1, graph=None) -> Optional[MemoryPlan]:
        """Analyze one executable into the registry (idempotent per
        signature; re-capture refreshes). ``graph`` is the owning
        SameDiff (attribution — see :func:`graph_key`). Never raises —
        plan capture must not be able to break a compile path."""
        try:
            fields = _analyze(compiled=compiled, lowered=lowered)
            if not fields:
                return None
            key = self._sig_key(sig)
            plan = MemoryPlan(label=str(label), sig=key,
                              steps=max(1, int(steps)), t=time.time(),
                              graph=graph_key(graph), **fields)
            with self._lock:
                if key not in self._by_sig:
                    self._order.append(key)
                self._by_sig[key] = plan
            return plan
        except Exception:       # noqa: BLE001 — observability-only path
            return None

    def note_dispatch(self, sig, steps: int = 1) -> None:
        """The fit tiers report the program they just dispatched; the
        MFU gauge and OOM forensics read it back as the ACTIVE plan."""
        if not self._by_sig:
            return
        key = self._sig_key(sig)
        if key in self._by_sig:
            self._active_sig = key

    def active_plan(self) -> Optional[MemoryPlan]:
        key = self._active_sig
        return self._by_sig.get(key) if key is not None else None

    def get(self, sig) -> Optional[MemoryPlan]:
        return self._by_sig.get(self._sig_key(sig))

    def find(self, label: str) -> Optional[MemoryPlan]:
        """Newest plan captured under ``label``."""
        with self._lock:
            for key in reversed(self._order):
                p = self._by_sig.get(key)
                if p is not None and p.label == label:
                    return p
        return None

    def plans(self) -> List[MemoryPlan]:
        with self._lock:
            return [self._by_sig[k] for k in self._order]

    def __len__(self) -> int:
        return len(self._by_sig)

    def reset(self) -> None:
        with self._lock:
            self._by_sig.clear()
            self._order.clear()
            self._active_sig = None


#: The process-wide plan registry.
PLANS = MemoryPlans()

_capture_enabled = False


def enable_plan_capture() -> None:
    """Arm lazy-compile plan capture: the fit tiers promote a new
    placeholder signature's first compile to an AOT ``lower().
    compile()`` (same lowering the jit call would do — ONE compile
    either way, bit-identical outputs, tested) so its memory plan is
    inspectable. ``MonitorListener`` calls this at training start;
    AOT surfaces (``precompile``/warmup) capture unconditionally."""
    global _capture_enabled
    _capture_enabled = True


def disable_plan_capture() -> None:
    global _capture_enabled
    _capture_enabled = False


def plan_capture_enabled() -> bool:
    return _capture_enabled


def capture_plan(label: str, sig, compiled=None, lowered=None,
                 steps: int = 1, graph=None) -> Optional[MemoryPlan]:
    """Module-level convenience over :data:`PLANS` (see
    :meth:`MemoryPlans.capture`)."""
    return PLANS.capture(label, sig, compiled=compiled, lowered=lowered,
                         steps=steps, graph=graph)


def note_dispatch(sig, steps: int = 1) -> None:
    PLANS.note_dispatch(sig, steps)


# ---------------------------------------------------------------------
# live telemetry
def memory_record(epoch: Optional[int] = None,
                  iteration: Optional[int] = None,
                  source: str = "flush") -> dict:
    """One ``{"type": "memory"}`` record: per-device counters, totals,
    projected headroom, and the AllocationsTracker's tagged transfer
    totals. Pure host work — reading PJRT counters never syncs the
    device, so publishing these at flush boundaries keeps clean runs
    bit-identical (tested)."""
    snap = memory.snapshot()
    devices = [dataclasses.asdict(s) for s in snap]
    limits = [s.bytes_limit for s in snap if s.bytes_limit]
    tracker = memory.AllocationsTracker.get_instance()
    rec = {"type": "memory", "t": time.time(), "source": source,
           "bytes_in_use": sum(s.bytes_in_use for s in snap),
           "peak_bytes": max((s.peak_bytes or s.bytes_in_use)
                             for s in snap) if snap else 0,
           "bytes_limit": sum(limits),
           "devices": devices,
           "tracked": tracker.totals(),
           "tracked_counts": tracker.counts()}
    head = projected_headroom(snap)
    if head is not None:
        rec["headroom"] = int(head)
    skipped = sum(s.skipped_arrays for s in snap)
    if skipped:
        rec["live_skipped"] = int(skipped)
    if epoch is not None:
        rec["epoch"] = int(epoch)
    if iteration is not None:
        rec["iteration"] = int(iteration)
    return rec


def projected_headroom(snap: Optional[List] = None) -> Optional[int]:
    """Remaining HBM: min over devices reporting a ``bytes_limit`` of
    ``limit − in_use``. None when no device reports a limit (CPU) —
    headroom guards are then no-ops rather than false refusals."""
    if snap is None:
        snap = memory.snapshot()
    rooms = [s.bytes_limit - s.bytes_in_use
             for s in snap if s.bytes_limit]
    return min(rooms) if rooms else None


def check_headroom(required_bytes: int, what: str,
                   margin: float = 1.0) -> None:
    """Raise :class:`MemoryHeadroomError` when ``required_bytes ×
    margin`` exceeds the projected headroom (no-op where no device
    reports a limit)."""
    head = projected_headroom()
    if head is None:
        return
    need = int(required_bytes * float(margin))
    if need > head:
        raise MemoryHeadroomError(
            f"{what} needs ~{need / 2**20:.1f} MiB but projected HBM "
            f"headroom is {head / 2**20:.1f} MiB — refused before the "
            f"backend OOMs (docs/observability.md)",
            required_bytes=need, headroom_bytes=head)


# ---------------------------------------------------------------------
# OOM forensics
def is_resource_exhausted(exc: BaseException) -> bool:
    """Is this the backend's allocation-failure error? XLA surfaces it
    as ``XlaRuntimeError`` with a ``RESOURCE_EXHAUSTED:`` status (the
    chaos injector raises the same type+message)."""
    if isinstance(exc, MemoryExhaustedError):
        return False                 # already converted
    if "RESOURCE_EXHAUSTED" not in str(exc):
        return False
    try:
        from jax.errors import JaxRuntimeError
        if isinstance(exc, JaxRuntimeError):
            return True
    except ImportError:              # pragma: no cover - older jax
        pass
    return type(exc).__name__ == "XlaRuntimeError"


def oom_error(cause: BaseException, program: Optional[str] = None,
              step: Optional[int] = None,
              epoch: Optional[int] = None) -> MemoryExhaustedError:
    """Build the structured OOM with forensics attached: last device
    snapshot, live-array census, active program plan."""
    try:
        snap = memory.snapshot()
    except Exception:
        snap = []
    try:
        census = memory.live_census()
    except Exception:
        census = None
    plan = PLANS.active_plan()
    if plan is not None and program is None:
        program = plan.label
    return MemoryExhaustedError(
        f"device memory exhausted during "
        f"{program or 'execution'}: {cause}",
        program=program, step=step, epoch=epoch, snapshot=snap,
        census=census, plan=plan.to_record() if plan is not None else None)


def reraise_oom(exc: BaseException, program: Optional[str] = None,
                step: Optional[int] = None,
                epoch: Optional[int] = None) -> None:
    """Exec-path hook: convert a ``RESOURCE_EXHAUSTED`` into a
    :class:`MemoryExhaustedError` with forensics (raises); any other
    exception passes through untouched (returns)."""
    if is_resource_exhausted(exc):
        raise oom_error(exc, program=program, step=step,
                        epoch=epoch) from exc


# ---------------------------------------------------------------------
# lazy-compile promotion (the "SameDiff jit" plan-capture path)
def promote_dispatch(disp, args: Tuple, sig, label: str,
                     steps: int = 1, graph=None) -> bool:
    """With plan capture enabled, compile a NEW placeholder signature
    through the AOT path (``disp.lower(*args).compile()``) and install
    it in ``disp.aot`` so (a) its memory plan is captured and (b) the
    dispatch about to happen hits the prebuilt executable. This
    replaces the jit call's internal compile — same lowering, one
    compile either way. Returns True when promoted. Any failure falls
    back to the lazy jit path silently (observability must not break
    training)."""
    if not _capture_enabled:
        return False
    aot = getattr(disp, "aot", None)
    if aot is None or sig in aot:
        return False
    try:
        with _tracer.span("compile.plan_capture", cat="compile",
                          target=label):
            compiled = disp.lower(*args).compile()
        aot[sig] = compiled
        capture_plan(label, sig, compiled=compiled, steps=steps,
                     graph=graph)
        return True
    except Exception:       # noqa: BLE001 — fall back to lazy jit
        return False


# ---------------------------------------------------------------------
# MFU estimate
#: device-kind substring -> peak dense FLOPs/s per chip (bf16). The
#: bench's V5E number; extend as kinds show up. Overridable via the
#: DL4J_PEAK_FLOPS env var (any accelerator, CI on CPU).
_PEAK_FLOPS_BY_KIND = (
    ("v5 lite", 394.0e12), ("v5e", 394.0e12),
    ("v5p", 459.0e12), ("v5", 459.0e12),
    ("v4", 275.0e12), ("v6", 918.0e12),
)


def peak_flops() -> Optional[float]:
    """Peak FLOPs/s for the MFU denominator: the ``DL4J_PEAK_FLOPS``
    env var when set, else a device-kind table, else None (no MFU
    gauge — better absent than wrong)."""
    import os
    env = os.environ.get("DL4J_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax
        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:
        return None
    for sub, flops in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return flops
    return None


def mfu_estimate(step_seconds: float) -> Optional[Tuple[float, float]]:
    """Live MFU estimate from the ACTIVE program's plan: ``(flops_per
    _step / step_seconds / peak, flops_per_step)``. None when no plan
    with flops is active, step time is unknown, or the peak is unknown
    — the gauge is simply not exported rather than exported wrong."""
    plan = PLANS.active_plan()
    if plan is None or plan.flops_per_step is None or step_seconds <= 0:
        return None
    fps = plan.flops_per_step
    peak = peak_flops()
    if peak is None or peak <= 0:
        return None
    return fps / step_seconds / peak, fps


__all__ = ["MemoryPlan", "MemoryPlans", "PLANS", "graph_key",
           "capture_plan",
           "note_dispatch", "enable_plan_capture", "disable_plan_capture",
           "plan_capture_enabled", "memory_record", "projected_headroom",
           "check_headroom", "is_resource_exhausted", "oom_error",
           "reraise_oom", "promote_dispatch", "peak_flops",
           "mfu_estimate", "MemoryExhaustedError", "MemoryHeadroomError"]
