"""Device-side per-layer tensor statistics — the in-graph half of the
DL4J ``BaseStatsListener`` parity story.

The reference streams per-layer parameter/gradient/update histograms and
update:param ratios to its web UI from *inside* the per-op interpreter
(ui-model/.../stats/BaseStatsListener.java). Our port could only diff
host copies of parameters at epoch boundaries (``ui/stats.StatsListener``)
— under the fused-window tier gradients never reach the host at all, so
the single most diagnostic training-health signal (per-layer grad norms,
dead/exploding-layer detection) was invisible.

This module computes those summaries *inside* the jitted train step:

- **stat families** (``TensorStatsConfig.families``): ``grads`` (the raw
  per-step gradients, pre-clip — the diagnostic signal), ``updates``
  (the post-clip/post-updater update tensor ``u`` the step SUBTRACTS,
  ``new_params = params - u`` — the DL4J StatsListener convention, so
  its sign follows the gradient, not the parameter movement; the
  applied delta is ``-u``) and ``params`` (the post-update parameters);
- **per-layer summary vector**: L2 norm, mean |x|, min, max, nonfinite
  count, zero count (``SCALAR_FIELDS`` order) — every leaf reduces to
  the same fixed-size vector regardless of its shape, so the per-family
  result stacks to ``(layers, 6)``;
- **fixed log2-magnitude histogram**: ``hist_bins`` bins over
  ``floor(log2|x|)`` clipped to ``[hist_min_exp, hist_min_exp +
  hist_bins)`` — a dtype-health view (how much of a tensor sits near
  underflow / overflow) whose bin edges never move, so histograms are
  comparable across steps, layers and runs (unlike the reference's
  data-dependent bin ranges).

Sampling is **in-graph**: the step body evaluates the summaries under a
``lax.cond`` only on steps where :func:`sample_mask` fires (every
``every_n``-th step; with gradient accumulation, every ``every_n``-th
*update* so the ``updates`` family always describes a real apply). The
fused-window tier folds the sampled stats into the ``lax.scan`` carry
exactly like the divergence sentinel (faults/sentinels.py): a K-step
window returns ONE stats pytree (the last sampled step's) plus the
int32 iteration it was sampled at (``-1`` = no sample point in this
window), and the host fetches it at the flush boundaries it already
syncs on — in the same ``device_get`` burst as losses and sentinel
verdicts. Parameter math is untouched: stats-on training is
bit-identical to stats-off (tested).

Host side, :func:`build_record` turns a fetched stats pytree into one
``{"type": "tensorstats"}`` record (ui/stats.py schema), delivered to
listeners through the ``tensorstats_done`` rail; :class:`MonitorListener
<deeplearning4j_tpu.monitor.steptime.MonitorListener>` persists + folds
them (``dl4j_layer_*``) and :class:`LayerHealthWatcher` turns a dead or
exploding layer into a structured, recoverable fault.

See docs/observability.md ("Tensor statistics").
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Sequence, Tuple

#: per-leaf summary vector layout (the (layers, 6) scalar stack)
SCALAR_FIELDS = ("l2", "mean_abs", "min", "max", "nonfinite", "zeros")

#: family name -> record field prefix ("grads" -> "grad_l2", ...)
FAMILY_PREFIX = {"grads": "grad", "updates": "update", "params": "param"}

#: canonical family order (configs normalize to this, cache keys are
#: stable under permuted user input)
_FAMILY_ORDER = ("grads", "updates", "params")


@dataclasses.dataclass(frozen=True)
class TensorStatsConfig:
    """Sampling cadence + stat shape for the in-graph tensor statistics.

    ``every_n``: sample every Nth step (absolute iterations; with
    ``accum_steps > 1`` every Nth *update*, aligned to apply
    boundaries). The overhead tier: stats cost is paid only on sampled
    steps (``lax.cond``), so the amortized cost scales as 1/every_n —
    ``bench.py tensorstats_overhead`` guards ≤3% at the default.
    ``families``: which of grads/updates/params to summarize.
    ``hist_bins``/``hist_min_exp``: the fixed log2-magnitude histogram
    covers exponents ``[hist_min_exp, hist_min_exp + hist_bins)``;
    values outside clip to the edge bins.
    ``sample_cap``: distribution stats (mean |x|, min/max, zero count,
    the histogram, the sampled nonfinite count) are computed over a
    deterministic strided subsample of at most this many elements per
    leaf (0 = exact full-tensor stats). The L2 norm is ALWAYS exact —
    it feeds ``update_ratio``, the layer-health signal — and its
    full-tensor accumulator also lower-bounds the nonfinite count (a
    NaN/Inf anywhere poisons the sum even when the subsample missed
    it). The config is frozen (it is baked into compiled-program cache
    keys via :meth:`key`).
    """
    every_n: int = 25
    families: Tuple[str, ...] = _FAMILY_ORDER
    hist_bins: int = 20
    hist_min_exp: int = -16
    sample_cap: int = 16384

    def __post_init__(self):
        if int(self.every_n) < 1:
            raise ValueError("tensorstats every_n must be >= 1")
        if int(self.hist_bins) < 1:
            raise ValueError("tensorstats hist_bins must be >= 1")
        if int(self.sample_cap) < 0:
            raise ValueError("tensorstats sample_cap must be >= 0 "
                             "(0 = exact)")
        fams = tuple(f for f in _FAMILY_ORDER if f in tuple(self.families))
        unknown = set(self.families) - set(_FAMILY_ORDER)
        if unknown or not fams:
            raise ValueError(
                f"tensorstats families must be a non-empty subset of "
                f"{_FAMILY_ORDER}, got {tuple(self.families)}")
        object.__setattr__(self, "every_n", int(self.every_n))
        object.__setattr__(self, "families", fams)
        object.__setattr__(self, "hist_bins", int(self.hist_bins))
        object.__setattr__(self, "hist_min_exp", int(self.hist_min_exp))
        object.__setattr__(self, "sample_cap", int(self.sample_cap))

    def key(self) -> tuple:
        """Hashable identity for compiled-program cache keys: two
        configs with equal keys trace to identical programs."""
        return (self.every_n, self.families, self.hist_bins,
                self.hist_min_exp, self.sample_cap)

    def to_json(self) -> dict:
        return {"every_n": self.every_n, "families": list(self.families),
                "hist_bins": self.hist_bins,
                "hist_min_exp": self.hist_min_exp,
                "sample_cap": self.sample_cap}

    @staticmethod
    def from_json(d) -> "Optional[TensorStatsConfig]":
        if d is None or d is False:
            return None
        if d is True:
            return TensorStatsConfig()
        return TensorStatsConfig(
            every_n=d.get("every_n", 25),
            families=tuple(d.get("families", _FAMILY_ORDER)),
            hist_bins=d.get("hist_bins", 20),
            hist_min_exp=d.get("hist_min_exp", -16),
            sample_cap=d.get("sample_cap", 16384))


def layer_names(params: Dict[str, object]) -> Tuple[str, ...]:
    """THE canonical layer order: sorted trainable-param names. The
    device-side stat rows (``summarize_tree``/``compute_stats``) and
    the host-side record labels (``build_record``) must agree
    element-for-element — every call site goes through this ONE
    helper, because a silent ordering drift would attribute every
    layer's stats to the wrong name with no error (the same
    single-key-construction rule as ``window_trace_set``)."""
    return tuple(sorted(params.keys()))


def normalize(cfg) -> Optional[TensorStatsConfig]:
    """``TrainingConfig.tensorstats`` accepts ``True`` (defaults), a
    :class:`TensorStatsConfig`, or a serde dict — canonicalize."""
    if cfg is None or cfg is False:     # False = disabled, like sentinel
        return None
    if isinstance(cfg, TensorStatsConfig):
        return cfg
    if cfg is True:
        return TensorStatsConfig()
    if isinstance(cfg, dict):
        return TensorStatsConfig.from_json(cfg)
    raise TypeError(f"tensorstats must be True, a TensorStatsConfig or "
                    f"a dict, got {type(cfg).__name__}")


# ---------------------------------------------------------------------------
# traced (device-side) summaries — called only inside jit traces

def summarize_leaf(x, cfg: TensorStatsConfig):
    """One leaf -> ``((6,) float32 scalars, (hist_bins,) int32 hist)``.

    Engineered for the in-scan hot path (the naive full-tensor
    formulation cost ~10x a train step per sampled step on CPU):

    - ``l2`` is EXACT, via one dot-product over the full tensor (the
      one reduction backends run at memory bandwidth) — it feeds
      ``update_ratio``, the layer-health signal. Nonfinite entries
      propagate into it: a poisoned layer has no meaningful norm, and
      a NaN l2 is itself diagnostic.
    - the distribution stats (mean |x|, min/max over finite entries,
      zero count, sampled nonfinite count, histogram) run over a
      deterministic strided subsample of ≤ ``sample_cap`` elements
      (exact when the leaf is smaller). ``nonfinite`` is
      lower-bounded by the full-tensor norm accumulator: any NaN/Inf
      poisons the dot even when the subsample misses it, reporting at
      least 1. (An f32-overflowing norm reads the same way — by the
      time ``sum(x^2)`` exceeds f32 range the layer IS exploding.)
    - histogram binning reads ``floor(log2|x|)`` straight from the
      float32 exponent bits (no transcendental per element); denormals
      clip into the lowest bin, zeros and nonfinites are excluded.
    """
    import jax
    import jax.numpy as jnp
    xf = jnp.ravel(x).astype(jnp.float32)
    n = xf.size
    sumsq = jnp.vdot(xf, xf)
    l2 = jnp.sqrt(sumsq)
    cap = cfg.sample_cap
    stride = max(1, -(-n // cap)) if cap else 1
    xs = xf[::stride]
    m = max(1, xs.size)
    finite = jnp.isfinite(xs)
    xz = jnp.where(finite, xs, 0.0)
    bits = jax.lax.bitcast_convert_type(xs, jnp.int32)
    biased_exp = (bits >> 23) & 0xFF
    nonzero = (bits & 0x7FFFFFFF) != 0
    nonfinite = jnp.maximum(
        jnp.sum(jnp.logical_not(finite)),
        jnp.logical_not(jnp.isfinite(sumsq)).astype(jnp.int32))
    scalars = jnp.stack([
        l2, jnp.sum(jnp.abs(xz)) / m,
        jnp.min(jnp.where(finite, xs, jnp.inf)),
        jnp.max(jnp.where(finite, xs, -jnp.inf)),
        nonfinite.astype(jnp.float32),
        jnp.sum(finite & jnp.logical_not(nonzero)).astype(jnp.float32)])
    # floor(log2|x|) == biased_exp - 127 for normal floats
    idx = jnp.clip(biased_exp - 127 - cfg.hist_min_exp, 0,
                   cfg.hist_bins - 1)
    mask = finite & nonzero
    # one-hot sum, not scatter-add: B small vectorized passes over the
    # subsample beat XLA-CPU's serial scatter by ~10x
    onehot = (idx[:, None] == jnp.arange(cfg.hist_bins)[None, :]) \
        & mask[:, None]
    hist = jnp.sum(onehot, axis=0, dtype=jnp.int32)
    return scalars, hist


def summarize_tree(tree: Dict[str, object], names: Sequence[str],
                   cfg: TensorStatsConfig):
    """Stack per-leaf summaries over ``names`` (the canonical sorted
    layer order) -> ``((L, 6) scalars, (L, hist_bins) hist)``."""
    import jax.numpy as jnp
    scalars, hists = [], []
    for n in names:
        s, h = summarize_leaf(tree[n], cfg)
        scalars.append(s)
        hists.append(h)
    return jnp.stack(scalars), jnp.stack(hists)


def compute_stats(cfg: TensorStatsConfig, names: Sequence[str],
                  grads=None, updates=None, params=None):
    """The sampled-branch payload: ``{family: (scalars, hist)}`` for
    every configured family (callers pass the trees the step already
    produced)."""
    trees = {"grads": grads, "updates": updates, "params": params}
    out = {}
    for fam in cfg.families:
        tree = trees[fam]
        if tree is None:
            raise ValueError(f"tensorstats family {fam!r} configured but "
                             f"no tree passed")
        out[fam] = summarize_tree(tree, names, cfg)
    return out


def zeros_stats(n_layers: int, cfg: TensorStatsConfig):
    """The not-sampled-branch payload: the same pytree structure, all
    zeros (shape-stable across the ``lax.cond``)."""
    import jax.numpy as jnp
    return {fam: (jnp.zeros((n_layers, len(SCALAR_FIELDS)), jnp.float32),
                  jnp.zeros((n_layers, cfg.hist_bins), jnp.int32))
            for fam in cfg.families}


def sample_mask(iteration, cfg: TensorStatsConfig, accum_steps: int = 1):
    """Traced sampling predicate for the absolute ``iteration``.

    Plain training samples every ``every_n``-th step. With gradient
    accumulation the cadence counts *updates* and aligns to apply
    boundaries — a mid-cycle micro-step has a zero ``updates`` delta
    that would read as a dead layer, so sampling there is banned by
    construction."""
    if accum_steps <= 1:
        return iteration % cfg.every_n == 0
    nxt = iteration + 1
    return (nxt % accum_steps == 0) & \
        ((nxt // accum_steps) % cfg.every_n == 0)


# ---------------------------------------------------------------------------
# host side: fetched stats -> {"type": "tensorstats"} records

def build_record(names: Sequence[str], stats: Dict[str, tuple],
                 iteration: int, epoch: int,
                 cfg: TensorStatsConfig) -> dict:
    """One fetched stats pytree (host numpy after ``device_get``) ->
    one ``{"type": "tensorstats"}`` record (schema: ui/stats.py).

    Non-finite float stats serialize as ``None``, never NaN/Infinity —
    ``json.dumps`` would emit the non-RFC ``NaN`` token and corrupt the
    JSONL file and the /stats NDJSON for strict parsers. No signal is
    lost: the ``*_nonfinite`` counts (exact-lower-bounded by the norm
    accumulator) are what carry the poison diagnostic."""
    import math

    import numpy as np

    def _clean(v: float):
        return v if math.isfinite(v) else None

    layers: Dict[str, dict] = {}
    for li, name in enumerate(names):
        ent: Dict[str, object] = {}
        for fam, (scalars, hist) in stats.items():
            pfx = FAMILY_PREFIX[fam]
            row = np.asarray(scalars)[li]
            for fi, field in enumerate(SCALAR_FIELDS):
                v = float(row[fi])
                ent[f"{pfx}_{field}"] = int(v) \
                    if field in ("nonfinite", "zeros") else _clean(v)
            ent[f"{pfx}_hist"] = [int(c) for c in np.asarray(hist)[li]]
        if ent.get("update_l2") is not None and \
                ent.get("param_l2") is not None:
            ent["update_ratio"] = ent["update_l2"] / \
                (ent["param_l2"] + 1e-12)
        elif "update_l2" in ent and "param_l2" in ent:
            ent["update_ratio"] = None      # poisoned norm -> no ratio
        layers[name] = ent
    return {"type": "tensorstats", "iter": int(iteration),
            "epoch": int(epoch), "t": time.time(),
            "every_n": cfg.every_n, "hist_min_exp": cfg.hist_min_exp,
            "layers": layers}


class LayerHealthWatcher:
    """Listener-rail watcher over ``tensorstats`` records: raises a
    structured :class:`~deeplearning4j_tpu.faults.errors.
    TrainingDivergedError` when a layer goes **dead** (update:param
    ratio below ``dead_ratio`` for ``patience`` consecutive samples —
    the optimizer has stopped moving it) or **exploding** (ratio above
    ``explode_ratio`` — the update is rewriting the parameter
    wholesale). The per-layer counterpart of
    :class:`~deeplearning4j_tpu.faults.sentinels.LossSpikeWatcher`:
    riding the same listener rail, it makes ``FaultTolerantFit`` roll
    back on layer-level pathologies a healthy-looking loss curve hides
    (docs/fault_tolerance.md).

    A **poisoned** layer (any family's nonfinite count > 0 — the
    record's ratio is ``None`` because the norms are meaningless) is
    flagged immediately, warmup included (``flag_nonfinite=True``):
    this is the listener-rail backstop for runs without the device
    sentinel, and a NaN ratio must never slip through the threshold
    comparisons unflagged.

    ``warmup`` samples per layer are observed before dead/exploding
    verdicts fire (init transients routinely look dead or hot).
    ``reset()`` forgets all state — FaultTolerantFit calls it on
    rollback so replayed timelines are judged fresh. Decisions are
    appended to ``events`` and published as ``{"type": "faults",
    "event": "layer_health"}`` records when a storage is attached.
    """

    #: epoch-only cadence ask: never forces extra mid-epoch flushes
    #: (same huge-frequency idiom as PlateauWatcher) — the watcher
    #: rides whatever tensorstats cadence the run already has
    frequency = 1_000_000_000

    def __init__(self, dead_ratio: float = 1e-9,
                 explode_ratio: float = 1.0, patience: int = 3,
                 warmup: int = 2, storage=None,
                 flag_nonfinite: bool = True):
        if explode_ratio <= dead_ratio:
            raise ValueError("explode_ratio must exceed dead_ratio")
        self.dead_ratio = float(dead_ratio)
        self.explode_ratio = float(explode_ratio)
        self.patience = max(1, int(patience))
        self.warmup = max(0, int(warmup))
        self.storage = storage
        self.flag_nonfinite = bool(flag_nonfinite)
        self.events: List[dict] = []
        self.reset()

    def reset(self) -> None:
        """Forget per-layer sample counts and dead-streaks (the
        rollback listener-reset hook, faults/recovery.py)."""
        self._seen: Dict[str, int] = {}
        self._dead_streak: Dict[str, int] = {}

    def _flag(self, cause: str, layer: str, ratio: float, record: dict):
        import math
        ev = {"type": "faults", "event": "layer_health", "cause": cause,
              "layer": layer,
              "ratio": ratio if math.isfinite(ratio) else None,
              "iter": record.get("iter"), "t": time.time()}
        self.events.append(ev)
        if self.storage is not None:
            self.storage.put(ev)
        from deeplearning4j_tpu.faults.errors import TrainingDivergedError
        raise TrainingDivergedError(
            f"layer {layer!r} {cause.replace('_', ' ')}: update:param "
            f"ratio {ratio:.3g} at iteration {record.get('iter')} "
            f"(dead < {self.dead_ratio:.3g}, exploding > "
            f"{self.explode_ratio:.3g})",
            step=record.get("iter"), epoch=record.get("epoch"),
            cause=cause, value=ratio)

    # -- listener rail (duck-typed: the only callback that matters is
    # tensorstats_done; the rest of the protocol is no-op) --------------
    def on_training_start(self, sd) -> None: ...
    def on_training_end(self, sd) -> None: ...
    def on_epoch_start(self, sd, epoch: int) -> None: ...
    def on_epoch_end(self, sd, epoch: int, mean_loss) -> None: ...
    def iterations_done(self, sd, epoch: int, iterations, losses) -> None:
        ...

    def tensorstats_done(self, sd, epoch: int,
                         records: Sequence[dict]) -> None:
        for rec in records:
            for layer, ent in rec.get("layers", {}).items():
                if self.flag_nonfinite and any(
                        ent.get(f"{p}_nonfinite", 0)
                        for p in FAMILY_PREFIX.values()):
                    # poisoned layer: the ratio is None/meaningless and
                    # would otherwise sail past both threshold checks —
                    # flag regardless of warmup (categorical, not a
                    # transient)
                    self._flag("poisoned_layer", layer,
                               float("nan"), rec)
                ratio = ent.get("update_ratio")
                if ratio is None:
                    continue
                seen = self._seen.get(layer, 0)
                self._seen[layer] = seen + 1
                if seen < self.warmup:
                    continue
                if ratio > self.explode_ratio:
                    self._flag("exploding_layer", layer, float(ratio),
                               rec)
                if ratio < self.dead_ratio:
                    streak = self._dead_streak.get(layer, 0) + 1
                    self._dead_streak[layer] = streak
                    if streak >= self.patience:
                        self._flag("dead_layer", layer, float(ratio),
                                   rec)
                else:
                    self._dead_streak[layer] = 0


__all__ = ["TensorStatsConfig", "LayerHealthWatcher", "SCALAR_FIELDS",
           "FAMILY_PREFIX", "summarize_leaf", "summarize_tree",
           "compute_stats", "zeros_stats", "sample_mask", "build_record",
           "normalize", "layer_names"]
