"""MetricsRegistry — one labeled namespace over every subsystem's signals.

Serving counters (``serving/metrics.ServingMetrics``), the fit tiers'
dispatch accounting (``sd.last_fit_stats``), checkpoint commit timings,
fault-rail events and step-time breakdowns each grew up with their own
record shape. This registry folds them into ONE namespace of labeled
counters / gauges / histograms so a scrape endpoint, a dashboard, or a
test can ask "how is this process doing" without knowing five schemas:

    reg = MetricsRegistry()
    reg.fold_serving(server.metrics)
    reg.fold_dispatch(sd.last_fit_stats)
    reg.fold_storage(stats_storage)        # checkpoint/faults/steptime
    print(reg.to_prometheus_text())        # standard exposition format
    reg.publish(stats_storage)             # {"type": "metrics"} record

Metric identity is ``name + sorted(labels)``; all operations are
thread-safe behind one registry lock (recording is dict math — no I/O).
Naming follows the Prometheus conventions: ``<namespace>_<subsystem>_
<metric>_<unit>``, counters end in ``_total``, histograms expose
``_bucket``/``_sum``/``_count`` series.

The reference has no analogue — deeplearning4j-ui charts families
straight off StatsStorage; the registry is what lets the SAME numbers
feed StatsStorage records (ui/report.py), a Prometheus scrape, and
assertions in tests without three collection paths.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# log-spaced seconds buckets: 100 µs .. 100 s (checkpoint commits and
# window flushes live at opposite ends of this range)
_DEFAULT_BUCKETS = tuple(
    round(b, 6) for e in range(-4, 3) for b in (10.0 ** e, 2.5 * 10.0 ** e,
                                                5.0 * 10.0 ** e))

# log-spaced dimensionless buckets for update:param ratios (healthy
# training sits around 1e-4..1e-2; the edges are the dead/exploding
# regimes LayerHealthWatcher flags)
_RATIO_BUCKETS = tuple(10.0 ** e for e in range(-9, 2))

#: wall-clock process start, for dl4j_process_uptime_seconds
_PROCESS_START_T = time.time()


def _process_self_metrics() -> Dict[str, float]:
    """Process self-telemetry exported with every scrape: uptime, and
    resident-set bytes where the platform exposes them (/proc — Linux;
    silently absent elsewhere)."""
    out = {"process_uptime_seconds":
           round(max(0.0, time.time() - _PROCESS_START_T), 3)}
    try:
        import os
        with open("/proc/self/statm", "r", encoding="ascii") as fh:
            pages = int(fh.read().split()[1])
        out["process_rss_bytes"] = float(
            pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    return out


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: LabelKey, extra: Sequence[Tuple[str, str]] = ()) -> str:
    items = list(key) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in items)
    return "{" + body + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


class _Histogram:
    """Cumulative-bucket histogram (prometheus ``le`` semantics)."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # + the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        idx = len(self.buckets)
        for i, b in enumerate(self.buckets):
            if v <= b:
                idx = i
                break
        self.counts[idx] += 1
        self.sum += v
        self.count += 1


class _Family:
    """One metric name: type, help text, per-label-set values."""

    __slots__ = ("name", "kind", "help", "values", "buckets")

    def __init__(self, name: str, kind: str, help_: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind                  # counter | gauge | histogram
        self.help = help_
        self.values: Dict[LabelKey, object] = {}
        self.buckets = buckets


class MetricsRegistry:
    """Thread-safe labeled counters / gauges / histograms with
    Prometheus text export and ui/stats publication."""

    def __init__(self, namespace: str = "dl4j"):
        import weakref
        self.namespace = namespace
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        # per-storage fold high-water marks: fold_storage() must be
        # idempotent over a growing storage (a scrape endpoint re-folds
        # on every scrape; counters would otherwise double-count).
        # _fold_lock serializes whole folds — a /metrics scrape thread
        # and the MonitorListener's flush thread fold the SAME storage
        # into the same registry, and racing on the mark would fold the
        # same records twice (a separate lock: the fold body takes
        # self._lock per metric op)
        self._fold_marks: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._fold_lock = threading.Lock()

    # -- core recording -------------------------------------------------
    def _family(self, name: str, kind: str, help_: str,
                buckets: Optional[Sequence[float]] = None) -> _Family:
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = _Family(name, kind, help_, buckets)
        elif fam.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {fam.kind}, "
                f"not {kind}")
        if help_ and not fam.help:
            fam.help = help_
        return fam

    def inc(self, name: str, value: float = 1.0, help: str = "",
            **labels) -> None:
        """Add ``value`` to a counter (monotonic; use gauges for
        levels)."""
        if value < 0:
            raise ValueError(f"counter {name!r} cannot decrease")
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "counter", help)
            fam.values[key] = float(fam.values.get(key, 0.0)) + float(value)

    def set_gauge(self, name: str, value: float, help: str = "",
                  **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "gauge", help)
            fam.values[key] = float(value)

    def observe(self, name: str, value: float, help: str = "",
                buckets: Optional[Sequence[float]] = None,
                **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            fam = self._family(name, "histogram", help,
                               buckets or _DEFAULT_BUCKETS)
            h = fam.values.get(key)
            if h is None:
                h = fam.values[key] = _Histogram(fam.buckets)
            h.observe(value)

    # -- readout --------------------------------------------------------
    def get(self, name: str, **labels):
        """Current value of a counter/gauge (None if absent)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                return None
            return fam.values.get(_label_key(labels))

    def collect(self) -> Dict[str, object]:
        """Flat ``{"name{label=\"v\"}": value}`` snapshot (histograms
        contribute ``_sum``/``_count``)."""
        out: Dict[str, object] = {}
        with self._lock:
            for fam in self._families.values():
                full = f"{self.namespace}_{fam.name}"
                for key, val in fam.values.items():
                    if isinstance(val, _Histogram):
                        out[f"{full}_sum{_fmt_labels(key)}"] = \
                            round(val.sum, 9)
                        out[f"{full}_count{_fmt_labels(key)}"] = val.count
                    else:
                        out[f"{full}{_fmt_labels(key)}"] = val
        return out

    def to_prometheus_text(self) -> str:
        """The Prometheus text exposition format (0.0.4): HELP/TYPE
        headers + one sample per line, histograms with cumulative
        ``le`` buckets."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._families):
                fam = self._families[name]
                full = f"{self.namespace}_{fam.name}"
                if fam.help:
                    lines.append(f"# HELP {full} {_escape(fam.help)}")
                lines.append(f"# TYPE {full} {fam.kind}")
                for key in sorted(fam.values):
                    val = fam.values[key]
                    if isinstance(val, _Histogram):
                        cum = 0
                        for b, c in zip(val.buckets, val.counts):
                            cum += c
                            lines.append(
                                f"{full}_bucket"
                                f"{_fmt_labels(key, [('le', repr(b))])} "
                                f"{cum}")
                        lines.append(
                            f"{full}_bucket"
                            f"{_fmt_labels(key, [('le', '+Inf')])} "
                            f"{val.count}")
                        lines.append(f"{full}_sum{_fmt_labels(key)} "
                                     f"{val.sum!r}")
                        lines.append(f"{full}_count{_fmt_labels(key)} "
                                     f"{val.count}")
                    else:
                        lines.append(f"{full}{_fmt_labels(key)} {val!r}")
            # process self-telemetry: synthesized at scrape time, never
            # stored (uptime/RSS are instantaneous reads, not state)
            for name, val in sorted(_process_self_metrics().items()):
                full = f"{self.namespace}_{name}"
                lines.append(f"# HELP {full} process self-telemetry")
                lines.append(f"# TYPE {full} gauge")
                lines.append(f"{full} {val!r}")
        return "\n".join(lines) + "\n"

    def to_record(self) -> dict:
        """One ``{"type": "metrics"}`` record in the ui/stats JSON-lines
        convention (ui/stats.py module docstring)."""
        return {"type": "metrics", "t": time.time(),
                "namespace": self.namespace, "metrics": self.collect()}

    def publish(self, storage) -> dict:
        """Append the current snapshot to a ui.stats.StatsStorage."""
        rec = self.to_record()
        storage.put(rec)
        return rec

    # -- adapters: fold the existing per-subsystem shapes ---------------
    def fold_serving(self, metrics_or_record) -> None:
        """Fold a ``serving.ServingMetrics`` (or its ``to_record()``
        dict / a stored ``{"type": "serving"}`` record) into
        ``serving_*`` metrics."""
        rec = metrics_or_record
        if hasattr(rec, "to_record"):
            rec = rec.to_record()
        for name, v in rec.get("counters", {}).items():
            self.set_gauge(f"serving_{name}_total", v,
                           help="serving lifetime counter")
        for cause, n in rec.get("failure_causes", {}).items():
            self.set_gauge("serving_failures_by_cause_total", n,
                           help="failed requests by cause", cause=cause)
        for cause, n in rec.get("timeout_causes", {}).items():
            self.set_gauge("serving_timeouts_by_cause_total", n,
                           help="timed-out requests by cause", cause=cause)
        for lane, summ in rec.get("latency_ms", {}).items():
            for stat in ("mean", "p50", "p95", "p99", "max"):
                if stat in summ:
                    self.set_gauge(
                        "serving_latency_ms", summ[stat],
                        help="serving latency summary", lane=lane,
                        stat=stat)
            # low-sample propagation (serving/metrics.py summary()):
            # a p99 read from < 32 samples is the max, not a p99 —
            # dashboards alerting on serving_latency_ms must be able
            # to gate on this flag per lane instead of paging on a
            # cold-start artifact
            if "count" in summ:
                self.set_gauge("serving_latency_count", summ["count"],
                               help="samples behind the latency "
                                    "summary", lane=lane)
            if "low_sample" in summ:
                self.set_gauge("serving_latency_low_sample",
                               1 if summ["low_sample"] else 0,
                               help="1 when the lane's percentiles "
                                    "rest on < 32 samples", lane=lane)
        batch = rec.get("batch", {})
        if batch:
            self.set_gauge("serving_batch_mean_size",
                           batch.get("mean_size", 0.0))
            self.set_gauge("serving_batch_padding_waste_ratio",
                           batch.get("padding_waste", 0.0))
        # resilience rail (serving/resilience.py): breaker state as an
        # enum gauge (0 closed / 1 half-open / 2 open — the /healthz
        # 503 signal on a dashboard) + last hot-reload provenance; the
        # shed/requeue/restart/quarantine/reload counters already
        # export through the generic serving_<counter>_total loop above
        # generative tier (serving/generative.py): occupancy + token
        # throughput gauges; the token/prefill/step counters already
        # export through the generic serving_<counter>_total loop
        gen = rec.get("generative") or {}
        if gen:
            self.set_gauge("serving_slot_occupancy_ratio",
                           gen.get("slot_occupancy", 0.0),
                           help="mean active slots / max_slots per "
                                "decode step")
            self.set_gauge("serving_tokens_per_sec",
                           gen.get("tokens_per_sec", 0.0),
                           help="lifetime generated-token rate")
            self.set_gauge("serving_max_slots",
                           gen.get("max_slots", 0),
                           help="KV cache slots")
            # speculative decoding (satellite lane): the acceptance
            # rate IS the speedup knob — accepted draft tokens ride a
            # verify dispatch for free; .get() defense keeps records
            # written before the lane existed folding cleanly
            if gen.get("spec_rounds"):
                self.set_gauge("serving_draft_acceptance_rate",
                               gen.get("draft_acceptance_rate", 0.0),
                               help="accepted / drafted speculative "
                                    "tokens (lifetime)")
                self.set_gauge("serving_draft_tokens_rejected_total",
                               gen.get("draft_rejected", 0),
                               help="drafted tokens the target's "
                                    "verify pass rejected")
        # paged KV tier (serving/paged/): pool + prefix-cache gauges;
        # every ratio is safe_ratio'd at the source (0.0 at cold start,
        # never NaN — satellite rule for the new series)
        paged = rec.get("paged") or {}
        if paged:
            self.set_gauge("serving_pool_blocks",
                           paged.get("num_blocks", 0),
                           help="usable KV blocks in the pool")
            self.set_gauge("serving_pool_block_size",
                           paged.get("block_size", 0),
                           help="tokens per KV block")
            self.set_gauge("serving_pool_occupancy_ratio",
                           paged.get("pool_occupancy", 0.0),
                           help="mean held blocks / pool capacity per "
                                "decode step")
            self.set_gauge("serving_prefix_hit_rate",
                           paged.get("prefix_hit_rate", 0.0),
                           help="prefix-cache hits / lookups")
            self.set_gauge("serving_blocks_per_request",
                           paged.get("blocks_per_request", 0.0),
                           help="mean KV blocks held per retired "
                                "request")
            self.set_gauge("serving_pool_cached_blocks",
                           paged.get("cached_blocks", 0),
                           help="prefix-cache registered blocks")
            self.set_gauge("serving_pool_evictions_total",
                           paged.get("evictions", 0),
                           help="prefix-cache blocks reclaimed under "
                                "pool pressure")
        res = rec.get("resilience") or {}
        state = res.get("breaker_state")
        if state is not None:
            self.set_gauge(
                "serving_breaker_state",
                {"closed": 0, "half_open": 1, "open": 2}.get(state, -1),
                help="circuit breaker: 0 closed, 1 half-open, 2 open")
        if res.get("last_reload_step") is not None:
            self.set_gauge("serving_last_reload_step",
                           res["last_reload_step"],
                           help="checkpoint step of the last hot reload")
            self.set_gauge("serving_last_reload_failed",
                           1 if res.get("last_reload_failed") else 0,
                           help="1 when the last hot reload rolled back")

    def fold_fleet(self, metrics_or_record) -> None:
        """Fold a ``serving.fleet.FleetMetrics`` (or its
        ``to_record()`` dict / a stored ``{"type": "fleet"}`` record)
        into ``fleet_*`` metrics — the cluster-tier dashboard: routing
        mix + affinity hit rate, retry/shed/death pressure, deploy and
        autoscale events, and a per-replica gauge set labeled by
        replica name (occupancy / queue depth / readiness)."""
        rec = metrics_or_record
        if hasattr(rec, "to_record"):
            rec = rec.to_record()
        for name, v in rec.get("counters", {}).items():
            self.set_gauge(f"fleet_{name}_total", v,
                           help="fleet lifetime counter")
        agg = rec.get("fleet") or {}
        self.set_gauge("fleet_replicas", agg.get("n_replicas", 0),
                       help="replicas known to the router")
        self.set_gauge("fleet_replicas_ready", agg.get("n_ready", 0),
                       help="replicas ready at the last scrape")
        self.set_gauge("fleet_affinity_hit_rate",
                       agg.get("affinity_hit_rate", 0.0),
                       help="affinity-eligible requests placed on "
                            "their rendezvous home replica")
        self.set_gauge("fleet_retries_per_request",
                       agg.get("retries_per_request", 0.0),
                       help="mean retries per routed request")
        dur = rec.get("durability")
        if dur:
            for name, h in (
                    ("resumes", "mid-stream failovers resumed from "
                                "the emitted prefix"),
                    ("tokens_salvaged", "already-decoded tokens carried "
                                        "across resumes instead of "
                                        "regenerated"),
                    ("dedup_drops", "duplicate token deliveries the "
                                    "exactly-once cursor absorbed"),
                    ("journal_records", "write-ahead journal records "
                                        "appended"),
                    ("journal_truncated_bytes", "torn-tail bytes "
                                                "dropped by recovery "
                                                "scans"),
                    ("recovered_requests", "incomplete journal entries "
                                           "replayed by recover()")):
                self.set_gauge(f"fleet_durability_{name}_total",
                               dur.get(name, 0), help=h)
            fs = dur.get("journal_fsync_ms") or {}
            self.set_gauge("fleet_durability_journal_fsync_ms_p99",
                           fs.get("p99", 0.0),
                           help="p99 journal fsync latency")
        slo = rec.get("slo")
        if slo:
            self.set_gauge("fleet_slo_window", slo.get("window", 0),
                           help="request outcomes in the rolling SLO "
                                "window")
            for outcome, n in (slo.get("outcomes") or {}).items():
                self.set_gauge("fleet_slo_requests_total", n,
                               help="request outcomes recorded by the "
                                    "SLO tracker",
                               outcome=outcome)
            for field, obj in (slo.get("objectives") or {}).items():
                labels = {"objective": field}
                self.set_gauge("fleet_slo_target_ms",
                               obj.get("target_ms", 0.0),
                               help="the objective's latency target",
                               **labels)
                self.set_gauge("fleet_slo_attainment",
                               obj.get("attainment", 1.0),
                               help="fraction of windowed requests "
                                    "that met the objective", **labels)
                self.set_gauge("fleet_slo_burn_rate",
                               obj.get("burn_rate", 0.0),
                               help="window miss fraction over the "
                                    "error budget (1.0 = burning "
                                    "exactly as provisioned)", **labels)
                self.set_gauge("fleet_slo_p50_ms",
                               obj.get("p50_ms", 0.0),
                               help="windowed p50 of the objective's "
                                    "measured value", **labels)
                self.set_gauge("fleet_slo_p99_ms",
                               obj.get("p99_ms", 0.0),
                               help="windowed p99 of the objective's "
                                    "measured value", **labels)
        for name, rep in (rec.get("replicas") or {}).items():
            labels = {"replica": name}
            self.set_gauge("fleet_replica_ready",
                           1 if rep.get("ready") else 0,
                           help="1 when the replica scraped ready",
                           **labels)
            self.set_gauge("fleet_replica_queue_depth",
                           rep.get("queue_depth", 0),
                           help="queued requests at the last scrape",
                           **labels)
            self.set_gauge("fleet_replica_occupancy",
                           rep.get("occupancy", 0.0),
                           help="max(slot, pool) occupancy at the "
                                "last scrape", **labels)
            self.set_gauge("fleet_replica_p99_decode_step_ms",
                           rep.get("p99_decode_step_ms", 0.0),
                           help="replica's rolling p99 decode step",
                           **labels)
            self.set_gauge("fleet_replica_routed_total",
                           rep.get("routed", 0),
                           help="requests the router placed here",
                           **labels)

    def fold_dispatch(self, stats: Optional[dict],
                      epoch: Optional[int] = None) -> None:
        """Fold a fit tier's dispatch accounting (``sd.last_fit_stats``
        or a stored ``{"type": "dispatch"}`` record)."""
        if not stats:
            return
        labels = {"tier": stats.get("tier", "unknown")}
        for key in ("steps_per_epoch", "dispatches_per_epoch",
                    "window_compiles", "fused_steps", "accum_steps"):
            if key in stats:
                self.set_gauge(f"fit_{key}", stats[key],
                               help="fit dispatch accounting", **labels)
        if epoch is not None:
            self.set_gauge("fit_epoch", epoch, help="last observed epoch")

    def fold_checkpoint(self, record: dict) -> None:
        """Fold one ``{"type": "checkpoint"}`` commit record."""
        self.inc("checkpoint_commits_total",
                 help="committed checkpoints")
        self.inc("checkpoint_bytes_total", record.get("bytes", 0),
                 help="bytes committed to checkpoints")
        for key, metric in (("serialize_seconds", "serialize"),
                            ("commit_seconds", "commit"),
                            ("queue_seconds", "queue")):
            if key in record:
                self.observe("checkpoint_stage_seconds", record[key],
                             help="checkpoint stage wall time",
                             stage=metric)
        self.set_gauge("checkpoint_last_step", record.get("step", 0))

    def fold_faults(self, events: Iterable[dict]) -> None:
        """Fold fault-rail events (``{"type": "faults"}`` records or
        ``FaultTolerantFit.events``)."""
        for ev in events:
            self.inc("faults_events_total",
                     help="fault-rail decisions by event",
                     event=ev.get("event", "unknown"))
            if ev.get("event") == "rollback":
                self.observe("faults_rollback_seconds",
                             ev.get("overhead_s", 0.0),
                             help="rollback wall time")

    def fold_reshard(self, record: dict) -> None:
        """Fold one ``{"type": "reshard"}`` record (checkpoint/
        reshard.py / ParallelTrainer.restore_latest) into ``reshard_*``
        metrics — how often elastic restores cross topology changes,
        how much global state they reassemble, and how long the
        re-slice costs."""
        self.inc("reshard_events_total",
                 help="elastic resharded restores (topology changes "
                      "survived)")
        self.inc("reshard_arrays_resliced_total", record.get("arrays", 0),
                 help="arrays re-sliced onto a new mesh by resharded "
                      "restores")
        self.inc("reshard_bytes_gathered_total", record.get("bytes", 0),
                 help="global-state bytes reassembled by resharded "
                      "restores")
        self.observe("reshard_seconds", record.get("seconds", 0.0),
                     help="resharded-restore wall time")
        if record.get("step") is not None:
            self.set_gauge("reshard_last_step", record["step"],
                           help="step of the last resharded restore")
        if record.get("from_shards") is not None:
            self.set_gauge("reshard_last_from_shards",
                           record["from_shards"],
                           help="shard count of the last resharded "
                                "checkpoint")

    def fold_compile(self, stats_or_record) -> None:
        """Fold XLA compile accounting (``compilecache.COMPILE_STATS``
        or a stored ``{"type": "compile"}`` record) into ``compile_*``
        gauges — the cache-hit vs miss split that tells a dashboard
        whether a restart was warm."""
        rec = stats_or_record
        if hasattr(rec, "to_record"):
            rec = rec.to_record()
        for key in ("backend_compiles", "cache_hits", "cache_misses",
                    "miss_compiles"):
            if key in rec:
                self.set_gauge(f"compile_{key}_total", rec[key],
                               help="XLA compiles by persistent-cache "
                                    "outcome (compilecache/)")
        for key in ("backend_compile_seconds", "trace_seconds",
                    "lower_seconds", "saved_seconds"):
            if key in rec:
                self.set_gauge(f"compile_{key}", rec[key],
                               help="cumulative compile-phase wall time")

    def fold_tensorstats(self, record: dict) -> None:
        """Fold one ``{"type": "tensorstats"}`` record (monitor/
        tensorstats.py) into per-layer ``layer_*`` gauges — grad/update/
        param L2 norms, nonfinite counts, the update:param ratio — plus
        a ``layer_update_ratio_dist`` histogram over all layers/samples
        (the dead↔exploding spectrum a dashboard alerts on). Histogram
        bin lists stay record-only: L layers x 3 families x B bins as
        label sets would swamp the namespace."""
        for layer, ent in record.get("layers", {}).items():
            for k, v in ent.items():
                if k.endswith("_hist") or v is None:
                    # None = poisoned stat (build_record sanitizes
                    # non-finite floats); the *_nonfinite counts carry
                    # the signal
                    continue
                self.set_gauge(f"layer_{k}", v,
                               help="per-layer tensor statistics "
                                    "(tensorstats)", layer=layer)
            ratio = ent.get("update_ratio")
            if ratio is not None:
                self.observe("layer_update_ratio_dist", ratio,
                             help="update:param ratio distribution over "
                                  "layers and samples",
                             buckets=_RATIO_BUCKETS)
        if record.get("iter") is not None:
            self.set_gauge("layer_stats_last_iteration", record["iter"],
                           help="iteration of the last tensorstats "
                                "sample")

    def fold_memory(self, record: dict) -> None:
        """Fold one ``{"type": "memory"}`` record (monitor/memstats.py)
        into ``hbm_*`` gauges — total and per-device bytes in use /
        peak / limit / headroom, plus the AllocationsTracker's tagged
        transfer totals (gauges, not counters: the record carries
        cumulative values)."""
        for key, metric in (("bytes_in_use", "hbm_bytes_in_use"),
                            ("peak_bytes", "hbm_peak_bytes"),
                            ("bytes_limit", "hbm_bytes_limit"),
                            ("headroom", "hbm_headroom")):
            if record.get(key) is not None:
                self.set_gauge(metric, record[key],
                               help="device HBM accounting "
                                    "(monitor/memstats.py)")
        for dev in record.get("devices", ()):
            name = dev.get("device", "?")
            for key, metric in (("bytes_in_use", "hbm_bytes_in_use"),
                                ("peak_bytes", "hbm_peak_bytes"),
                                ("bytes_limit", "hbm_bytes_limit")):
                if dev.get(key):
                    self.set_gauge(metric, dev[key],
                                   help="device HBM accounting "
                                        "(monitor/memstats.py)",
                                   device=name)
        for tag, nbytes in (record.get("tracked") or {}).items():
            self.set_gauge("memory_tracked_bytes", nbytes,
                           help="AllocationsTracker tagged transfer "
                                "totals", tag=tag)
        if record.get("live_skipped"):
            self.set_gauge("memory_live_skipped_arrays",
                           record["live_skipped"],
                           help="live arrays the fallback census could "
                                "not size (deleted/donated)")

    def fold_memory_plan(self, record: dict) -> None:
        """Fold one ``{"type": "memory_plan"}`` record into per-program
        ``plan_*`` gauges — the compiled executable's predicted
        footprint (temp/argument/output/generated-code bytes) and its
        flops (the MFU-estimate numerator)."""
        program = record.get("program", "?")
        for key in ("temp_bytes", "argument_bytes", "output_bytes",
                    "generated_code_bytes", "total_bytes"):
            if record.get(key) is not None:
                self.set_gauge(f"plan_{key}", record[key],
                               help="compiled-program memory plan "
                                    "(compiled.memory_analysis)",
                               program=program)
        for key in ("flops", "flops_per_step", "bytes_accessed"):
            if record.get(key) is not None:
                self.set_gauge(f"plan_{key}", record[key],
                               help="compiled-program cost plan "
                                    "(compiled.cost_analysis)",
                               program=program)

    def fold_analysis(self, record: dict) -> None:
        """Fold one ``{"type": "analysis"}`` record (analyze/,
        docs/static_analysis.md) into ``analysis_*`` gauges — the
        finding counts by severity a dashboard alerts on (a nonzero
        error gauge means a fit is running against a graph the
        analyzer would have failed in strict mode), plus the one-time
        analysis cost."""
        for sev, n in (record.get("counts") or {}).items():
            self.set_gauge("analysis_findings", n,
                           help="static-analysis findings by severity "
                                "(analyze/)", severity=sev)
        if record.get("rules_run") is not None:
            self.set_gauge("analysis_rules_run", record["rules_run"],
                           help="rules the last static analysis ran")
        if record.get("seconds") is not None:
            self.set_gauge("analysis_seconds", record["seconds"],
                           help="wall seconds of the last static "
                                "analysis (runs once per graph "
                                "version, pre-compile)")

    def fold_datapipe(self, record: dict) -> None:
        """Fold one ``{"type": "datapipe"}`` record (the streaming
        input pipeline's per-flush telemetry, datapipe/ +
        monitor/steptime.MonitorListener) into ``datapipe_*`` metrics:
        delta counters for records/batches delivered, IO retries,
        quarantines and supervision decisions, plus throughput /
        data-wait / per-worker-utilization gauges."""
        for key in ("records", "batches", "read_retries", "shard_reads",
                    "bytes_read", "rows_quarantined", "records_withheld",
                    "worker_restarts", "requeues", "slow_reads"):
            v = record.get(key)
            if v:
                self.inc(f"datapipe_{key}_total", v,
                         help="streaming data-plane counter (datapipe/)")
        for key, metric in (("records_per_sec", "datapipe_records_per_sec"),
                            ("data_wait_frac",
                             "datapipe_data_wait_fraction"),
                            ("quarantined_shards",
                             "datapipe_quarantined_shards"),
                            ("passes_started",
                             "datapipe_passes_started"),
                            ("workers", "datapipe_workers")):
            if record.get(key) is not None:
                self.set_gauge(metric, record[key],
                               help="streaming data-plane gauge "
                                    "(datapipe/)")
        for worker, util in (record.get("worker_utilization")
                             or {}).items():
            self.set_gauge("datapipe_worker_utilization", util,
                           help="prefetch-worker busy fraction since "
                                "the previous flush", worker=str(worker))

    def fold_integrity(self, record: dict) -> None:
        """Fold one ``{"type": "integrity"}`` record (the integrity
        rail: checkpoint scrubber cycles/quarantines and stall-watchdog
        forensics — integrity/, checkpoint/scrub.py) into
        ``integrity_*`` metrics. Stall FAULT events already count under
        ``faults_events_total{event="stall"}``; this adds the scrub
        cadence and the rot/quarantine tallies a fleet dashboard
        alerts on."""
        ev = record.get("event")
        if ev == "scrub":
            self.inc("integrity_scrub_cycles_total",
                     help="checkpoint scrub cycles completed")
            self.inc("integrity_scrubbed_dirs_total",
                     record.get("scanned", 0),
                     help="step dirs re-hashed by the scrubber")
            self.inc("integrity_scrub_bytes_total",
                     record.get("bytes", 0),
                     help="bytes re-hashed by the scrubber")
            self.inc("integrity_rotten_total", record.get("rotten", 0),
                     help="step dirs that failed scrub verification")
            self.observe("integrity_scrub_seconds",
                         record.get("seconds", 0.0),
                         help="scrub cycle wall time")
        elif ev in ("checkpoint_quarantined", "checkpoint_rotten"):
            self.inc("integrity_quarantined_total",
                     1 if ev == "checkpoint_quarantined" else 0,
                     help="rotten checkpoints moved aside "
                          "(step_N.rotten)")
            if record.get("step") is not None:
                self.set_gauge("integrity_last_rotten_step",
                               record["step"],
                               help="newest step found rotten")
        elif ev == "stall_forensics":
            self.inc("integrity_stalls_total",
                     help="stall-watchdog expiries (forensics dumped)")
            if record.get("waited_s") is not None:
                self.observe("integrity_stall_waited_seconds",
                             record["waited_s"],
                             help="how long stalled boundaries blocked")

    def fold_steptime(self, record: dict) -> None:
        """Fold one ``{"type": "steptime"}`` breakdown record
        (monitor/steptime.py)."""
        steps = record.get("steps", 0)
        if not steps:
            return
        self.inc("steptime_steps_total", steps, help="attributed steps")
        for stage in ("data_wait_s", "dispatch_s", "flush_s", "other_s"):
            if stage in record:
                self.inc(f"steptime_{stage[:-2]}_seconds_total",
                         record[stage],
                         help="per-stage wall time attributed to steps")
        for stat in ("p50", "p95", "max"):
            key = f"step_ms_{stat}"
            if key in record:
                self.set_gauge("steptime_step_ms", record[key],
                               help="rolling step-time percentiles",
                               stat=stat)

    def fold_storage(self, storage) -> None:
        """Fold everything recognizable a StatsStorage holds (serving /
        dispatch / checkpoint / faults / steptime records). Incremental
        per storage: repeated calls fold only records appended since
        the last call, so re-folding on every scrape is safe. (The
        record-level adapters above are NOT idempotent for
        counter-typed metrics — fold each record/event stream once.)"""
        with self._fold_lock:
            # held across the fold, not just the mark update: gauges are
            # last-write-wins, so two racing folders must apply their
            # slices in order (the per-metric ops take self._lock — a
            # different lock — so no deadlock)
            start = self._fold_marks.get(storage, 0)
            records = list(storage.records)
            self._fold_marks[storage] = len(records)
            new = records[start:]
            for rec in new:
                self._fold_one(rec)

    def _fold_one(self, rec: dict) -> None:
        t = rec.get("type")
        if t == "serving":
            self.fold_serving(rec)
        elif t == "fleet":
            self.fold_fleet(rec)
        elif t == "dispatch":
            self.fold_dispatch(rec, epoch=rec.get("epoch"))
        elif t == "checkpoint":
            self.fold_checkpoint(rec)
        elif t == "faults":
            self.fold_faults([rec])
        elif t == "steptime":
            self.fold_steptime(rec)
        elif t == "datapipe":
            self.fold_datapipe(rec)
        elif t == "tensorstats":
            self.fold_tensorstats(rec)
        elif t == "compile":
            self.fold_compile(rec)
        elif t == "reshard":
            self.fold_reshard(rec)
        elif t == "memory":
            self.fold_memory(rec)
        elif t == "memory_plan":
            self.fold_memory_plan(rec)
        elif t == "analysis":
            self.fold_analysis(rec)
        elif t == "integrity":
            self.fold_integrity(rec)


__all__ = ["MetricsRegistry"]
