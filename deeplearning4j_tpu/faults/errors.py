"""Structured fault taxonomy for the training stack.

Every error the detect → decide → recover loop routes on carries machine-
readable provenance (absolute step, epoch, batch index, cause tag) so the
recovery driver — and a postmortem reading ``{"type": "faults"}`` stats
records — can answer *where* and *why* without parsing message strings.

Reference parity: the reference signals failure with bare
``ND4JIllegalStateException`` / ``RuntimeException`` from deep inside the
executor (DefaultOpExecutioner NAN_PANIC, FailureTestingListener); the
caller learns "something broke" but not at which iteration of which
epoch. Here the fault rail is typed end-to-end.
"""
from __future__ import annotations

from typing import Any, Dict, Optional


class FaultError(RuntimeError):
    """Base for all structured training-stack faults.

    ``provenance()`` returns the machine-readable view used for
    ``'faults'`` stats records and recovery decisions.
    """

    cause_tag: str = "fault"

    def __init__(self, message: str, *, step: Optional[int] = None,
                 epoch: Optional[int] = None,
                 batch_index: Optional[int] = None,
                 cause: Optional[str] = None,
                 value: Optional[float] = None):
        super().__init__(message)
        self.step = step
        self.epoch = epoch
        self.batch_index = batch_index
        self.cause = cause or self.cause_tag
        self.value = value

    def provenance(self) -> Dict[str, Any]:
        return {"error": type(self).__name__, "cause": self.cause,
                "step": self.step, "epoch": self.epoch,
                "batch_index": self.batch_index, "value": self.value}


class TrainingDivergedError(FaultError, ArithmeticError):
    """Training left the healthy regime: non-finite loss or gradient
    (device sentinel, ``TrainingConfig.sentinel``), a host-side loss
    spike, or a plateau watcher firing. Also an ``ArithmeticError`` so
    callers already catching ``NumericsException``-style numerics
    failures see it."""

    cause_tag = "divergence"


class DataPipelineError(FaultError):
    """A data loader/iterator failed: a worker-thread exception
    (``AsyncDataSetIterator``'s poisoned sentinel), a retry budget
    exhausted (``faults.RetryingIterator``), or a corrupt batch that
    could not be quarantined. ``batch_index`` is the index of the batch
    (within the current pass) that failed to materialize."""

    cause_tag = "data_pipeline"


class ShardCorruptError(DataPipelineError):
    """A data shard failed integrity verification: the bytes on disk do
    not match the ``ShardManifest`` (sha256/size/record-count mismatch,
    truncation, an unreadable npz) or the manifest itself is torn.
    RETRYABLE (⊂ :class:`DataPipelineError`): flaky NFS can serve bad
    bytes once and good bytes on the re-read, so the sharded reader
    retries within its budget before the shard is quarantined.
    ``shard`` names the shard file and ``offset`` the first affected
    record offset within it (None = whole-shard damage)."""

    cause_tag = "shard_corrupt"

    def __init__(self, message: str, *, shard: Optional[str] = None,
                 offset: Optional[int] = None, **kw):
        super().__init__(message, **kw)
        self.shard = shard
        self.offset = offset

    def provenance(self) -> Dict[str, Any]:
        out = super().provenance()
        out["shard"] = self.shard
        out["offset"] = self.offset
        return out


class TransientDeviceError(FaultError):
    """A device/runtime error believed transient (injected by the chaos
    harness; real runs map backend runtime errors onto the same retry
    path via ``retryable_errors()``)."""

    cause_tag = "device"


class FaultBudgetExhaustedError(FaultError):
    """The recovery driver's retry budget ran out. The model has been
    rolled back to the last committed checkpoint and a final checkpoint
    is committed — the run aborted *cleanly*; ``__cause__`` is the last
    underlying fault."""

    cause_tag = "budget_exhausted"


class TrainingStalledError(FaultError):
    """A blocking device boundary (window dispatch, flush device_get,
    serving exec, checkpoint capture) exceeded its adaptive stall
    deadline (integrity/watchdog.py) — the non-raising failure class:
    a wedged collective, a hung host↔device transfer, a dead tunnel.
    RETRYABLE: a stall that eventually un-wedges (transient network
    partition, a straggling peer that recovers) heals through the
    normal rollback path; a permanent wedge never returns from the
    blocking call, but the watchdog has already published the
    ``{"type": "faults", "event": "stall"}`` record, flipped
    ``/healthz`` to 503, and dumped forensics for the supervisor that
    will eventually kill the process.

    ``forensics`` carries all-thread stacks, an HBM snapshot and the
    active compiled-program memory plan captured AT EXPIRY (while the
    boundary was still wedged), not at raise time."""

    cause_tag = "stall"

    def __init__(self, message: str, *, boundary: Optional[str] = None,
                 waited_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 forensics: Optional[Dict[str, Any]] = None, **kw):
        super().__init__(message, **kw)
        self.boundary = boundary
        self.waited_s = waited_s
        self.deadline_s = deadline_s
        self.forensics = dict(forensics or {})

    def provenance(self) -> Dict[str, Any]:
        out = super().provenance()
        out["boundary"] = self.boundary
        out["waited_s"] = self.waited_s
        out["deadline_s"] = self.deadline_s
        return out


class SilentCorruptionError(FaultError):
    """Bitwise state divergence that raised nothing: a replay probe's
    fingerprint mismatch (SDC/nondeterminism inside a dispatch), a
    device-vs-host fingerprint mismatch at checkpoint capture (a
    corrupted device→host copy), cross-replica fingerprint disagreement
    under DP sharding, or a checkpoint whose fingerprint stamp no
    longer matches its payload at restore (integrity/fingerprint.py).
    RETRYABLE — but ``faults.FaultTolerantFit`` answers it by rolling
    back to the last *fingerprint-verified* checkpoint rather than
    merely the newest (docs/fault_tolerance.md "Non-raising
    failures")."""

    cause_tag = "silent_corruption"

    def __init__(self, message: str, *, check: Optional[str] = None,
                 expected: Optional[int] = None,
                 actual: Optional[int] = None, **kw):
        super().__init__(message, **kw)
        self.check = check
        self.expected = expected
        self.actual = actual

    def provenance(self) -> Dict[str, Any]:
        out = super().provenance()
        out["check"] = self.check
        out["expected"] = self.expected
        out["actual"] = self.actual
        return out


def retryable_errors() -> tuple:
    """Exception classes the recovery driver treats as recoverable:
    the structured fault taxonomy, numerics panics from the fit tiers,
    checkpoint-write failures (``CheckpointError`` — which covers
    ``TopologyChangedError``/``ShardCountMismatchError``, the elastic
    topology-change signals routed through resharded restore), and the
    backend's runtime errors (preemption / transient device loss
    surface there)."""
    types = [TrainingDivergedError, DataPipelineError, TransientDeviceError,
             TrainingStalledError, SilentCorruptionError]
    from deeplearning4j_tpu.autodiff.samediff import NumericsException
    types.append(NumericsException)
    from deeplearning4j_tpu.checkpoint.manager import CheckpointError
    types.append(CheckpointError)
    try:
        from jax.errors import JaxRuntimeError
        types.append(JaxRuntimeError)
    except ImportError:      # pragma: no cover - older jax
        pass
    return tuple(types)
