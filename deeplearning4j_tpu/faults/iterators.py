"""RetryingIterator — the data pipeline's recovery rail.

Production loaders fail in three ways and each gets its own treatment:

- **transient loader exceptions** (flaky NFS, a hiccuping decoder): the
  wrapped iterator is reset and fast-forwarded past the batches already
  delivered, then iteration continues — the consumer sees an unbroken
  batch stream. Bounded by a per-pass retry budget and an exponential
  backoff between attempts.
- **corrupt batches** (NaN/Inf features from a torn shard): quarantined
  — the batch index is recorded, the batch is skipped on this and every
  later pass, and iteration continues. A poisoned batch must not reach
  the compiled train step where it becomes a divergence.
- **persistent failure**: when the consecutive-failure budget is spent,
  a structured :class:`DataPipelineError` carrying the failing batch
  index escapes to the caller (where ``FaultTolerantFit`` decides).

Recovery positioning takes one of two paths:

- **seek (O(1))** — a wrapped source exposing ``seek_batches(skip)``
  (``datapipe.StreamingDataPipeline``: its pass order is a pure
  function of ``(seed, pass_index, host)``, so any position is
  recomputable) is repositioned directly: the SAME pass's permutation
  continues at batch ``skip`` without a single record re-read. Exact
  recovery is guaranteed by construction.
- **reset + fast-forward (O(n) fallback)** — a plain iterator is
  ``reset()`` and replayed past the batches already delivered. Exact
  recovery (no sample trained twice or dropped, index-keyed
  quarantine naming the right batch) then requires a source that is
  restartable and deterministic per pass. Shuffling/sampling sources
  (``ArrayDataSetIterator(shuffle=True)``,
  ``SamplingDataSetIterator``) produce a FRESH order each pass: a
  retry resumes at position ``index`` of a different permutation —
  some samples of the recovered pass repeat and others drop. That is
  usually acceptable for SGD (the pass is stochastic anyway) but not
  for exact-order pipelines; wrap a deterministic view, use the
  seekable pipeline, or disable with
  ``RetryPolicy(data_max_retries=0)``.

Both paths are pinned by regression tests (tests/test_datapipe.py).
Reference parity: the reference's executor retry loops
(EarlyStoppingTrainer's fit loop catches per-minibatch exceptions);
here the budget, backoff and quarantine are explicit and observable
via ``events``.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.dataset.iterators import DataSetIterator
from deeplearning4j_tpu.faults.errors import DataPipelineError
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer


def _batch_arrays(batch) -> list:
    if isinstance(batch, dict):
        return list(batch.values())
    if hasattr(batch, "features") and hasattr(batch, "labels"):
        batch = (batch.features, batch.labels)
    if isinstance(batch, (tuple, list)):
        out = []
        for part in batch:
            out.extend(part if isinstance(part, (tuple, list)) else [part])
        return out
    return [batch]


def batch_is_corrupt(batch) -> bool:
    """True when any HOST-RESIDENT floating-point array in the batch
    holds NaN/Inf. Device-resident arrays (DeviceCachedIterator slices,
    pre-sharded batches) are deliberately NOT pulled back to host — a
    D2H copy per step would defeat the transfer/compute overlap the
    fused-window pipeline exists for, and the armed device sentinel
    already catches NaN that reaches the compiled step. The scan is one
    memory-bound pass over loader output — the cost of validating
    untrusted bytes where they enter."""
    for a in _batch_arrays(batch):
        if not isinstance(a, np.ndarray):
            continue
        if np.issubdtype(a.dtype, np.floating) and \
                not np.isfinite(a).all():
            return True
    return False


class RetryingIterator(DataSetIterator):
    """Wrap a DataSetIterator with retry + quarantine semantics.

    ``max_retries``: total transient-failure retries per pass;
    ``max_consecutive_failures``: failures at the SAME batch index
    before giving up on it (a batch that fails every attempt is not
    transient); ``quarantine_corrupt``: skip (and remember) NaN/Inf
    batches instead of yielding them; ``transient``: exception classes
    eligible for retry (anything else propagates immediately);
    ``on_event``: callback receiving one dict per retry/quarantine
    (also appended to ``self.events``).
    """

    def __init__(self, wrapped: DataSetIterator, max_retries: int = 3,
                 max_consecutive_failures: int = 2,
                 quarantine_corrupt: bool = True,
                 backoff_base: float = 0.0, backoff_max: float = 5.0,
                 transient: Tuple[type, ...] = (Exception,),
                 on_event: Optional[Callable[[dict], None]] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._wrapped = wrapped
        self.max_retries = int(max_retries)
        self.max_consecutive_failures = int(max_consecutive_failures)
        self.quarantine_corrupt = bool(quarantine_corrupt)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self._transient = tuple(transient)
        self._on_event = on_event
        self._sleep = sleep
        self.quarantined: set = set()      # batch indices skipped forever
        self.events: List[dict] = []

    def reset(self):
        if hasattr(self._wrapped, "reset"):
            self._wrapped.reset()

    def batch_size(self):
        if hasattr(self._wrapped, "batch_size"):
            return self._wrapped.batch_size()
        return None

    # -- event plumbing -------------------------------------------------
    def _event(self, kind: str, index: int, error=None) -> None:
        ev = {"type": "faults", "event": kind, "batch_index": int(index),
              "t": time.time()}
        if error is not None:
            ev["error"] = repr(error)
        self.events.append(ev)
        if self._on_event is not None:
            self._on_event(ev)

    # -- iteration ------------------------------------------------------
    def _restarted(self, skip: int):
        """A fresh iterator positioned at batch index ``skip`` of the
        current pass. Seekable sources (``seek_batches``) are
        repositioned in O(1) — the same pass's order continues with no
        records re-read; plain iterators reset and fast-forward (O(n)
        replay). A source that shrank below ``skip`` between attempts
        is a pipeline fault, not a clean end-of-pass — silent
        truncation is exactly what this rail exists to prevent (the
        seek path raises it typed from ``seek_batches``)."""
        seek = getattr(self._wrapped, "seek_batches", None)
        if callable(seek):
            with _tracer.span("data.loader_seek", cat="data", skip=skip):
                return seek(skip)
        with _tracer.span("data.loader_retry", cat="data", skip=skip):
            self.reset()
            it = iter(self._wrapped)
            for i in range(skip):
                try:
                    next(it)
                except StopIteration:
                    raise DataPipelineError(
                        f"data source shrank during retry: expected at "
                        f"least {skip} batches, ended at {i}",
                        batch_index=i, cause="source_shrank") from None
            return it

    def __iter__(self):
        self.reset()
        it = iter(self._wrapped)
        index = 0                       # index of the batch being fetched
        retries_left = self.max_retries
        consecutive = 0
        while True:
            try:
                batch = next(it)
            except StopIteration:
                return
            except self._transient as e:
                consecutive += 1
                retries_left -= 1
                if retries_left < 0 or \
                        consecutive > self.max_consecutive_failures:
                    self._event("loader_failed", index, e)
                    raise DataPipelineError(
                        f"data loader failed at batch {index} after "
                        f"{self.max_retries - max(retries_left, 0) } "
                        f"retries ({consecutive} consecutive): {e!r}",
                        batch_index=index, cause="loader_exhausted") from e
                self._event("loader_retry", index, e)
                if self.backoff_base > 0:
                    self._sleep(min(self.backoff_max, self.backoff_base *
                                    (2 ** (consecutive - 1))))
                # keep attempting the restart until it succeeds or the
                # budget is spent — NEVER fall back to the old iterator:
                # a generator that raised is closed, and next() on it
                # returns StopIteration, which would silently END the
                # pass short (the truncation this rail exists to stop)
                while True:
                    try:
                        it = self._restarted(index)
                        break
                    except DataPipelineError:
                        raise      # source shrank: not a retryable fault
                    except self._transient as e2:
                        consecutive += 1
                        retries_left -= 1
                        self._event("loader_retry", index, e2)
                        if retries_left < 0 or \
                                consecutive > self.max_consecutive_failures:
                            raise DataPipelineError(
                                f"data loader restart failed at batch "
                                f"{index}: {e2!r}", batch_index=index,
                                cause="loader_exhausted") from e2
                        if self.backoff_base > 0:
                            self._sleep(min(
                                self.backoff_max, self.backoff_base *
                                (2 ** (consecutive - 1))))
                continue
            consecutive = 0
            if index in self.quarantined:
                self._event("quarantine_skip", index)
                index += 1
                continue
            if self.quarantine_corrupt and batch_is_corrupt(batch):
                self.quarantined.add(index)
                self._event("quarantine", index)
                index += 1
                continue
            index += 1
            yield batch
