"""FaultTolerantFit — the decide/recover half of the fault rail.

Closes the loop the sentinels open: on a structured fault (divergence,
data-pipeline failure, transient device/runtime error, checkpoint-write
error) during ``fit``, the driver

1. waits out / clears the checkpoint writer, garbage-collects torn
   staging dirs, and **rolls the model back** to the newest committed
   ``CheckpointManager`` snapshot (params, updater state, iteration,
   epoch, RNG base seed — bit-exact resume, checkpoint/state.py);
2. optionally **rescales the learning rate** (``RetryPolicy.lr_rescale``)
   so a genuinely-too-hot run heals instead of re-diverging;
3. sleeps a **bounded exponential backoff** and retries the remaining
   epochs — the retry budget counts consecutive rollbacks *without
   checkpoint progress* (a run that diverges, heals, trains further and
   diverges again later is progressing, not crash-looping);
4. when the budget is spent, restores the last good state, re-commits it
   as a pinned final checkpoint, and raises
   :class:`FaultBudgetExhaustedError` — a clean abort whose ``__cause__``
   is the last underlying fault.

The data pipeline gets the same treatment one layer down: the input
iterator is wrapped in :class:`~deeplearning4j_tpu.faults.iterators.
RetryingIterator` (transient loader retries, corrupt-batch quarantine)
unless the caller already did.

Works with every fit front end — ``SameDiff``, ``MultiLayerNetwork``,
``ComputationGraph`` and ``parallel.ParallelTrainer`` (restores re-shard
onto the mesh via the trainer's own ``restore_latest``).

Every recovery decision is published as a ``{"type": "faults"}`` record
to the optional ``stats_storage`` (ui/stats.py) and kept in ``events``.
"""
from __future__ import annotations

import dataclasses
import sys
import time
from typing import Callable, List, Optional, Sequence

from deeplearning4j_tpu.checkpoint.listener import CheckpointListener
from deeplearning4j_tpu.checkpoint.manager import (CheckpointError,
                                                   CheckpointManager,
                                                   TopologyChangedError)
from deeplearning4j_tpu.faults.errors import (FaultBudgetExhaustedError,
                                              FaultError,
                                              SilentCorruptionError,
                                              retryable_errors)
from deeplearning4j_tpu.faults.iterators import RetryingIterator
from deeplearning4j_tpu.memory import MemoryExhaustedError
from deeplearning4j_tpu.monitor.trace import TRACER as _tracer


@dataclasses.dataclass
class RetryPolicy:
    """Bounds and knobs for the rollback-and-retry loop.

    ``max_retries``: consecutive rollbacks without checkpoint progress
    before aborting; ``backoff_base``/``backoff_max``: bounded
    exponential backoff seconds between attempts; ``lr_rescale``:
    multiply the updater's learning rate by this on every rollback
    (1.0 = off; rescaling retraces the train step);
    ``data_max_retries``: transient-loader retry budget per pass
    (0 = don't wrap the iterator); ``quarantine_corrupt``: skip NaN/Inf
    batches instead of training on them.
    """
    max_retries: int = 3
    backoff_base: float = 0.5
    backoff_max: float = 30.0
    lr_rescale: float = 1.0
    data_max_retries: int = 3
    quarantine_corrupt: bool = True


class FaultTolerantFit:
    """Supervised training: ``fit()`` that survives divergence, flaky
    loaders, torn checkpoints and transient device errors.

    ::

        mgr = CheckpointManager(ckpt_dir, keep_last_n=3)
        ftf = FaultTolerantFit(net, mgr, policy=RetryPolicy(max_retries=2),
                               checkpoint_every_n_iterations=50,
                               stats_storage=storage)
        history = ftf.fit(train_iter, epochs=20)

    ``sentinel=True`` (default) arms the device-side divergence sentinel
    on the model's TrainingConfig — the rail that turns a NaN gradient
    inside a fused window into a structured, recoverable error instead
    of silently-poisoned parameters.
    """

    def __init__(self, model, manager: CheckpointManager,
                 policy: Optional[RetryPolicy] = None,
                 checkpoint_every_n_iterations: Optional[int] = None,
                 checkpoint_every_n_epochs: Optional[int] = None,
                 stats_storage=None, sentinel: bool = True,
                 sleep: Callable[[float], None] = time.sleep):
        self.model = model
        self.sd = getattr(model, "samediff", None) or \
            getattr(model, "sd", None) or model
        self.manager = manager
        self.policy = policy or RetryPolicy()
        self.stats_storage = stats_storage
        self._sleep = sleep
        if checkpoint_every_n_iterations is None and \
                checkpoint_every_n_epochs is None:
            checkpoint_every_n_epochs = 1
        self._ckpt_iters = checkpoint_every_n_iterations
        self._ckpt_epochs = checkpoint_every_n_epochs
        self.events: List[dict] = []
        self.recovery_seconds = 0.0
        self.rollbacks = 0
        if sentinel and self.sd.training_config is not None:
            if not getattr(self.sd.training_config, "sentinel", False):
                self.sd.training_config.sentinel = True
                self.sd._mutated()

    # ------------------------------------------------------------------
    def _publish(self, event: str, **fields) -> dict:
        rec = {"type": "faults", "event": event, "t": time.time(), **fields}
        self.events.append(rec)
        if self.stats_storage is not None:
            self.stats_storage.put(rec)
        return rec

    def _tc(self):
        tc = self.sd.training_config
        if tc is None:
            raise ValueError("model has no TrainingConfig; set it (or "
                             "init() the network) before FaultTolerantFit")
        return tc

    def _restore_latest(self, verified_only: bool = False):
        """Restore the newest committed checkpoint into the model via
        the most specific hook it offers (ParallelTrainer re-shards).
        ``verified_only`` routes through the manager's fingerprint-
        verified walk (integrity/) — the rollback target after a
        :class:`SilentCorruptionError` must be a checkpoint whose
        stamp still proves its bytes, not merely the newest. A model
        hook that accepts ``verified_only`` (ParallelTrainer) keeps
        its mesh re-commit even on the verified walk; one that
        predates the parameter falls back to the manager path."""
        hook = getattr(self.model, "restore_latest", None)
        if hook is not None and not isinstance(self.model,
                                               CheckpointManager):
            if not verified_only:
                return hook(self.manager)
            import inspect
            try:
                accepts = "verified_only" in \
                    inspect.signature(hook).parameters
            except (TypeError, ValueError):
                accepts = False
            if accepts:
                return hook(self.manager, verified_only=True)
        return self.manager.restore_latest(model=self.model,
                                           verified_only=verified_only)

    def _restore_datapipe(self, state) -> None:
        """Seek the streaming pipeline (datapipe/) back to the
        restored snapshot's position: the checkpoint's
        ``metadata["datapipe"]`` PipelineState (shard cursor, shuffle
        pass, quarantine sets) is re-armed on the live pipeline, so the
        retried fit resumes the interrupted pass MID-EPOCH by seeking —
        bit-exact vs uninterrupted — instead of replaying it (or worse,
        training a different permutation)."""
        if state is None:
            return
        meta = getattr(state, "metadata", None) or {}
        data = meta.get("datapipe")
        if not data:
            return
        dp = getattr(self, "_datapipe", None)
        if dp is None:
            # restored before fit() saw the iterator (resume_latest in
            # a relaunched job): apply when fit() registers the pipeline
            self._pending_datapipe_state = data
            return
        dp.restore_state(data)
        self._publish("datapipe_seek",
                      pass_index=data.get("pass_index"),
                      cursor=data.get("cursor"),
                      quarantined=len(data.get("quarantined_records",
                                               ())))

    def _maybe_precompile(self) -> None:
        """Re-run AOT precompilation from the remembered spec after a
        recovery that dropped or invalidated compiled programs (LR
        rescale retraces; a topology change reshards every input). With
        a persistent cache a previously-seen program is a cache hit;
        either way the compile lands HERE, observable (compile.* spans,
        the ``precompile`` event), not silently inside the first retry
        window."""
        spec = getattr(self.sd, "_precompile_spec", None)
        if spec is None:
            return
        try:
            info = self.sd.precompile(**spec)
        except Exception as e:
            # fall back to lazy compiles in the retry — but say so: a
            # silent fallback would put the compile back inside the
            # first retry window with zero observability, the exact
            # condition the precompile event exists to surface
            info = {"failed": f"{type(e).__name__}: {e}"}
        self._publish("precompile", **info)

    def _reshard_restore(self, cause: Optional[BaseException] = None,
                         precompile: bool = True):
        """Topology-change recovery: the committed shard set was
        written by a different process/mesh count than this runtime
        has. Reassemble the global state from ALL shards, re-slice it
        for the current mesh (checkpoint/reshard.py), publish the
        decision, and re-AOT if the graph was precompiled
        (``precompile=False`` when the caller is about to mutate the
        graph again — e.g. an LR rescale — and will re-AOT itself)."""
        from deeplearning4j_tpu.checkpoint.reshard import restore_resharded
        res = restore_resharded(self.manager, model=self.model,
                                stats_storage=self.stats_storage)
        if res is None:
            raise FaultBudgetExhaustedError(
                "no committed checkpoint to reshard from",
                cause="no_checkpoint") from cause
        step, state = res
        info = dict(state.metadata.get("reshard_info") or {})
        self._publish("reshard",
                      **({"error": type(cause).__name__} if cause else {}),
                      **info)
        if precompile:
            self._maybe_precompile()
        return res

    def resume_latest(self):
        """Restore the newest committed checkpoint into the model —
        the restart half of elastic training (call before ``fit`` in a
        relaunched job). A same-topology restore goes through the
        model's own hook; a :class:`TopologyChangedError` (the job came
        back with a different process count after a host loss/rescale)
        routes through the resharded restore and is published as a
        ``reshard`` event. Returns ``(step, state)`` or None when no
        committed checkpoint exists."""
        try:
            res = self._restore_latest()
        except SilentCorruptionError as e:
            # the newest checkpoint's fingerprint stamp no longer
            # matches its payload: publish, then restart from the
            # newest VERIFIED one instead
            self._publish("corrupt_checkpoint", **e.provenance())
            res = self._restore_latest(verified_only=True)
            if res is not None:
                self._restore_datapipe(res[1])
            return res
        except TopologyChangedError as e:
            self._publish("topology_changed", error=type(e).__name__,
                          step=e.step, manifest=e.manifest,
                          runtime=e.runtime)
            res = self._reshard_restore(cause=e)
            if res is not None:
                self._restore_datapipe(res[1])
            return res
        self._publish_trainer_reshard()
        if res is not None and isinstance(res, tuple) and len(res) == 2:
            self._restore_datapipe(res[1])
        return res

    def _publish_trainer_reshard(self, precompile: bool = True) -> None:
        """A ParallelTrainer restore that crossed a MESH change (same
        process count, different device mesh — e.g. resuming on a
        shrunken sub-mesh) records the reshard on the trainer; surface
        it on the fault rail too."""
        lr = getattr(self.model, "last_reshard", None)
        if lr:
            self._publish("reshard", **lr)
            if self.stats_storage is not None and \
                    getattr(self.model, "stats_storage", None) is None:
                self.stats_storage.put({"type": "reshard",
                                        "t": time.time(), **lr})
            if precompile:
                self._maybe_precompile()

    def _rollback(self, cause: BaseException):
        t0 = time.perf_counter()
        rb_span = _tracer.span("faults.rollback", cat="faults",
                               cause=type(cause).__name__)
        rb_span.__enter__()
        try:
            self.manager.wait_until_finished(timeout=60.0)
        except Exception:
            pass
        try:
            self.manager.check_error()
        except CheckpointError:
            pass               # a failed async write IS the fault here
        removed = self.manager.gc_uncommitted()
        # an LR rescale below mutates the graph (dropping every
        # compiled program) and re-AOTs itself — precompiling in the
        # reshard branch first would be compiled-then-discarded waste
        will_rescale = self.policy.lr_rescale != 1.0 and isinstance(
            getattr(self._tc().updater, "learning_rate", None),
            (int, float))
        # a SilentCorruptionError rolls back to the last fingerprint-
        # VERIFIED checkpoint, not merely the newest: the newest may
        # have captured the corrupted state, or its stamp may itself be
        # the mismatch (docs/fault_tolerance.md "Non-raising failures")
        verified_only = isinstance(cause, SilentCorruptionError)
        try:
            try:
                res = self._restore_latest(verified_only=verified_only)
                self._publish_trainer_reshard(
                    precompile=not will_rescale)
            except SilentCorruptionError as e:
                # the NEWEST checkpoint's stamp failed during a plain
                # rollback: publish the corruption and fall back to the
                # verified walk
                self._publish("corrupt_checkpoint", **e.provenance())
                verified_only = True
                res = self._restore_latest(verified_only=True)
            except TopologyChangedError as e:
                # the world changed shape between the snapshot and this
                # rollback (host loss, elastic rescale): reassemble from
                # ALL committed shards and re-slice for the current mesh
                self._publish("topology_changed", error=type(e).__name__,
                              step=e.step, manifest=e.manifest,
                              runtime=e.runtime)
                res = self._reshard_restore(cause=e,
                                            precompile=not will_rescale)
            if res is None:
                raise FaultBudgetExhaustedError(
                    "no committed checkpoint to roll back to",
                    cause="no_checkpoint") from cause
            step, _state = res
            self._restore_datapipe(_state)
            rb_span.set(restored_step=int(step))
        finally:
            rb_span.__exit__(*sys.exc_info())
        if self.policy.lr_rescale != 1.0:
            upd = self._tc().updater
            lr = getattr(upd, "learning_rate", None)
            if isinstance(lr, (int, float)):
                upd.learning_rate = lr * self.policy.lr_rescale
                self.sd._mutated()     # the LR is baked into the program
                # the mutation dropped every compiled program (including
                # AOT-precompiled ones). If the graph was precompiled,
                # re-AOT NOW — during recovery, where the compile is
                # observable (compile.* spans) and expected — instead of
                # paying it silently inside the first retry window. With
                # a persistent cache, a retry at a previously-seen LR is
                # a cache hit.
                self._maybe_precompile()
        dt = time.perf_counter() - t0
        self.recovery_seconds += dt
        self.rollbacks += 1
        self._publish(
            "rollback", restored_step=int(step),
            gc_removed=len(removed), overhead_s=round(dt, 6),
            lr_rescale=self.policy.lr_rescale,
            verified_only=verified_only,
            **(cause.provenance() if isinstance(cause, FaultError)
               else {"error": type(cause).__name__, "cause": "exception"}))
        return step

    # ------------------------------------------------------------------
    def fit(self, dataset_iterator, epochs: int = 1,
            listeners: Sequence = ()):
        """Train ``epochs`` epochs (counted from the model's current
        ``epoch_count``), surviving recoverable faults within the retry
        budget. Returns the History of the final (successful) attempt."""
        tc = self._tc()
        policy = self.policy
        if policy.data_max_retries > 0 and \
                not isinstance(dataset_iterator, RetryingIterator) and \
                not hasattr(dataset_iterator, "stacked_batches"):
            # device-cached sources (stacked_batches) stay unwrapped:
            # wrapping would hide the attribute the scanned/windowed
            # fast paths route on (re-staging every epoch from host),
            # and buys nothing — an in-memory device source has no
            # transient loader failures, and the corrupt scan skips
            # device-resident arrays anyway (the sentinel covers them)
            dataset_iterator = RetryingIterator(
                dataset_iterator, max_retries=policy.data_max_retries,
                quarantine_corrupt=policy.quarantine_corrupt,
                on_event=(self.stats_storage.put
                          if self.stats_storage is not None else None))
        # seekable streaming pipeline (datapipe/): registered BEFORE the
        # rollback-target save below so even the step-0 snapshot embeds
        # its PipelineState — a rollback all the way to the start then
        # replays PASS 0's permutation (a fresh pass index would train a
        # different order than the uninterrupted run)
        from deeplearning4j_tpu.datapipe.pipeline import find_pipeline
        self._datapipe = find_pipeline(dataset_iterator)
        # assigned UNCONDITIONALLY (including None): the rollback-target
        # save below runs before sd.fit() refreshes the attribute, and a
        # stale pipeline from a previous fit would embed bogus
        # PipelineState into this fit's step-0 snapshot
        self.sd._active_datapipe = self._datapipe
        if self._datapipe is not None:
            pending = getattr(self, "_pending_datapipe_state", None)
            if pending:
                # resume_latest() restored a snapshot before this fit
                # saw the iterator: seek now
                self._pending_datapipe_state = None
                self._datapipe.restore_state(pending)
                self._publish("datapipe_seek",
                              pass_index=pending.get("pass_index"),
                              cursor=pending.get("cursor"),
                              quarantined=len(pending.get(
                                  "quarantined_records", ())))
        ckpt_iters = self._ckpt_iters
        accum = max(1, int(getattr(tc, "accum_steps", 1) or 1))
        if ckpt_iters is not None and accum > 1 and ckpt_iters % accum:
            # the partial gradient accumulator is NOT part of the
            # checkpoint schema (autodiff/window.py): a rollback target
            # must sit on an accumulation-cycle boundary or the resumed
            # cycle restarts from zeros. Round the cadence up so every
            # snapshot is a boundary. Residual constraint the rounding
            # cannot fix (documented, docs/fault_tolerance.md): snapshots
            # actually land on WINDOW boundaries at-or-after the cadence,
            # and epoch-cadence snapshots land wherever the epoch ends —
            # with accum_steps > 1 also keep fused_steps and the
            # steps-per-epoch multiples of accum_steps, or accept that a
            # rollback into a mid-cycle snapshot averages only the
            # post-resume micro-grads of that one cycle.
            ckpt_iters = ((ckpt_iters + accum - 1) // accum) * accum
        ckpt = CheckpointListener(
            self.manager, every_n_iterations=ckpt_iters,
            every_n_epochs=self._ckpt_epochs)
        all_listeners = list(listeners) + [ckpt]
        # a rollback target must exist before the first step can fail
        if self.manager.latest_step() is None:
            step0 = int(getattr(tc, "iteration_count", 0))
            self.manager.save(step0, model=self.sd,
                              epoch=int(getattr(tc, "epoch_count", 0)),
                              blocking=True)
        target = int(getattr(tc, "epoch_count", 0)) + int(epochs)
        attempts = 0
        last_restore_step = -1
        history = None
        retryable = retryable_errors()
        while True:
            remaining = target - int(getattr(tc, "epoch_count", 0))
            if remaining <= 0:
                break
            try:
                history = self.model.fit(dataset_iterator,
                                         epochs=remaining,
                                         listeners=all_listeners)
                break          # done (or a listener chose to stop early)
            except MemoryExhaustedError as e:
                # OOM is non-retryable-WITH-DIAGNOSIS: a rollback
                # replays the same compiled program against the same
                # HBM — it cannot shrink the footprint, so burning the
                # retry budget would only delay the inevitable. Publish
                # the forensics (program, per-device usage, live-array
                # census, plan) as the {"type": "faults", "event":
                # "oom"} record — /healthz goes sticky-503 on it — and
                # abort cleanly (docs/fault_tolerance.md).
                forensics = e.forensics()
                self._publish(
                    "oom", **e.provenance(),
                    devices=[{k: d.get(k) for k in
                              ("device", "bytes_in_use", "peak_bytes",
                               "bytes_limit")}
                             for d in forensics.get("devices", [])],
                    live_arrays=(forensics.get("census") or {}
                                 ).get("arrays"),
                    live_bytes=(forensics.get("census") or {}
                                ).get("total_bytes"),
                    plan=forensics.get("plan"))
                raise
            except retryable as e:
                self._publish(
                    "fault",
                    **(e.provenance() if isinstance(e, FaultError)
                       else {"error": type(e).__name__,
                             "cause": "exception"}))
                step = self._rollback(e)
                if step > last_restore_step:
                    attempts = 1          # progress since the last loop
                else:
                    attempts += 1
                last_restore_step = step
                if attempts > policy.max_retries:
                    # budget spent: re-commit the known-good state as a
                    # pinned final checkpoint and abort cleanly
                    try:
                        self.manager.save(int(step), model=self.sd,
                                          epoch=int(getattr(
                                              tc, "epoch_count", 0)),
                                          blocking=True, pin=True)
                    except Exception:
                        pass   # the restored step is already on disk
                    self._publish("retry_exhausted", attempts=attempts,
                                  restored_step=int(step))
                    raise FaultBudgetExhaustedError(
                        f"retry budget exhausted after {attempts - 1} "
                        f"rollbacks to step {step}: {e!r}",
                        step=int(step), cause="budget_exhausted") from e
                # stateful listeners (watchers with EMAs/best-scores)
                # must judge the replayed timeline fresh — statistics
                # from the discarded attempt would fire spuriously
                for l in listeners:
                    reset = getattr(l, "reset", None)
                    if callable(reset):
                        reset()
                backoff = min(policy.backoff_max,
                              policy.backoff_base * (2 ** (attempts - 1)))
                self._publish("retry", attempt=attempts,
                              backoff_s=round(backoff, 6),
                              resume_step=int(step))
                if backoff > 0:
                    with _tracer.span("faults.backoff", cat="faults",
                                      attempt=attempts,
                                      backoff_s=round(backoff, 6)):
                        self._sleep(backoff)
        self.manager.wait_until_finished()
        if self.rollbacks:
            self._publish("recovered", rollbacks=self.rollbacks,
                          overhead_s=round(self.recovery_seconds, 6))
        return history

    # ------------------------------------------------------------------
    def report(self) -> dict:
        """Machine-readable recovery summary for the run so far."""
        return {"rollbacks": self.rollbacks,
                "recovery_seconds": round(self.recovery_seconds, 6),
                "events": list(self.events)}
