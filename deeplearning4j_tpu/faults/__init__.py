"""faults/ — the robustness layer: detect → decide → recover.

- ``errors``    : structured fault taxonomy (step/epoch/batch provenance)
- ``sentinels`` : device-side divergence sentinel semantics + host-side
  loss-spike / plateau watchers (the per-layer ``LayerHealthWatcher``
  lives in monitor/tensorstats.py — it rides the in-graph tensor
  statistics — and is re-exported here next to its siblings)
- ``recovery``  : FaultTolerantFit — rollback-and-retry training over
  the checkpoint/ manager, bounded backoff, clean abort
- ``iterators`` : RetryingIterator — loader retry + corrupt-batch
  quarantine for the data pipeline
- ``chaos``     : deterministic seed-driven fault injection (NaN grads,
  loader exceptions, torn checkpoint commits, SIGTERM mid-window,
  host loss / topology shrink for elastic-resume drills)

See docs/fault_tolerance.md and docs/elastic_training.md.
"""
from deeplearning4j_tpu.checkpoint.manager import (ShardCountMismatchError,
                                                   TopologyChangedError)
from deeplearning4j_tpu.faults.chaos import (ChaosMonkey, FileBarrier,
                                             HostKiller, HostLossInjector,
                                             TornShard)
from deeplearning4j_tpu.faults.errors import (DataPipelineError,
                                              FaultBudgetExhaustedError,
                                              FaultError,
                                              ShardCorruptError,
                                              SilentCorruptionError,
                                              TrainingDivergedError,
                                              TrainingStalledError,
                                              TransientDeviceError,
                                              retryable_errors)
from deeplearning4j_tpu.faults.iterators import RetryingIterator
from deeplearning4j_tpu.faults.recovery import FaultTolerantFit, RetryPolicy
from deeplearning4j_tpu.faults.sentinels import (LossSpikeWatcher,
                                                 PlateauWatcher)
from deeplearning4j_tpu.monitor.tensorstats import LayerHealthWatcher

__all__ = ["ChaosMonkey", "DataPipelineError", "FaultBudgetExhaustedError",
           "FaultError", "FaultTolerantFit", "FileBarrier", "HostKiller",
           "HostLossInjector", "LayerHealthWatcher", "LossSpikeWatcher",
           "PlateauWatcher", "RetryPolicy", "RetryingIterator",
           "ShardCorruptError", "ShardCountMismatchError",
           "SilentCorruptionError", "TornShard", "TopologyChangedError",
           "TrainingDivergedError", "TrainingStalledError",
           "TransientDeviceError", "retryable_errors"]
