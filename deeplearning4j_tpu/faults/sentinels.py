"""Divergence sentinels: device-side rail + host-side watchers.

Two detection layers with very different costs:

- **Device sentinel** (``TrainingConfig.sentinel = True``): the compiled
  train-step body additionally emits one boolean — ``isfinite(loss)``
  AND-ed with ``all(isfinite(g))`` over EVERY gradient leaf. Full
  coverage matters because a where-based op (relu, dropout masks) can
  launder NaN activations into a finite loss while a single weight's
  gradient silently poisons that parameter; the boolean reduce fuses
  into the gradient producers and is noise-level next to the step's
  matmuls. In the
  fused-window tier the flag folds into the ``lax.scan`` carry as the
  absolute iteration of the FIRST bad step (``-1`` = clean window), so a
  K-step window pays ONE extra scalar output and the host only looks at
  it at the flush boundaries it already syncs on — no per-step host
  round-trip. The sentinel never touches the parameter math: with no
  fault present, sentinel-on training is bit-identical to sentinel-off
  (tested). Detection raises :class:`~deeplearning4j_tpu.faults.errors.
  TrainingDivergedError` with the absolute step, epoch and in-epoch
  batch index.

- **Host watchers** (this module): listeners that inspect the loss
  scalars fit() already fetches — catching *finite-but-wrong* regimes
  the device flag cannot see (a 100x loss spike, a dead plateau).
  They cost nothing extra: they ride the existing burst flushes.

Reference parity: NanScoreWatcher (org.deeplearning4j.optimize.listeners)
checked ``Double.isNaN(score)`` per iteration on the host; here the
finite check happens inside the XLA program and the host-side family
grows spike/plateau detection with structured provenance.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.autodiff.training import Listener
from deeplearning4j_tpu.faults.errors import TrainingDivergedError


class LossSpikeWatcher(Listener):
    """Raise :class:`TrainingDivergedError` when the loss jumps more
    than ``spike_factor``x above its exponential moving average (or goes
    non-finite). ``warmup`` iterations are observed before spikes fire,
    so the noisy first steps cannot trip it.

    ``frequency`` is the scalar-delivery cadence the watcher asks of
    the fit loop (the flush interval is the MIN across listeners). The
    default of 10 rides the standard burst flushes — detection lags a
    spike by at most one burst, which a rollback driver absorbs for
    free. Set ``frequency=1`` only when the extra per-step device
    round-trip on the per-step tier is acceptable.
    """

    def __init__(self, spike_factor: float = 10.0, warmup: int = 20,
                 ema_decay: float = 0.9, frequency: int = 10):
        if spike_factor <= 1.0:
            raise ValueError("spike_factor must be > 1")
        self.spike_factor = float(spike_factor)
        self.warmup = int(warmup)
        self.ema_decay = float(ema_decay)
        self.frequency = max(1, int(frequency))
        self._ema: Optional[float] = None
        self._seen = 0

    def reset(self) -> None:
        """Forget the EMA/warmup state. FaultTolerantFit calls this on
        every rollback: replayed iterations must be judged fresh, not
        against statistics from the discarded (pre-fault) timeline."""
        self._ema = None
        self._seen = 0

    def iterations_done(self, sd, epoch: int, iterations: Sequence[int],
                        losses: Sequence[float]):
        for it, loss in zip(iterations, losses):
            loss = float(loss)
            if not np.isfinite(loss):
                raise TrainingDivergedError(
                    f"non-finite loss {loss} at iteration {it} "
                    f"(epoch {epoch})", step=int(it), epoch=int(epoch),
                    cause="non_finite_loss", value=loss)
            if self._ema is not None and self._seen >= self.warmup and \
                    loss > self.spike_factor * max(self._ema, 1e-12):
                raise TrainingDivergedError(
                    f"loss spike: {loss:.6g} > {self.spike_factor}x EMA "
                    f"{self._ema:.6g} at iteration {it} (epoch {epoch})",
                    step=int(it), epoch=int(epoch), cause="loss_spike",
                    value=loss)
            self._ema = loss if self._ema is None else \
                self.ema_decay * self._ema + (1 - self.ema_decay) * loss
            self._seen += 1


class PlateauWatcher(Listener):
    """Raise :class:`TrainingDivergedError` (cause ``"plateau"``) when
    the epoch mean loss has not improved by ``min_delta`` for
    ``patience`` consecutive epochs — a stalled run on a preemptible pod
    is budget burning that a supervisor should see as a fault, not as
    progress. Opt-in (only attach it to runs that must keep moving)."""

    #: epoch-only listener: never force mid-epoch burst flushes (the
    #: fit loop flushes at the MIN frequency across listeners — the
    #: same huge-frequency idiom as checkpoint/listener.py's
    #: epoch-cadence branch)
    frequency = 1_000_000_000

    def __init__(self, patience: int = 5, min_delta: float = 0.0):
        self.patience = int(patience)
        self.min_delta = float(min_delta)
        self.best = float("inf")
        self._stale = 0

    def reset(self) -> None:
        """Forget best/staleness. FaultTolerantFit calls this on every
        rollback: epochs replayed from an earlier snapshot cannot beat
        the discarded timeline's best, and must not count as a
        plateau."""
        self.best = float("inf")
        self._stale = 0

    def on_epoch_end(self, sd, epoch: int, mean_loss: float):
        if mean_loss is None:
            return
        if mean_loss < self.best - self.min_delta:
            self.best = float(mean_loss)
            self._stale = 0
            return
        self._stale += 1
        if self._stale >= self.patience:
            raise TrainingDivergedError(
                f"loss plateaued for {self._stale} epochs (best "
                f"{self.best:.6g}, epoch {epoch} mean {mean_loss:.6g})",
                epoch=int(epoch), cause="plateau", value=float(mean_loss))


def check_ok_flags(oks, iterations, epoch: int,
                   epoch_start_iter: int) -> None:
    """Host-side verdict check shared by the fit tiers: ``oks`` is a
    fetched bool array of per-step sentinel flags aligned with
    ``iterations``; the first False raises with that step's
    provenance."""
    if oks.all():
        return
    iterations = list(iterations)
    raise_diverged(int(iterations[int(np.argmin(oks))]), epoch,
                   epoch_start_iter)


def check_bad_steps(bads, epoch: int, epoch_start_iter: int) -> None:
    """Windowed-tier variant: ``bads`` is a fetched int array of
    per-window first-bad-step markers (-1 = clean window); the earliest
    marked step raises."""
    hit = bads[bads >= 0]
    if hit.size:
        raise_diverged(int(hit.min()), epoch, epoch_start_iter)


def raise_diverged(bad_step: int, epoch: int, epoch_start_iter: int,
                   loss: Optional[float] = None) -> None:
    """Shared raise site for the device sentinel (called by the fit
    tiers when a fetched sentinel flag names a bad step)."""
    raise TrainingDivergedError(
        f"device sentinel: non-finite loss/gradient at iteration "
        f"{bad_step} (epoch {epoch}, batch {bad_step - epoch_start_iter} "
        f"of the epoch); roll back to the last committed checkpoint or "
        f"localize the producing op with sd.exec_debug()",
        step=int(bad_step), epoch=int(epoch),
        batch_index=int(bad_step - epoch_start_iter),
        cause="device_sentinel",
        value=None if loss is None else float(loss))
